"""Model containers: ``Sequential`` and graph ``Model`` + KerasNet facade.

Reference capability: api/keras/models/Topology.scala — ``KerasNet``
(compile:136 / fit:344 / evaluate:497 / predict), ``Model``:603,
``Sequential``:826.  Training itself lives in
``analytics_zoo_tpu.train.Estimator`` (one jitted SPMD step); KerasNet
methods are thin façades over it, exactly inverting the reference where the
optimizer was buried inside Topology.scala.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn import autograd
from analytics_zoo_tpu.nn.autograd import Variable, evaluate, topo_sort
from analytics_zoo_tpu.nn.module import Layer, split_rng


def _carry_weights(est):
    """(params, state) worth carrying from an estimator: live params if
    built, else its still-pending initial weights; None otherwise."""
    if est is None:
        return None
    if est.params is not None:
        return (jax.device_get(est.params), jax.device_get(est.state or {}))
    pending = getattr(est, "_initial_weights", None)
    return pending


class KerasNet(Layer):
    """Shared compile/fit/evaluate/predict facade for Sequential and Model."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._estimator = None  # created by compile()

    # -- training facade (delegates to train.Estimator) -------------------
    def compile(self, optimizer, loss, metrics=None, sharding="dp",
                aux_loss_weight: float = 0.01, grad_accum_steps: int = 1):
        """Configure training (reference Topology.scala:136-204).

        ``optimizer``/``loss``/``metrics`` accept strings (Keras-style
        lowering, reference KerasUtils.scala:165-167) or objects.
        ``sharding``: "dp" (replicated params) | "tp" (model-axis splits)
        | "ep" (expert-axis MoE splits) | a parallel.ShardingStrategy.
        ``aux_loss_weight`` scales any layer-emitted auxiliary losses
        (SparseMoE load balancing) added to the objective.
        """
        from analytics_zoo_tpu.train.estimator import Estimator

        prev = self._estimator
        self._estimator = Estimator(self, optimizer=optimizer, loss=loss,
                                    metrics=metrics, sharding=sharding,
                                    aux_loss_weight=aux_loss_weight,
                                    grad_accum_steps=grad_accum_steps)
        # re-compiling must NOT lose weights: carry the previous
        # estimator's live params (or its still-pending initial weights —
        # e.g. a sub-graph seeded by nn/net.py new_graph) forward;
        # weights staged via set_initial_weights before the first compile
        # take priority
        carried = _carry_weights(prev)
        if getattr(self, "_pending_init", None) is not None:
            carried = self._pending_init
            self._pending_init = None
        if carried is not None:
            self._estimator.set_initial_weights(*carried)
        # apply settings made before compile()
        if getattr(self, "_tb_dir", None):
            self._estimator.set_tensorboard(self._tb_dir)
        if getattr(self, "_ckpt_dir", None):
            self._estimator.set_checkpoint(self._ckpt_dir)
        return self

    def set_initial_weights(self, params, state=None):
        """Seed weights by layer name (e.g. layers shared with a trained
        model — a new head over a cut backbone).  Works before or after
        compile(); unknown layer names are ignored, uncovered layers warn
        at build (estimator._ensure_built)."""
        if self._estimator is not None:
            self._estimator.set_initial_weights(params, state or {})
        else:
            self._pending_init = (params, state or {})
        return self

    @property
    def estimator(self):
        if self._estimator is None:
            raise RuntimeError("call compile(optimizer, loss) before fit/evaluate")
        return self._estimator

    def fit(self, x, y=None, batch_size: int = 32,
            nb_epoch: Optional[int] = None,
            validation_data=None, epochs: Optional[int] = None, **kw):
        """``nb_epoch`` mirrors the reference (Topology.scala:344); ``epochs``
        is accepted as the modern-Keras alias for the same knob."""
        if nb_epoch is not None and epochs is not None:
            raise ValueError(
                "pass either nb_epoch= or epochs= (aliases), not both")
        n = nb_epoch if nb_epoch is not None else (
            epochs if epochs is not None else 1)
        return self.estimator.fit(x, y, batch_size=batch_size,
                                  epochs=n,
                                  validation_data=validation_data, **kw)

    def evaluate(self, x, y=None, batch_size: int = 32):
        return self.estimator.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32, distributed: bool = True):
        return self.estimator.predict(x, batch_size=batch_size)

    def predict_classes(self, x, batch_size: int = 32,
                        zero_based_label: bool = True):
        """Reference Predictable.predictClasses convenience."""
        return self.estimator.predict_classes(
            x, batch_size=batch_size, zero_based_label=zero_based_label)

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo"):
        """Reference Topology.scala:205-212."""
        from analytics_zoo_tpu.train.estimator import Estimator
        self._tb_dir = f"{log_dir.rstrip('/')}/{app_name}"
        if self._estimator is not None:
            self._estimator.set_tensorboard(self._tb_dir)
        return self

    def set_checkpoint(self, path: str, over_write: bool = True):
        """Reference Topology.scala:246-256."""
        self._ckpt_dir = path
        if self._estimator is not None:
            self._estimator.set_checkpoint(path, over_write=over_write)
        return self

    # -- persistence ------------------------------------------------------
    def save_weights(self, path: str, params, state=None):
        from analytics_zoo_tpu.train import checkpoint as ckpt
        ckpt.save_pytree(path, {"params": params, "state": state or {}})

    def load_weights(self, path: str):
        from analytics_zoo_tpu.train import checkpoint as ckpt
        tree = ckpt.load_pytree(path)
        return tree["params"], tree.get("state", {})

    # -- introspection ----------------------------------------------------
    def summary(self, params=None) -> str:
        lines = [f"Model: {self.name}", "-" * 64]
        total = 0
        for layer in self.layers:
            shape = getattr(layer, "built_shapes", None)
            n = layer.param_count(params.get(layer.name, {})) if params else 0
            total += n
            lines.append(f"{layer.name:<32}{str(shape):<24}{n:>8}")
        lines.append("-" * 64)
        if params is not None:
            total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        lines.append(f"Total params: {total}")
        out = "\n".join(lines)
        print(out)
        return out

    @property
    def layers(self) -> List[Layer]:
        raise NotImplementedError

    def regularization_loss(self, params):
        """Sum of every layer's weight-decay penalty (w/b_regularizer
        kwargs) — added to the training objective by the Estimator.
        Layers without regularizers contribute a literal 0.0, which
        constant-folds away under jit."""
        total = 0.0
        for layer in self.layers:
            fn = getattr(layer, "regularization_loss", None)
            if fn is not None:
                total = total + fn(params.get(layer.name, {}))
        return total


class Sequential(KerasNet):
    """Linear stack of layers (reference Topology.scala:826)."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None, **kw):
        super().__init__(**kw)
        self._layers: List[Layer] = []
        for l in layers or []:
            self.add(l)

    def add(self, layer: Layer) -> "Sequential":
        self._layers.append(layer)
        return self

    @property
    def layers(self) -> List[Layer]:
        return self._layers

    # -- functional protocol ----------------------------------------------
    def build(self, rng, *input_shapes):
        if len(input_shapes) == 1:
            shape = input_shapes[0]
        elif self._layers and self._layers[0].input_shape is not None:
            shape = (2,) + self._layers[0].input_shape
        else:
            raise ValueError("Sequential.build needs an input shape")
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        rngs = split_rng(rng, len(self._layers))
        shapes: Union[Tuple, List[Tuple]] = shape
        for layer, r in zip(self._layers, rngs):
            cur = shapes if isinstance(shapes, tuple) else tuple(shapes)
            p, s = layer.init(r, cur)
            params[layer.name] = p
            state[layer.name] = s
            shapes = layer.output_shape(p, s, cur)
        self._output_shape = shapes
        return params, state

    def call(self, params, state, x, *, training: bool = False, rng=None):
        new_state = dict(state)
        rngs = split_rng(rng, len(self._layers))
        for layer, r in zip(self._layers, rngs):
            x, ns = layer.call(params.get(layer.name, {}),
                               state.get(layer.name, {}), x,
                               training=training, rng=r)
            new_state[layer.name] = ns
        return x, new_state


class Model(KerasNet):
    """Graph model over autograd Variables (reference Topology.scala:603).

    >>> a = Input(shape=(8,)); b = Input(shape=(8,))
    >>> h = Dense(16, activation="relu")(merge([a, b], mode="concat"))
    >>> out = Dense(1, activation="sigmoid")(h)
    >>> model = Model([a, b], out)
    """

    def __init__(self, inputs, outputs, **kw):
        super().__init__(**kw)
        self.inputs: List[Variable] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.single_output = not isinstance(outputs, (list, tuple))
        self.outputs: List[Variable] = (
            [outputs] if self.single_output else list(outputs))
        self.order = topo_sort(self.outputs)
        input_ids = {v.id for v in self.inputs}
        for v in self.order:
            if v.kind == "input" and v.id not in input_ids:
                raise ValueError(f"graph uses input {v.name} not in inputs=")

    @property
    def layers(self) -> List[Layer]:
        seen = {}
        for v in self.order:
            if v.kind in ("layer", "param") and v.layer.name not in seen:
                seen[v.layer.name] = v.layer
        return list(seen.values())

    def input_ancestors(self, layer_name: str) -> Tuple[str, ...]:
        """Names of the graph inputs whose values (transitively) feed
        any application of the layer called ``layer_name``, in input
        order.  This is the input-field-to-table manifest the serving
        hot-row caches use to record each sharded table's OWN id
        streams — not every integer input of the model (deploy/
        inference.py ``record_hot_ids``)."""
        targets = [v for v in self.order
                   if v.kind in ("layer", "param")
                   and v.layer.name == layer_name]
        found: set = set()
        stack = [p for t in targets for p in t.parents]
        seen_ids = set()
        while stack:
            v = stack.pop()
            if v.id in seen_ids:
                continue
            seen_ids.add(v.id)
            if v.kind == "input":
                found.add(v.id)
            stack.extend(v.parents)
        return tuple(v.name for v in self.inputs if v.id in found)

    # -- functional protocol ----------------------------------------------
    def build(self, rng, *input_shapes):
        if not input_shapes:
            input_shapes = tuple(
                (2,) + tuple(d for d in v.shape[1:]) for v in self.inputs)
        if len(input_shapes) != len(self.inputs):
            raise ValueError(
                f"expected {len(self.inputs)} input shapes, got {len(input_shapes)}")
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        # Abstract values per node, threaded through the DAG as we build.
        absval: Dict[int, Any] = {
            v.id: jax.ShapeDtypeStruct(tuple(s), v.dtype)
            for v, s in zip(self.inputs, input_shapes)
        }
        layer_nodes = [v for v in self.order if v.kind in ("layer", "param")]
        rngs = split_rng(rng, len(layer_nodes))
        rng_map = {v.id: r for v, r in zip(layer_nodes, rngs)}
        for v in self.order:
            if v.id in absval:
                continue
            parent_abs = [absval[p.id] for p in v.parents]
            if v.kind in ("layer", "param"):
                if v.layer.name not in params:  # shared layers build once
                    p, s = v.layer.init(rng_map[v.id],
                                        *[tuple(a.shape) for a in parent_abs])
                    params[v.layer.name] = p
                    state[v.layer.name] = s

                def absfn(lp, ls, *xs, _l=v.layer):
                    out, _ = _l.call(lp, ls, *xs, training=False, rng=None)
                    return out

                absval[v.id] = jax.eval_shape(
                    absfn, params[v.layer.name], state[v.layer.name], *parent_abs)
            else:
                absval[v.id] = jax.eval_shape(v.fn, *parent_abs)
        self._output_shape = tuple(
            absval[o.id].shape for o in self.outputs)
        if self.single_output:
            self._output_shape = self._output_shape[0]
        return params, state

    def call(self, params, state, *inputs, training: bool = False, rng=None):
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        if len(inputs) != len(self.inputs):
            raise ValueError(
                f"expected {len(self.inputs)} inputs, got {len(inputs)}")
        env = {v.id: x for v, x in zip(self.inputs, inputs)}
        env, new_state = evaluate(self.order, env, params, state,
                                  training=training, rng=rng)
        outs = [env[o.id] for o in self.outputs]
        return (outs[0] if self.single_output else outs), new_state
