"""Weight regularizers — L1/L2 penalties for layer ``w_regularizer`` /
``b_regularizer`` kwargs (reference: BigDL L1Regularizer/L2Regularizer
wrapped by every Keras layer's ``wRegularizer`` params).

A regularizer is just ``fn(weights) -> scalar``; these classes are the
named, serializable spellings.  The penalty is summed over layers by
``KerasNet.regularization_loss`` and added to the training objective
inside the jitted step (on the f32 master params under mixed precision).
"""

from __future__ import annotations

import jax.numpy as jnp


class Regularizer:
    def __call__(self, w):
        raise NotImplementedError


class L1(Regularizer):
    def __init__(self, l1: float = 0.01):
        self.l1 = float(l1)

    def __call__(self, w):
        return self.l1 * jnp.sum(jnp.abs(w))

    def __repr__(self):
        return f"L1(l1={self.l1})"


class L2(Regularizer):
    def __init__(self, l2: float = 0.01):
        self.l2 = float(l2)

    def __call__(self, w):
        return self.l2 * jnp.sum(jnp.square(w))

    def __repr__(self):
        return f"L2(l2={self.l2})"


class L1L2(Regularizer):
    def __init__(self, l1: float = 0.01, l2: float = 0.01):
        self.l1 = float(l1)
        self.l2 = float(l2)

    def __call__(self, w):
        return (self.l1 * jnp.sum(jnp.abs(w))
                + self.l2 * jnp.sum(jnp.square(w)))

    def __repr__(self):
        return f"L1L2(l1={self.l1}, l2={self.l2})"


def get(spec):
    """Lower a spec to a regularizer: None | callable | "l1" | "l2" |
    "l1l2" (Keras-style string lowering)."""
    if spec is None or callable(spec):
        return spec
    name = str(spec).lower()
    if name == "l1":
        return L1()
    if name == "l2":
        return L2()
    if name in ("l1l2", "l1_l2"):
        return L1L2()
    raise ValueError(f"unknown regularizer {spec!r}; known: l1, l2, l1l2")
