from analytics_zoo_tpu.nn.module import Layer, StatelessLayer, reset_name_scope  # noqa: F401
from analytics_zoo_tpu.nn.topology import KerasNet, Model, Sequential  # noqa: F401
from analytics_zoo_tpu.nn.autograd import Input, Parameter, Variable  # noqa: F401
from analytics_zoo_tpu.nn import (  # noqa: F401
    activations,
    autograd,
    initializers,
    metrics,
    objectives,
    regularizers,
)
from analytics_zoo_tpu.nn.layers import *  # noqa: F401,F403
