"""Keras-2 style layer surface (arg names/semantics of Keras 2.x).

Reference capability: ``pipeline/api/keras2/layers/`` — ~20 layers that
re-expose the v1 implementations under Keras-2 argument names
(units/filters/kernel_size/strides/padding instead of
output_dim/nb_filter/nb_row/subsample/border_mode).  Here each class is a
thin constructor adapter over the single native implementation — no
duplicated math, identical params/pytrees, so weights move freely between
the two surfaces.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

# note: `merge` the module is shadowed by the function re-exported from
# nn.layers.__init__, so merge classes are imported directly
from analytics_zoo_tpu.nn.layers.merge import (Add, Average, Concatenate,
                                               Maximum, Minimum, Multiply)
from analytics_zoo_tpu.nn.layers import advanced_activations as _aa
from analytics_zoo_tpu.nn.layers import convolutional as _cv
from analytics_zoo_tpu.nn.layers import core as _core
from analytics_zoo_tpu.nn.layers import embedding as _emb
from analytics_zoo_tpu.nn.layers import normalization as _nm
from analytics_zoo_tpu.nn.layers import pooling as _pl
from analytics_zoo_tpu.nn.layers import recurrent as _rc


from analytics_zoo_tpu.nn.layers.convolutional import _tuple


def _pair(v):
    return _tuple(v, 2)


class Dense(_core.Dense):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None, **kw):
        super().__init__(units, activation=activation, use_bias=use_bias,
                         init=kernel_initializer,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, **kw)


class Conv1D(_cv.Convolution1D):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "valid", activation=None,
                 dilation_rate: int = 1, use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None, **kw):
        super().__init__(filters, kernel_size, subsample=strides,
                         border_mode=padding, activation=activation,
                         dilation=dilation_rate, bias=use_bias,
                         init=kernel_initializer,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, **kw)


class Conv2D(_cv.Convolution2D):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None, dilation_rate=1,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None, **kw):
        kh, kw_ = _pair(kernel_size)
        super().__init__(filters, kh, kw_, subsample=_pair(strides),
                         border_mode=padding, activation=activation,
                         dilation=_pair(dilation_rate), bias=use_bias,
                         init=kernel_initializer,
                         w_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer, **kw)


class Conv3D(_cv.Convolution3D):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, **kw):
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        st = (strides,) * 3 if isinstance(strides, int) else tuple(strides)
        super().__init__(filters, *ks, subsample=st, border_mode=padding,
                         activation=activation, bias=use_bias, **kw)


class Conv2DTranspose(_cv.Deconvolution2D):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, **kw):
        kh, kw_ = _pair(kernel_size)
        super().__init__(filters, kh, kw_, subsample=_pair(strides),
                         border_mode=padding, activation=activation,
                         bias=use_bias, **kw)


class SeparableConv2D(_cv.SeparableConvolution2D):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", depth_multiplier: int = 1,
                 activation=None, use_bias: bool = True, **kw):
        kh, kw_ = _pair(kernel_size)
        super().__init__(filters, kh, kw_, subsample=_pair(strides),
                         border_mode=padding,
                         depth_multiplier=depth_multiplier,
                         activation=activation, bias=use_bias, **kw)


class MaxPooling1D(_pl.MaxPooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", **kw):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, **kw)


class MaxPooling2D(_pl.MaxPooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", **kw):
        super().__init__(_pair(pool_size),
                         strides=None if strides is None else _pair(strides),
                         border_mode=padding, **kw)


class AveragePooling1D(_pl.AveragePooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", **kw):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, **kw)


class AveragePooling2D(_pl.AveragePooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", **kw):
        super().__init__(_pair(pool_size),
                         strides=None if strides is None else _pair(strides),
                         border_mode=padding, **kw)


class Embedding(_emb.Embedding):
    def __init__(self, input_dim: int, output_dim: int,
                 embeddings_initializer="uniform", **kw):
        super().__init__(input_dim, output_dim,
                         init=embeddings_initializer, **kw)


class BatchNormalization(_nm.BatchNormalization):
    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 center: bool = True, scale: bool = True, **kw):
        super().__init__(momentum=momentum, epsilon=epsilon, center=center,
                         scale=scale, **kw)


class LSTM(_rc.LSTM):
    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="hard_sigmoid",
                 return_sequences: bool = False,
                 go_backwards: bool = False, **kw):
        super().__init__(units, activation=activation,
                         inner_activation=recurrent_activation,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards, **kw)


class GRU(_rc.GRU):
    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="hard_sigmoid",
                 return_sequences: bool = False,
                 go_backwards: bool = False, **kw):
        super().__init__(units, activation=activation,
                         inner_activation=recurrent_activation,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards, **kw)


class SimpleRNN(_rc.SimpleRNN):
    def __init__(self, units: int, activation="tanh",
                 return_sequences: bool = False, **kw):
        super().__init__(units, activation=activation,
                         return_sequences=return_sequences, **kw)


class LeakyReLU(_aa.LeakyReLU):
    def __init__(self, alpha: float = 0.3, **kw):
        super().__init__(alpha, **kw)


# identical-signature layers re-exported for a complete keras2 namespace
Activation = _core.Activation
Dropout = _core.Dropout
Flatten = _core.Flatten
Reshape = _core.Reshape
Permute = _core.Permute
RepeatVector = _core.RepeatVector
GlobalMaxPooling1D = _pl.GlobalMaxPooling1D
GlobalMaxPooling2D = _pl.GlobalMaxPooling2D
GlobalAveragePooling1D = _pl.GlobalAveragePooling1D
GlobalAveragePooling2D = _pl.GlobalAveragePooling2D
# (Add/Maximum/Minimum/Average/Multiply/Concatenate imported above)

__all__ = [
    "Dense", "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
    "SeparableConv2D", "MaxPooling1D", "MaxPooling2D", "AveragePooling1D",
    "AveragePooling2D", "Embedding", "BatchNormalization", "LSTM", "GRU",
    "SimpleRNN", "LeakyReLU", "Activation", "Dropout", "Flatten",
    "Reshape", "Permute", "RepeatVector", "GlobalMaxPooling1D",
    "GlobalMaxPooling2D", "GlobalAveragePooling1D",
    "GlobalAveragePooling2D", "Add", "Maximum", "Minimum", "Average",
    "Multiply", "Concatenate",
]
