"""Net — model import/export + transfer-learning graph surgery.

Reference capability:
- ``Net.load/loadTorch/loadTF/loadCaffe`` loaders (api/Net.scala:136-189)
- ``NetSaver`` exporters to TF / keras formats (api/Net.scala:277-445)
- ``GraphNet``/``NetUtils`` surgery: freeze/unfreeze layers, ``newGraph``
  from intermediate node names (pipeline/api/net/NetUtils.scala).

TPU-native redesign: every loader lands in the SAME Layer-protocol world
(pure fn + param pytree), so an imported model trains under the SPMD
Estimator exactly like a native one.  Freezing is realised by zeroing
optimizer updates for the frozen top-level param subtrees inside the
jitted step — no graph mutation, no second code path.

Legacy JVM binary formats (BigDL protobuf, Caffe) are intentionally not
parsed: their live content reaches this framework via the ONNX / TF /
torch ingestion paths instead.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set, Union

__all__ = ["Net", "GraphNet"]


class Net:
    """Unified loader facade (reference api/Net.scala:136-189)."""

    @staticmethod
    def load(path: str):
        """Load a natively saved ZooModel directory (models/common.py)."""
        from analytics_zoo_tpu.models.common import ZooModel

        return ZooModel.load_model(path)

    @staticmethod
    def load_torch(module_or_path):
        """torch.nn.Sequential (or a TorchScript file path) -> natively
        trainable model (reference loadTorch, Net.scala:161)."""
        import torch

        if isinstance(module_or_path, str):
            module_or_path = torch.jit.load(module_or_path)
        from analytics_zoo_tpu.tfpark.model import TorchModel

        return TorchModel(module_or_path)

    @staticmethod
    def load_tf(path_or_model, **kw):
        """TF SavedModel path or tf.keras model (reference loadTF,
        Net.scala:176)."""
        from analytics_zoo_tpu.tfpark.model import KerasModel, TFNet

        if not isinstance(path_or_model, str):
            return KerasModel(path_or_model, **kw)
        return TFNet(path_or_model, **kw)

    @staticmethod
    def load_onnx(path: str):
        """.onnx file -> trainable KerasNet (onnx/loader.py)."""
        from analytics_zoo_tpu.onnx import load_onnx, to_model

        return to_model(load_onnx(path))

    @staticmethod
    def load_bigdl(path: str):
        raise NotImplementedError(
            "BigDL protobuf checkpoints are a JVM-era format; export the "
            "model to ONNX or TF SavedModel and use Net.load_onnx / "
            "Net.load_tf")

    @staticmethod
    def load_caffe(def_path: str, model_path: str):
        """prototxt + caffemodel → trainable program (reference
        Net.loadCaffe, api/Net.scala:169-189; importer
        caffe/loader.py — the conv-net vocabulary; exotic layers raise
        with caffe2onnx guidance)."""
        from analytics_zoo_tpu.caffe import load_caffe as _load

        return _load(def_path, model_path)

    # -- exporters (reference NetSaver, Net.scala:277-445) -----------------
    @staticmethod
    def export_tf_saved_model(model, params, path: str,
                              input_shapes: Sequence[Sequence[int]],
                              state=None):
        """Native model -> TF SavedModel via jax2tf (serving handoff)."""
        import tensorflow as tf
        from jax.experimental import jax2tf

        def fwd(*xs):
            out, _ = model.call(params, state or {}, *xs, training=False,
                                rng=None)
            return out

        tf_fn = tf.function(
            jax2tf.convert(fwd, with_gradient=False),
            input_signature=[
                tf.TensorSpec([None] + list(s[1:]), tf.float32)
                for s in input_shapes],
            autograph=False)
        module = tf.Module()
        module.__call__ = tf_fn
        tf.saved_model.save(module, path)
        return path


class GraphNet:
    """Transfer-learning surgery over a graph ``Model``
    (reference GraphNet in NetUtils.scala: freeze/unfreeze/newGraph).

    Wraps a native ``Model`` (nn/topology.py); mutating operations mark
    layers frozen (their params stop receiving optimizer updates — the
    Estimator zeroes their update subtrees inside the jitted step) or cut
    a new sub-graph ending at named intermediate layers.
    """

    def __init__(self, model):
        self.model = model

    # -- freezing ---------------------------------------------------------
    def freeze(self, names: Optional[Sequence[str]] = None) -> "GraphNet":
        """Freeze the named layers (all layers when None) — reference
        GraphNet.freeze."""
        layer_names = {l.name for l in self.model.layers}
        targets = set(names) if names is not None else layer_names
        unknown = targets - layer_names
        if unknown:
            raise ValueError(f"unknown layers {sorted(unknown)}; "
                             f"known: {sorted(layer_names)}")
        frozen: Set[str] = set(getattr(self.model, "_frozen", set()))
        frozen |= targets
        self.model._frozen = frozen
        return self

    def unfreeze(self, names: Optional[Sequence[str]] = None) -> "GraphNet":
        frozen: Set[str] = set(getattr(self.model, "_frozen", set()))
        frozen -= set(names) if names is not None else set(frozen)
        self.model._frozen = frozen
        return self

    def freeze_up_to(self, name: str) -> "GraphNet":
        """Freeze every layer up to and including ``name`` in topological
        order (the classic fine-tune-the-head recipe)."""
        layers = self.model.layers
        idx = [i for i, l in enumerate(layers) if l.name == name]
        if not idx:
            raise ValueError(f"unknown layer {name!r}")
        return self.freeze([l.name for l in layers[:idx[-1] + 1]])

    @property
    def frozen(self) -> Set[str]:
        return set(getattr(self.model, "_frozen", set()))

    # -- sub-graphs -------------------------------------------------------
    def new_graph(self, output_names: Union[str, Sequence[str]]):
        """Cut a sub-model ending at the named layers' outputs
        (reference newGraph, NetUtils.scala) — e.g. chop the classifier
        off an imported backbone and reuse the feature extractor."""
        from analytics_zoo_tpu.nn.topology import Model

        single = isinstance(output_names, str)
        names = [output_names] if single else list(output_names)
        by_name = {}
        for v in self.model.order:
            if v.kind in ("layer", "param"):
                by_name[v.layer.name] = v     # last node of a shared layer
        missing = [n for n in names if n not in by_name]
        if missing:
            raise ValueError(f"unknown layers {missing}; known: "
                             f"{sorted(by_name)}")
        outs = [by_name[n] for n in names]
        sub = Model(self.model.inputs, outs[0] if single else outs)
        g = GraphNet(sub)
        # carry trained weights into the sub-graph (reference newGraph
        # reuses the SAME weighted graph): compile the sub lazily for
        # inference and seed it with the source model's current params
        from analytics_zoo_tpu.nn.topology import _carry_weights

        carried = _carry_weights(getattr(self.model, "_estimator", None))
        if carried is not None:
            # sgd is stateless: no optimizer moments allocated for what is
            # typically an inference-only feature extractor (re-compiling
            # for fine-tuning keeps these weights — topology.compile)
            sub.compile(optimizer="sgd", loss="mse")
            sub.estimator.set_initial_weights(*carried)
        return g

    # -- passthrough ------------------------------------------------------
    def compile(self, *a, **kw):
        self.model.compile(*a, **kw)
        return self

    def __getattr__(self, item):
        return getattr(self.model, item)
