"""Layer protocol — the base of the Keras-style API.

Reference capability: BigDL ``KerasLayer`` / ``AbstractModule`` with
``forward``/``backward`` (SURVEY.md L3).  TPU-native design: a layer is a
pair of *pure functions*

    build(rng, *input_shapes)                      -> (params, state)
    call(params, state, *inputs, training, rng)    -> (output, new_state)

``params`` are differentiated; ``state`` carries non-differentiated buffers
(BatchNorm moving stats).  Backward passes come from ``jax.grad`` — there is
no hand-written backward anywhere.  Layers compose via containers
(``Sequential``/``Model``) or symbolically via the autograd ``Variable`` DSL.

Shapes: layer ``build`` receives *full* shapes including the batch dim.
User-facing ``input_shape=`` kwargs follow Keras convention (no batch dim).
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Any   # pytree
State = Any    # pytree

# Auto-naming counters are THREAD-LOCAL: concurrent model builds (e.g.
# parallel AutoML trials in a thread pool) each get an isolated scope, so
# two threads never race a shared counter into colliding layer names.
_NAME_SCOPE = threading.local()


def _counters() -> Dict[str, int]:
    if not hasattr(_NAME_SCOPE, "counters"):
        _NAME_SCOPE.counters = collections.defaultdict(int)
    return _NAME_SCOPE.counters


def _auto_name(cls_name: str) -> str:
    c = _counters()
    c[cls_name] += 1
    return f"{cls_name.lower()}_{c[cls_name]}"


def reset_name_scope() -> None:
    """Reset the calling thread's auto-naming counters (test isolation)."""
    _counters().clear()


class Layer:
    """Base class for all layers and containers."""

    def __init__(self, name: Optional[str] = None,
                 input_shape: Optional[Sequence[int]] = None):
        self.name = name or _auto_name(type(self).__name__)
        # Keras-style input_shape excludes the batch dim.
        self.input_shape = tuple(input_shape) if input_shape is not None else None
        self.built_shapes: Optional[Tuple[Tuple[int, ...], ...]] = None

    # -- to be implemented by subclasses ---------------------------------
    def build(self, rng, *input_shapes) -> Tuple[Params, State]:
        """Allocate parameters/state for the given full input shapes."""
        return {}, {}

    def call(self, params: Params, state: State, *inputs,
             training: bool = False, rng=None) -> Tuple[Any, State]:
        raise NotImplementedError

    # -- generic machinery ------------------------------------------------
    def output_shape(self, params: Params, state: State,
                     *input_shapes, training: bool = False):
        """Infer the output shape abstractly (no FLOPs) via eval_shape."""
        args = [jax.ShapeDtypeStruct(s, jnp.float32) for s in input_shapes]
        rng = jax.ShapeDtypeStruct((2,), jnp.uint32)

        def fn(params, state, rng, *xs):
            out, _ = self.call(params, state, *xs, training=training, rng=rng)
            return out

        out = jax.eval_shape(fn, params, state, rng, *args)
        return out.shape

    def init(self, rng, *input_shapes) -> Tuple[Params, State]:
        """User-facing build; records shapes for summary printing."""
        self.built_shapes = tuple(tuple(s) for s in input_shapes)
        return self.build(rng, *input_shapes)

    def param_count(self, params: Params) -> int:
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))

    # -- symbolic application (autograd DSL) ------------------------------
    def __call__(self, *args):
        """Apply to ``Variable``s → new ``Variable`` (graph building)."""
        from analytics_zoo_tpu.nn.autograd import Variable, apply_layer

        if args and all(isinstance(a, Variable) for a in args):
            return apply_layer(self, args)
        raise TypeError(
            f"{type(self).__name__} called with {[type(a) for a in args]}; "
            "layers are applied to autograd Variables (use Model DSL) or via "
            "explicit .call(params, state, x)."
        )

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class StatelessLayer(Layer):
    """Convenience base for the ~90% of layers with no mutable state.

    Subclasses implement ``build_params(rng, *shapes) -> params`` and
    ``forward(params, *inputs, training, rng) -> out``.
    """

    def build_params(self, rng, *input_shapes) -> Params:
        return {}

    def forward(self, params, *inputs, training: bool = False, rng=None):
        raise NotImplementedError

    def build(self, rng, *input_shapes):
        return self.build_params(rng, *input_shapes), {}

    def call(self, params, state, *inputs, training: bool = False, rng=None):
        return self.forward(params, *inputs, training=training, rng=rng), state


def split_rng(rng, n: int):
    """Split an optional rng into n optional rngs."""
    if rng is None:
        return [None] * n
    return list(jax.random.split(rng, n))


def full_shape(input_shape: Sequence[int], batch: int = 1) -> Tuple[int, ...]:
    """Prepend a batch dim to a Keras-style (batch-less) shape."""
    return (batch,) + tuple(input_shape)
