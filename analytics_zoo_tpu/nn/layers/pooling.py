"""Pooling layers: max/average, local and global, 1D/2D/3D.

Reference capability: api/keras/layers/{MaxPooling1D,MaxPooling2D,
MaxPooling3D,AveragePooling*,GlobalMaxPooling*,GlobalAveragePooling*}.scala.

TPU-first: local pools are single ``lax.reduce_window`` calls (XLA lowers
these to fused vector-unit reductions); global pools are plain axis
reductions.  Channels-last interior, ``dim_ordering="th"`` handled at the
boundary as in convolutional.py.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import lax

from analytics_zoo_tpu.nn.layers.convolutional import (
    _from_channels_last, _to_channels_last, _tuple)
from analytics_zoo_tpu.nn.module import StatelessLayer

IntOrPair = Union[int, Sequence[int]]


class PoolND(StatelessLayer):
    spatial = 2
    mode = "max"  # or "avg"

    def __init__(self, pool_size, strides=None, border_mode: str = "valid",
                 dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.pool_size = _tuple(pool_size, self.spatial)
        self.strides = (_tuple(strides, self.spatial) if strides is not None
                        else self.pool_size)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode}")
        self.border_mode = border_mode.upper()
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        if self.mode == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  self.border_mode)
        else:
            summed = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                       self.border_mode)
            if self.border_mode == "VALID":
                y = summed / float(np.prod(self.pool_size))
            else:
                # SAME: divide by the actual window size at each position
                counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                           window, strides, self.border_mode)
                y = summed / counts
        return _from_channels_last(y, self.dim_ordering, self.spatial)


class MaxPooling1D(PoolND):
    spatial, mode = 1, "max"

    def __init__(self, pool_length: int = 2, stride=None, **kw):
        super().__init__((pool_length,),
                         (stride,) if stride is not None else None, **kw)


class MaxPooling2D(PoolND):
    spatial, mode = 2, "max"

    def __init__(self, pool_size: IntOrPair = (2, 2), strides=None, **kw):
        super().__init__(pool_size, strides, **kw)


class MaxPooling3D(PoolND):
    spatial, mode = 3, "max"

    def __init__(self, pool_size: IntOrPair = (2, 2, 2), strides=None, **kw):
        super().__init__(pool_size, strides, **kw)


class AveragePooling1D(PoolND):
    spatial, mode = 1, "avg"

    def __init__(self, pool_length: int = 2, stride=None, **kw):
        super().__init__((pool_length,),
                         (stride,) if stride is not None else None, **kw)


class AveragePooling2D(PoolND):
    spatial, mode = 2, "avg"

    def __init__(self, pool_size: IntOrPair = (2, 2), strides=None, **kw):
        super().__init__(pool_size, strides, **kw)


class AveragePooling3D(PoolND):
    spatial, mode = 3, "avg"

    def __init__(self, pool_size: IntOrPair = (2, 2, 2), strides=None, **kw):
        super().__init__(pool_size, strides, **kw)


class GlobalPoolND(StatelessLayer):
    spatial = 2
    mode = "max"

    def __init__(self, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        axes = tuple(range(1, 1 + self.spatial))
        return (jnp.max(x, axis=axes) if self.mode == "max"
                else jnp.mean(x, axis=axes))


class GlobalMaxPooling1D(GlobalPoolND):
    spatial, mode = 1, "max"


class GlobalMaxPooling2D(GlobalPoolND):
    spatial, mode = 2, "max"


class GlobalMaxPooling3D(GlobalPoolND):
    spatial, mode = 3, "max"


class GlobalAveragePooling1D(GlobalPoolND):
    spatial, mode = 1, "avg"


class GlobalAveragePooling2D(GlobalPoolND):
    spatial, mode = 2, "avg"


class GlobalAveragePooling3D(GlobalPoolND):
    spatial, mode = 3, "avg"
