"""Parametric / advanced activation layers and noise layers.

Reference capability: api/keras/layers/{LeakyReLU,ELU,PReLU,SReLU,
ThresholdedReLU,GaussianNoise,GaussianDropout,SpatialDropout1D/2D/3D}.scala.
All elementwise — XLA fuses them into neighbouring ops for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.nn.module import StatelessLayer


class LeakyReLU(StatelessLayer):
    def __init__(self, alpha: float = 0.3, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x >= 0, x, self.alpha * x)


class ELU(StatelessLayer):
    def __init__(self, alpha: float = 1.0, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x >= 0, x, self.alpha * (jnp.exp(x) - 1.0))


class ThresholdedReLU(StatelessLayer):
    def __init__(self, theta: float = 1.0, **kw):
        super().__init__(**kw)
        self.theta = theta

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x > self.theta, x, 0.0)


class PReLU(StatelessLayer):
    """ReLU with a learned per-channel negative slope
    (reference api/keras/layers/PReLU.scala)."""

    def build_params(self, rng, input_shape):
        return {"alpha": jnp.zeros(input_shape[1:], jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x >= 0, x, params["alpha"] * x)


class SReLU(StatelessLayer):
    """S-shaped ReLU with four learned per-element tensors
    (reference api/keras/layers/SReLU.scala; Jin et al. 2016):

        y = t_r + a_r (x - t_r)   if x >= t_r
        y = x                     if t_l < x < t_r
        y = t_l + a_l (x - t_l)   if x <= t_l
    """

    def build_params(self, rng, input_shape):
        shape = tuple(input_shape[1:])
        return {
            "t_left": jnp.zeros(shape, jnp.float32),
            "a_left": jnp.zeros(shape, jnp.float32),
            "t_right": jnp.ones(shape, jnp.float32),
            "a_right": jnp.ones(shape, jnp.float32),
        }

    def forward(self, params, x, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y_right = tr + ar * (x - tr)
        y_left = tl + al * (x - tl)
        return jnp.where(x >= tr, y_right, jnp.where(x <= tl, y_left, x))


class GaussianNoise(StatelessLayer):
    """Additive zero-mean Gaussian noise at train time
    (reference api/keras/layers/GaussianNoise.scala)."""

    def __init__(self, sigma: float, **kw):
        super().__init__(**kw)
        self.sigma = sigma

    def forward(self, params, x, training=False, rng=None):
        if not training:
            return x
        if rng is None:
            raise ValueError(f"GaussianNoise {self.name} needs rng in training")
        return x + self.sigma * jax.random.normal(rng, x.shape, x.dtype)


class GaussianDropout(StatelessLayer):
    """Multiplicative 1-mean Gaussian noise
    (reference api/keras/layers/GaussianDropout.scala)."""

    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.rate = p

    def forward(self, params, x, training=False, rng=None):
        if not training or self.rate <= 0:
            return x
        if rng is None:
            raise ValueError(f"GaussianDropout {self.name} needs rng in training")
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype))


class SpatialDropoutND(StatelessLayer):
    """Drop entire feature maps (channels-last interior)."""

    spatial = 2

    def __init__(self, p: float = 0.5, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.rate = p
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        if not training or self.rate <= 0:
            return x
        if rng is None:
            raise ValueError(f"{type(self).__name__} {self.name} needs rng")
        keep = 1.0 - self.rate
        ch_axis = 1 if self.dim_ordering == "th" else x.ndim - 1
        shape = [x.shape[0]] + [1] * (x.ndim - 1)
        shape[ch_axis] = x.shape[ch_axis]
        mask = jax.random.bernoulli(rng, keep, tuple(shape))
        return jnp.where(mask, x / keep, 0.0)


class SpatialDropout1D(SpatialDropoutND):
    spatial = 1


class SpatialDropout2D(SpatialDropoutND):
    spatial = 2


class SpatialDropout3D(SpatialDropoutND):
    spatial = 3


class RReLU(StatelessLayer):
    """Randomized leaky ReLU (reference BigDL RReLU via keras layer
    surface): negative slope ~ U[lower, upper] per element in training,
    the fixed mean slope at inference."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 **kw):
        super().__init__(**kw)
        self.lower = lower
        self.upper = upper

    def forward(self, params, x, training=False, rng=None):
        if training and rng is not None:
            a = jax.random.uniform(rng, x.shape, x.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x)
