"""Torch-style element/shape layers.

Reference capability: pyzoo/zoo/pipeline/api/keras/layers/torch.py (Select:28,
Narrow:61, Squeeze:94, AddConstant:130, MulConstant:153, CAdd:271, CMul:302,
Exp:334, Identity:355, Log:374, Mul:395, Power:416, Scale:445, Sqrt:472,
Square:493, HardShrink:514, HardTanh:537, Negative:562, SoftShrink:644,
BinaryThreshold:696, Threshold:721, SelectTable:793) and the Scala-only
Max.scala / Expand.scala / GetShape.scala.

TPU-native design: every layer is a pure ``jnp`` expression — XLA fuses these
into the neighbouring matmul/conv, so none of them costs a kernel launch the
way the reference's per-layer torch modules do.  Axis conventions follow the
reference python API: ``dim`` is a 0-based index over the FULL tensor
(batch included); the batch dimension (dim 0) cannot be selected / narrowed /
squeezed / reduced; negative dims count from the end.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.nn.module import StatelessLayer


def _norm_dim(dim: int, rank: int, what: str) -> int:
    d = dim + rank if dim < 0 else dim
    if not 0 <= d < rank:
        raise ValueError(f"{what}: dim {dim} out of range for rank {rank}")
    if d == 0:
        raise ValueError(f"{what}: cannot operate on the batch dimension")
    return d


# ---------------------------------------------------------------------------
# element-wise math (no parameters)
# ---------------------------------------------------------------------------

class Square(StatelessLayer):
    """Element-wise ``x**2`` (reference torch.py:493)."""

    def forward(self, params, x, training=False, rng=None):
        return jnp.square(x)


class Sqrt(StatelessLayer):
    """Element-wise square root (reference torch.py:472)."""

    def forward(self, params, x, training=False, rng=None):
        return jnp.sqrt(x)


class Log(StatelessLayer):
    """Element-wise natural log (reference torch.py:374)."""

    def forward(self, params, x, training=False, rng=None):
        return jnp.log(x)


class Exp(StatelessLayer):
    """Element-wise exp (reference torch.py:334)."""

    def forward(self, params, x, training=False, rng=None):
        return jnp.exp(x)


class Negative(StatelessLayer):
    """Element-wise negation (reference torch.py:562)."""

    def forward(self, params, x, training=False, rng=None):
        return -x


class Identity(StatelessLayer):
    """Pass-through (reference torch.py:355)."""

    def forward(self, params, x, training=False, rng=None):
        return x


class Power(StatelessLayer):
    """``f(x) = (shift + scale * x) ** power`` (reference torch.py:416)."""

    def __init__(self, power, scale=1.0, shift=0.0, **kw):
        super().__init__(**kw)
        self.power = float(power)
        self.scale = float(scale)
        self.shift = float(shift)

    def forward(self, params, x, training=False, rng=None):
        return jnp.power(self.shift + self.scale * x, self.power)


class AddConstant(StatelessLayer):
    """Add a non-learnable scalar constant (reference torch.py:130)."""

    def __init__(self, constant, **kw):
        super().__init__(**kw)
        self.constant = float(constant)

    def forward(self, params, x, training=False, rng=None):
        return x + self.constant


class MulConstant(StatelessLayer):
    """Multiply by a non-learnable scalar constant (reference torch.py:153)."""

    def __init__(self, constant, **kw):
        super().__init__(**kw)
        self.constant = float(constant)

    def forward(self, params, x, training=False, rng=None):
        return x * self.constant


# ---------------------------------------------------------------------------
# thresholding / shrinkage activations
# ---------------------------------------------------------------------------

class HardTanh(StatelessLayer):
    """Clip to ``[min_value, max_value]`` (reference torch.py:537)."""

    def __init__(self, min_value=-1.0, max_value=1.0, **kw):
        super().__init__(**kw)
        if max_value <= min_value:
            raise ValueError("HardTanh needs max_value > min_value")
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def forward(self, params, x, training=False, rng=None):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(StatelessLayer):
    """``x if |x| > value else 0`` (reference torch.py:514)."""

    def __init__(self, value=0.5, **kw):
        super().__init__(**kw)
        self.value = float(value)

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0)


class SoftShrink(StatelessLayer):
    """``x-v if x>v; x+v if x<-v; else 0`` (reference torch.py:644)."""

    def __init__(self, value=0.5, **kw):
        super().__init__(**kw)
        self.value = float(value)

    def forward(self, params, x, training=False, rng=None):
        v = self.value
        return jnp.where(x > v, x - v, jnp.where(x < -v, x + v, 0.0))


class Threshold(StatelessLayer):
    """``x if x > th else v`` (reference torch.py:721)."""

    def __init__(self, th=1e-6, v=0.0, **kw):
        super().__init__(**kw)
        self.th = float(th)
        self.v = float(v)

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(StatelessLayer):
    """``0 where x < value, 1 elsewhere`` (reference torch.py:696)."""

    def __init__(self, value=1e-6, **kw):
        super().__init__(**kw)
        self.value = float(value)

    def forward(self, params, x, training=False, rng=None):
        return jnp.where(x < self.value, 0.0, 1.0).astype(x.dtype)


# ---------------------------------------------------------------------------
# learnable element-wise layers
# ---------------------------------------------------------------------------

class CAdd(StatelessLayer):
    """Learnable bias of shape ``size`` added element-wise with broadcast
    (reference torch.py:271).  Expansion follows numpy broadcasting rules:
    singleton dims of the bias repeat against the input."""

    def __init__(self, size: Sequence[int], b_regularizer=None, **kw):
        super().__init__(**kw)
        self.size = tuple(int(s) for s in size)
        from analytics_zoo_tpu.nn import regularizers as _reg
        self.b_regularizer = _reg.get(b_regularizer)

    def build_params(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size, jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        return x + params["bias"]

    def regularization_loss(self, params):
        if self.b_regularizer is None:
            return 0.0
        return self.b_regularizer(params["bias"])


class CMul(StatelessLayer):
    """Learnable weight of shape ``size`` multiplied element-wise with
    broadcast (reference torch.py:302)."""

    def __init__(self, size: Sequence[int], W_regularizer=None, **kw):
        super().__init__(**kw)
        self.size = tuple(int(s) for s in size)
        from analytics_zoo_tpu.nn import regularizers as _reg
        self.w_regularizer = _reg.get(W_regularizer)

    def build_params(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        return x * params["weight"]

    def regularization_loss(self, params):
        if self.w_regularizer is None:
            return 0.0
        return self.w_regularizer(params["weight"])


class Mul(StatelessLayer):
    """Single learnable scalar factor (reference torch.py:395)."""

    def build_params(self, rng, input_shape):
        return {"weight": jnp.ones((), jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        return x * params["weight"]


class Scale(StatelessLayer):
    """CMul then CAdd with shared ``size`` (reference torch.py:445)."""

    def __init__(self, size: Sequence[int], **kw):
        super().__init__(**kw)
        self.size = tuple(int(s) for s in size)

    def build_params(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        return x * params["weight"] + params["bias"]


# ---------------------------------------------------------------------------
# shape / indexing layers
# ---------------------------------------------------------------------------

class Select(StatelessLayer):
    """Select ``index`` along ``dim`` and drop that dimension
    (reference torch.py:28).  ``dim``/``index`` may be negative."""

    def __init__(self, dim: int, index: int, **kw):
        super().__init__(**kw)
        self.dim = int(dim)
        self.index = int(index)

    def forward(self, params, x, training=False, rng=None):
        d = _norm_dim(self.dim, x.ndim, "Select")
        i = self.index + x.shape[d] if self.index < 0 else self.index
        if not 0 <= i < x.shape[d]:
            raise IndexError(
                f"Select: index {self.index} out of range for dim {d} "
                f"of size {x.shape[d]}")
        return jax.lax.index_in_dim(x, i, axis=d, keepdims=False)


class Narrow(StatelessLayer):
    """Slice ``[offset, offset+length)`` along ``dim`` without reducing rank
    (reference torch.py:61).  ``length=-1`` means to the end."""

    def __init__(self, dim: int, offset: int, length: int = 1, **kw):
        super().__init__(**kw)
        self.dim = int(dim)
        self.offset = int(offset)
        self.length = int(length)

    def forward(self, params, x, training=False, rng=None):
        d = _norm_dim(self.dim, x.ndim, "Narrow")
        ofs = self.offset + x.shape[d] if self.offset < 0 else self.offset
        length = x.shape[d] - ofs if self.length == -1 else self.length
        if not (0 <= ofs and ofs + length <= x.shape[d] and length >= 0):
            raise IndexError(
                f"Narrow: [{self.offset}, {self.offset}+{self.length}) out "
                f"of range for dim {d} of size {x.shape[d]}")
        return jax.lax.slice_in_dim(x, ofs, ofs + length, axis=d)


class Squeeze(StatelessLayer):
    """Drop singleton dim(s); never the batch dim (reference torch.py:94).
    ``dim=None`` drops every non-batch singleton dimension."""

    def __init__(self, dim: Union[int, Sequence[int], None] = None, **kw):
        super().__init__(**kw)
        if isinstance(dim, int):
            dim = (dim,)
        self.dim = tuple(dim) if dim is not None else None

    def forward(self, params, x, training=False, rng=None):
        if self.dim is None:
            axes = tuple(d for d in range(1, x.ndim) if x.shape[d] == 1)
        else:
            axes = tuple(_norm_dim(d, x.ndim, "Squeeze") for d in self.dim)
            for d in axes:
                if x.shape[d] != 1:
                    raise ValueError(
                        f"Squeeze: dim {d} has size {x.shape[d]}, not 1")
        return jnp.squeeze(x, axis=axes)


class SelectTable(StatelessLayer):
    """Pick element ``index`` from a multi-input list (reference
    torch.py:793)."""

    def __init__(self, index: int, **kw):
        super().__init__(**kw)
        self.index = int(index)

    def forward(self, params, *inputs, training=False, rng=None):
        return inputs[self.index]


class Max(StatelessLayer):
    """Max over ``dim``, keeping it as size 1 (reference Max.scala:39 —
    ``computeOutputShape`` pins the reduced dim to 1).  ``return_value=False``
    returns the argmax indices instead."""

    def __init__(self, dim: int, return_value: bool = True, **kw):
        super().__init__(**kw)
        self.dim = int(dim)
        self.return_value = bool(return_value)

    def forward(self, params, x, training=False, rng=None):
        d = _norm_dim(self.dim, x.ndim, "Max")
        if self.return_value:
            return jnp.max(x, axis=d, keepdims=True)
        return jnp.argmax(x, axis=d, keepdims=True).astype(jnp.int32)


class Expand(StatelessLayer):
    """Broadcast singleton dims to ``tgt_sizes`` (full shape incl. batch;
    ``-1`` keeps a dim unchanged).  Reference Expand.scala:InternalExpand."""

    def __init__(self, tgt_sizes: Sequence[int], **kw):
        super().__init__(**kw)
        self.tgt_sizes = tuple(int(s) for s in tgt_sizes)

    def forward(self, params, x, training=False, rng=None):
        if len(self.tgt_sizes) != x.ndim:
            raise ValueError(
                f"Expand: tgt_sizes rank {len(self.tgt_sizes)} != input "
                f"rank {x.ndim} (include the batch dim; use -1 to keep)")
        target = tuple(x.shape[i] if t == -1 else t
                       for i, t in enumerate(self.tgt_sizes))
        return jnp.broadcast_to(x, target)


class GetShape(StatelessLayer):
    """Return the input's full shape as an int32 vector of length ``rank``
    (reference GetShape.scala — zero gradient, which holds trivially here
    because the output does not depend on the input values)."""

    def forward(self, params, x, training=False, rng=None):
        return jnp.asarray(x.shape, jnp.int32)
