"""Attention layers: MultiHeadAttention, TransformerLayer, BERT.

Reference capability: api/keras/layers/TransformerLayer.scala:56 (GPT-style
decoder stack: token+position embedding, n blocks of attention+FFN with
residuals and LayerNorm) and api/keras/layers/BERT.scala:66 (encoder stack
with word/position/segment embeddings, attention mask, pooler).

TPU-first: attention lowers to ``ops.attention.dot_product_attention`` —
blockwise online-softmax (flash) rather than the reference's materialized
O(L²) score matrix; projections are fused batched matmuls (MXU); dropout
uses threaded PRNG keys.  Long-context via ring attention plugs in here
through the same op interface (parallel/sequence.py).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import Layer, StatelessLayer, split_rng
from analytics_zoo_tpu.ops.attention import dot_product_attention
from analytics_zoo_tpu.parallel.mode import (
    current_pipeline as _current_pipeline,
    current_seq_parallel as _current_seq_parallel)


def _dense_params(rng, d_in, d_out, init, dtype=jnp.float32):
    return {"kernel": init(rng, (d_in, d_out), dtype),
            "bias": jnp.zeros((d_out,), dtype)}


def _dense(p, x):
    return jnp.dot(x, p["kernel"]) + p["bias"]


# Single source of LayerNorm math: the canonical layer from normalization.py
from analytics_zoo_tpu.nn.layers.normalization import LayerNorm as _LayerNorm

_LN = _LayerNorm(name="attention_shared_ln")


def _layernorm_params(d):
    return _LN.build_params(None, (1, d))


def _layernorm(p, x):
    return _LN.forward(p, x)


def _dropout(rng, x, rate, training):
    if not training or rate <= 0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


class MultiHeadAttention(StatelessLayer):
    """Multi-head (self or cross) attention with fused QKV projection.

    Single input → self-attention; two inputs (q, kv) → cross-attention.
    An optional third input is the attention mask (1 = attend),
    broadcastable to (B, 1, Lq, Lk).
    """

    def __init__(self, nhead: int, hidden_size: Optional[int] = None,
                 attn_drop: float = 0.0, output_drop: float = 0.0,
                 causal: bool = False, init="glorot_uniform",
                 seq_shards: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.nhead = nhead
        self.hidden_size = hidden_size
        self.attn_drop = attn_drop
        self.output_drop = output_drop
        self.causal = causal
        # sequence shards for ring attention outside an explicit sp
        # regime: None defers to the ZooConfig.seq_shards knob at
        # forward time; 0/1 disables (docs/PARALLELISM.md)
        self.seq_shards = seq_shards
        self.initializer = initializers.get(init)

    def build_params(self, rng, q_shape, *rest):
        d = self.hidden_size or q_shape[-1]
        if d % self.nhead:
            raise ValueError(f"hidden {d} not divisible by nhead {self.nhead}")
        kv_d = rest[0][-1] if rest else q_shape[-1]
        ks = jax.random.split(rng, 4)
        return {
            "q": _dense_params(ks[0], q_shape[-1], d, self.initializer),
            "k": _dense_params(ks[1], kv_d, d, self.initializer),
            "v": _dense_params(ks[2], kv_d, d, self.initializer),
            "o": _dense_params(ks[3], d, d, self.initializer),
        }

    def _split_heads(self, x):
        b, l, d = x.shape
        return x.reshape(b, l, self.nhead, d // self.nhead).transpose(
            0, 2, 1, 3)

    def forward(self, params, *inputs, training=False, rng=None):
        # Input forms: (x) self-attn; (q, kv) cross-attn with kv 3D;
        # (x, mask) self-attn with a 2D key-padding or 4D full mask;
        # (q, kv, mask).  A 3D (B, Lq, Lk) mask needs the 3-arg form.
        mask = None
        if len(inputs) == 1:
            q_in = kv_in = inputs[0]
        elif len(inputs) == 2:
            if inputs[1].ndim == 3:
                q_in, kv_in = inputs
            else:
                q_in = kv_in = inputs[0]
                mask = inputs[1]
        else:
            q_in, kv_in, mask = inputs
        q = self._split_heads(_dense(params["q"], q_in))
        k = self._split_heads(_dense(params["k"], kv_in))
        v = self._split_heads(_dense(params["v"], kv_in))
        if mask is not None:
            if mask.ndim == 2:      # (B, Lk) key padding mask
                mask = mask[:, None, None, :]
            elif mask.ndim == 3:    # (B, Lq, Lk) full mask
                mask = mask[:, None, :, :]
        r1, r2 = split_rng(rng, 2)
        sp = _current_seq_parallel()
        if sp is not None:
            # sequence-parallel regime (compile(sharding="sp")): K/V
            # rotate around the mesh's sequence ring instead of
            # materialising blockwise attention on one device.  The ring
            # kernel supports causal/no mask and skips attention-prob
            # dropout (parallel/sequence.py).
            if mask is not None:
                raise ValueError(
                    "sequence-parallel attention does not support "
                    "padding/attention masks (causal=True is supported); "
                    "drop the mask input or use sharding='dp'")
            if kv_in is not q_in:
                raise ValueError(
                    "sequence-parallel attention supports self-attention "
                    "only (q and kv shards must rotate together)")
            from analytics_zoo_tpu.parallel.sequence import (
                ring_self_attention)
            out = ring_self_attention(q, k, v, sp.mesh, sp.axis,
                                      causal=self.causal,
                                      batch_axis=sp.batch_axis)
        else:
            # attn_drop acts on the softmax probabilities (reference
            # TransformerLayer/BERT semantics) via the blockwise path,
            # which keeps the flash memory bound; inference uses the
            # fused kernels
            drop = self.attn_drop if (training and r1 is not None) else 0.0
            ring_mesh = None
            if mask is None and kv_in is q_in and drop == 0.0:
                # seq_shards knob: long-context self-attention shards L
                # over a ring of devices even without an explicit sp
                # regime (serving's long-document bucket rides this).
                # The op's counted dispatch still applies its min-length
                # and knob routing, so short sequences stay local.
                from analytics_zoo_tpu.ops.dispatch import config_knob
                ways = (self.seq_shards if self.seq_shards is not None
                        else config_knob("seq_shards", 0) or 0)
                if ways and ways > 1:
                    from analytics_zoo_tpu.parallel.sharding import seq_mesh
                    ring_mesh = seq_mesh(int(ways))
            if ring_mesh is not None:
                from analytics_zoo_tpu.ops.ring_attention import (
                    ring_attention)
                out = ring_attention(q, k, v, mesh=ring_mesh, axis="seq",
                                     causal=self.causal)
            else:
                out = dot_product_attention(q, k, v, mask=mask,
                                            causal=self.causal,
                                            dropout_rate=drop,
                                            dropout_rng=r1)
        b, h, l, hd = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, l, h * hd)
        out = _dense(params["o"], out)
        return _dropout(r2, out, self.output_drop, training)


class TransformerBlock(StatelessLayer):
    """One attention + FFN block with residuals.

    ``after_norm=False`` → post-LN (original Transformer / BERT / the
    reference's TransformerLayer); ``True`` → pre-LN (more stable deep).
    """

    def __init__(self, nhead: int, hidden_size: int,
                 intermediate_size: Optional[int] = None,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 causal: bool = False, activation="gelu",
                 after_norm: bool = False, init="glorot_uniform",
                 seq_shards: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.attn = MultiHeadAttention(nhead, hidden_size,
                                       attn_drop=attn_drop, causal=causal,
                                       init=init, seq_shards=seq_shards,
                                       name=f"{self.name}_attn")
        self.hidden_size = hidden_size
        self.intermediate = intermediate_size or 4 * hidden_size
        self.hidden_drop = hidden_drop
        self.act = activations.get(activation)
        self.pre_ln = after_norm
        self.initializer = initializers.get(init)

    def build_params(self, rng, x_shape, *rest):
        d = self.hidden_size
        ks = jax.random.split(rng, 3)
        return {
            "attn": self.attn.build_params(ks[0], x_shape),
            "ln1": _layernorm_params(d),
            "ln2": _layernorm_params(d),
            "ffn1": _dense_params(ks[1], d, self.intermediate,
                                  self.initializer),
            "ffn2": _dense_params(ks[2], self.intermediate, d,
                                  self.initializer),
        }

    def forward(self, params, x, *rest, training=False, rng=None):
        mask = rest[0] if rest else None
        r1, r2, r3 = split_rng(rng, 3)
        attn_in = _layernorm(params["ln1"], x) if self.pre_ln else x
        a_args = (attn_in,) if mask is None else (attn_in, mask)
        a = self.attn.forward(params["attn"], *a_args, training=training,
                              rng=r1)
        x = x + _dropout(r2, a, self.hidden_drop, training)
        if not self.pre_ln:
            x = _layernorm(params["ln1"], x)
        ffn_in = _layernorm(params["ln2"], x) if self.pre_ln else x
        h = self.act(_dense(params["ffn1"], ffn_in))
        h = _dense(params["ffn2"], h)
        x = x + _dropout(r3, h, self.hidden_drop, training)
        if not self.pre_ln:
            x = _layernorm(params["ln2"], x)
        return x


def _stack_block_params(block, keys, hshape):
    """Build one params pytree per key and stack on a leading dim — the
    layout `lax.scan` consumes and the PipelineStrategy shards."""
    per_block = [block.build_params(k, hshape) for k in keys]
    return jax.tree_util.tree_map(lambda *ps: jnp.stack(ps, axis=0),
                                  *per_block)


def _run_block_stack(block, n_block, blocks_params, x, training, rng,
                     mask=None):
    """Run a stacked homogeneous block pytree: the GPipe schedule under
    an active pipeline regime, otherwise one `lax.scan` (per-block rng
    threading for dropout).  Shared by TransformerLayer and BERT so the
    two stacked paths cannot diverge."""
    pipe = _current_pipeline()
    if pipe is not None:
        from analytics_zoo_tpu.parallel.pipeline import pipeline_apply

        if mask is None:
            def stage(p, h):
                return block.forward(p, h, training=False, rng=None)

            return pipeline_apply(stage, blocks_params, x, pipe.mesh,
                                  pipe.axis, pipe.n_microbatches,
                                  pipe.remat, batch_axis=pipe.batch_axis)

        # masked pp: the mask is an aux side input — it never rides the
        # ppermute ring; every stage indexes the microbatch matching the
        # activation it holds (parallel/pipeline.py pipeline_spmd)
        def stage_m(p, h, m):
            return block.forward(p, h, m, training=False, rng=None)

        return pipeline_apply(stage_m, blocks_params, x, pipe.mesh,
                              pipe.axis, pipe.n_microbatches,
                              pipe.remat, batch_axis=pipe.batch_axis,
                              aux=mask)

    def apply(p, h, r):
        args = (h,) if mask is None else (h, mask)
        return block.forward(p, *args, training=training, rng=r)

    if rng is not None:
        rngs = jax.random.split(rng, n_block)

        def body(h, pr):
            p, r = pr
            return apply(p, h, r), None

        x, _ = jax.lax.scan(body, x, (blocks_params, rngs))
    else:
        def body(h, p):
            return apply(p, h, None), None

        x, _ = jax.lax.scan(body, x, blocks_params)
    return x


class TransformerLayer(StatelessLayer):
    """GPT-style decoder stack over token ids
    (reference api/keras/layers/TransformerLayer.scala:56).

    Input: int32 token ids (B, L) [+ optional position ids (B, L)].
    Output: hidden states (B, L, hidden_size).

    ``stacked=True`` stores the homogeneous blocks as ONE pytree with a
    leading ``n_block`` dim under ``params["blocks"]`` and runs them via
    ``lax.scan`` — faster compiles for deep stacks, and the layout the
    pipeline-parallel regime shards: under ``compile(sharding="pp")``
    the stack lowers to the GPipe microbatch schedule
    (parallel/pipeline.py) with stage weights 1/S per device.  Inside
    pipeline stages dropout is disabled (the ppermute ring carries no
    rng); embedding dropout still applies.
    """

    def __init__(self, vocab: int = 40990, seq_len: int = 77,
                 n_block: int = 12, nhead: int = 12, hidden_size: int = 768,
                 intermediate_size: Optional[int] = None,
                 hidden_drop: float = 0.1, attn_drop: float = 0.1,
                 embedding_drop: float = 0.1, causal: bool = True,
                 after_norm: bool = False, init="glorot_uniform",
                 stacked: bool = False,
                 seq_shards: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.vocab, self.seq_len = vocab, seq_len
        self.hidden_size = hidden_size
        self.embedding_drop = embedding_drop
        self.n_block = n_block
        self.stacked = stacked
        if stacked:
            # one template block; per-block weights differ via the rng
            self.block = TransformerBlock(nhead, hidden_size,
                                          intermediate_size, hidden_drop,
                                          attn_drop, causal=causal,
                                          after_norm=after_norm, init=init,
                                          seq_shards=seq_shards,
                                          name=f"{self.name}_block")
            self.blocks = []
        else:
            self.blocks = [
                TransformerBlock(nhead, hidden_size, intermediate_size,
                                 hidden_drop, attn_drop, causal=causal,
                                 after_norm=after_norm, init=init,
                                 seq_shards=seq_shards,
                                 name=f"{self.name}_block{i}")
                for i in range(n_block)]
        self.initializer = initializers.get(init)

    def build_params(self, rng, ids_shape, *rest):
        ks = jax.random.split(rng, 2 + self.n_block)
        d = self.hidden_size
        params = {
            "tok_embed": self.initializer(ks[0], (self.vocab, d),
                                          jnp.float32) * 0.1,
            "pos_embed": self.initializer(ks[1], (self.seq_len, d),
                                          jnp.float32) * 0.1,
        }
        hshape = tuple(ids_shape) + (d,)
        if self.stacked:
            params["blocks"] = _stack_block_params(
                self.block, ks[2:2 + self.n_block], hshape)
        else:
            for i, blk in enumerate(self.blocks):
                params[f"block{i}"] = blk.build_params(ks[2 + i], hshape)
        return params

    def forward(self, params, ids, *rest, training=False, rng=None):
        pos_ids = rest[0] if rest else None
        ids = ids.astype(jnp.int32)  # container abstract-eval passes f32
        l = ids.shape[1]
        x = params["tok_embed"][ids]
        if pos_ids is None:
            x = x + params["pos_embed"][None, :l]
        else:
            x = x + params["pos_embed"][pos_ids.astype(jnp.int32)]
        if self.stacked:
            r0, rblocks = split_rng(rng, 2)
            x = _dropout(r0, x, self.embedding_drop, training)
            return _run_block_stack(self.block, self.n_block,
                                    params["blocks"], x, training, rblocks)
        rngs = split_rng(rng, 1 + len(self.blocks))
        x = _dropout(rngs[0], x, self.embedding_drop, training)
        for i, blk in enumerate(self.blocks):
            x = blk.forward(params[f"block{i}"], x, training=training,
                            rng=rngs[1 + i])
        return x


class BERT(StatelessLayer):
    """BERT encoder (reference api/keras/layers/BERT.scala:66).

    Inputs: token ids (B, L), segment ids (B, L), [position ids (B, L)],
    [attention mask (B, L), 1 = real token].
    Output: (sequence_output (B, L, H), pooled_output (B, H)).
    """

    def __init__(self, vocab: int = 40990, hidden_size: int = 768,
                 n_block: int = 12, nhead: int = 12,
                 intermediate_size: int = 3072, max_position_len: int = 512,
                 type_vocab_size: int = 2, hidden_drop: float = 0.1,
                 attn_drop: float = 0.1, init="glorot_uniform",
                 stacked: bool = False,
                 seq_shards: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.vocab = vocab
        self.hidden_size = hidden_size
        self.max_position_len = max_position_len
        self.type_vocab_size = type_vocab_size
        self.hidden_drop = hidden_drop
        self.n_block = n_block
        # stacked=True: blocks live as ONE pytree (leading n_block dim)
        # run via lax.scan — compile time stays flat as the stack
        # deepens (trace one block, not twelve); the attention mask
        # threads through the scan as a broadcast operand
        self.stacked = stacked
        mk = lambda name: TransformerBlock(
            nhead, hidden_size, intermediate_size, hidden_drop, attn_drop,
            causal=False, activation="gelu", after_norm=False, init=init,
            seq_shards=seq_shards, name=name)
        if stacked:
            self.block = mk(f"{self.name}_enc")
            self.blocks = []
        else:
            self.blocks = [mk(f"{self.name}_enc{i}")
                           for i in range(n_block)]
        self.initializer = initializers.get(init)

    def build_params(self, rng, ids_shape, *rest):
        d = self.hidden_size
        ks = jax.random.split(rng, 4 + self.n_block)
        params = {
            "word_embed": self.initializer(ks[0], (self.vocab, d),
                                           jnp.float32) * 0.1,
            "pos_embed": self.initializer(ks[1], (self.max_position_len, d),
                                          jnp.float32) * 0.1,
            "type_embed": self.initializer(ks[2], (self.type_vocab_size, d),
                                           jnp.float32) * 0.1,
            "embed_ln": _layernorm_params(d),
            "pooler": _dense_params(ks[3], d, d, self.initializer),
        }
        hshape = tuple(ids_shape) + (d,)
        if self.stacked:
            params["blocks"] = _stack_block_params(
                self.block, ks[4:4 + self.n_block], hshape)
        else:
            for i, blk in enumerate(self.blocks):
                params[f"enc{i}"] = blk.build_params(ks[4 + i], hshape)
        return params

    def forward(self, params, ids, *rest, training=False, rng=None):
        ids = ids.astype(jnp.int32)  # container abstract-eval passes f32
        seg_ids = (rest[0].astype(jnp.int32) if len(rest) > 0
                   else jnp.zeros_like(ids))
        pos_ids = rest[1] if len(rest) > 1 else None
        mask = rest[2] if len(rest) > 2 else None
        l = ids.shape[1]
        x = params["word_embed"][ids] + params["type_embed"][seg_ids]
        if pos_ids is None:
            x = x + params["pos_embed"][None, :l]
        else:
            x = x + params["pos_embed"][pos_ids.astype(jnp.int32)]
        x = _layernorm(params["embed_ln"], x)
        if self.stacked:
            r0, rblocks = split_rng(rng, 2)
            x = _dropout(r0, x, self.hidden_drop, training)
            x = _run_block_stack(self.block, self.n_block,
                                 params["blocks"], x, training, rblocks,
                                 mask=mask)
        else:
            rngs = split_rng(rng, 1 + len(self.blocks))
            x = _dropout(rngs[0], x, self.hidden_drop, training)
            for i, blk in enumerate(self.blocks):
                args = (x,) if mask is None else (x, mask)
                x = blk.forward(params[f"enc{i}"], *args,
                                training=training, rng=rngs[1 + i])
        pooled = jnp.tanh(_dense(params["pooler"], x[:, 0]))
        return [x, pooled]
