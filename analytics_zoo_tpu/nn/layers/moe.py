"""Sparse Mixture-of-Experts with expert parallelism.

Reference capability: **absent** (SURVEY.md §2.4 — expert parallelism is
an explicit gap in the reference).  TPU-native design: dense one-hot
dispatch/combine einsums (the Switch/GShard recipe) so routing lowers to
MXU matmuls with static shapes — no scatter, no dynamic shapes, nothing
XLA can't tile.  The expert dimension of both weights and the dispatched
activations is sharded over an ``expert`` mesh axis; GSPMD inserts the
all-to-alls over ICI.

Routing = top-k gating with capacity: each expert processes at most
``C = ceil(top_k * N * capacity_factor / E)`` tokens per batch; overflow
tokens are dropped from that expert (their combine weight is zero), the
standard capacity discipline that keeps shapes static.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import Layer


class SparseMoE(Layer):
    """Mixture-of-experts FFN: ``y[t] = Σ_k gate_k(t) · FFN_{e_k(t)}(x[t])``.

    Params: gate kernel (D, E) + per-expert FFN weights stacked on a
    leading E dim — ``w1 (E, D, H)``, ``w2 (E, H, D_out)`` — so an
    ``ExpertParallel`` strategy (or ``expert_axis=``) shards dim 0.

    ``state`` carries the Switch-style load-balance auxiliary loss under
    ``"aux_loss"`` (refreshed every call); add
    ``aux_loss_weight * state["aux_loss"]`` to the objective when
    training routers.
    """

    def __init__(self, n_experts: int, hidden_dim: int,
                 output_dim: Optional[int] = None, top_k: int = 2,
                 capacity_factor: float = 1.25, activation="relu",
                 expert_axis: Optional[str] = None,
                 init="glorot_uniform", dtype=jnp.float32, **kw):
        super().__init__(**kw)
        if top_k < 1 or top_k > n_experts:
            raise ValueError(f"top_k {top_k} out of range for "
                             f"{n_experts} experts")
        self.n_experts = n_experts
        self.hidden_dim = hidden_dim
        self.output_dim = output_dim
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.activation = activations.get(activation)
        self.expert_axis = expert_axis
        self.initializer = initializers.get(init)
        self.dtype = dtype

    def build(self, rng, input_shape):
        d = input_shape[-1]
        out = self.output_dim or d
        kg, k1, k2 = jax.random.split(rng, 3)
        e, h = self.n_experts, self.hidden_dim
        params = {
            "gate": self.initializer(kg, (d, e), self.dtype),
            "w1": self.initializer(k1, (e, d, h), self.dtype),
            "b1": jnp.zeros((e, h), self.dtype),
            "w2": self.initializer(k2, (e, h, out), self.dtype),
            "b2": jnp.zeros((e, out), self.dtype),
        }
        return params, {"aux_loss": jnp.zeros((), jnp.float32)}

    # -- routing ----------------------------------------------------------
    def _route(self, gates, n_tokens):
        """gates (N, E) softmax probs -> dispatch/combine (N, E, C)."""
        e = self.n_experts
        cap = int(np.ceil(self.top_k * n_tokens * self.capacity_factor / e))
        cap = max(cap, 1)
        topw, topi = lax.top_k(gates, self.top_k)          # (N, k)
        topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)

        counts = jnp.zeros((e,), jnp.float32)
        dispatch = jnp.zeros((gates.shape[0], e, cap), gates.dtype)
        combine = jnp.zeros_like(dispatch)
        for j in range(self.top_k):
            oh = jax.nn.one_hot(topi[:, j], e, dtype=jnp.float32)   # (N, E)
            pos = jnp.cumsum(oh, axis=0) - 1.0 + counts[None, :]    # (N, E)
            counts = counts + oh.sum(0)
            keep = oh * (pos < cap)                                  # (N, E)
            pos_oh = jax.nn.one_hot(
                jnp.clip(pos, 0, cap - 1).astype(jnp.int32), cap,
                dtype=gates.dtype)                                   # (N,E,C)
            d_j = keep.astype(gates.dtype)[:, :, None] * pos_oh
            dispatch = dispatch + d_j
            combine = combine + d_j * topw[:, j][:, None, None]
        return dispatch, combine, cap

    def _constrain(self, x, spec):
        if self.expert_axis is None:
            return x
        try:
            from analytics_zoo_tpu.core.context import get_zoo_context
            mesh = get_zoo_context().mesh
        except (ImportError, RuntimeError, LookupError):
            return x          # no context initialised — run unconstrained
        if self.expert_axis not in mesh.axis_names:
            return x
        # a failing with_sharding_constraint is a real misconfiguration
        # and must propagate, not silently drop the expert layout
        return lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    def call(self, params, state, x, training: bool = False, rng=None):
        orig = x.shape
        d = orig[-1]
        tokens = x.reshape(-1, d)                           # (N, D)
        n = tokens.shape[0]
        ax = self.expert_axis

        logits = jnp.dot(tokens, params["gate"]).astype(jnp.float32)
        gates = jax.nn.softmax(logits, axis=-1)             # (N, E)
        dispatch, combine, cap = self._route(
            gates.astype(tokens.dtype), n)

        # Switch load-balance loss: E · Σ_e  frac_tokens(e) · mean_prob(e)
        me = gates.mean(0)                                  # (E,)
        ce = jax.nn.one_hot(jnp.argmax(gates, -1),
                            self.n_experts).mean(0)         # (E,)
        aux = self.n_experts * jnp.sum(me * ce)

        # dispatch -> (E, C, D), sharded on the expert axis (GSPMD turns
        # the layout change into an all-to-all over ICI)
        expert_in = jnp.einsum("nd,nec->ecd", tokens, dispatch)
        expert_in = self._constrain(expert_in, P(ax, None, None))
        h = jnp.einsum("ecd,edh->ech", expert_in, params["w1"])
        h = self.activation(h + params["b1"][:, None, :])
        h = self._constrain(h, P(ax, None, None))
        out = jnp.einsum("ech,eho->eco", h, params["w2"])
        out = out + params["b2"][:, None, :]
        out = self._constrain(out, P(ax, None, None))
        y = jnp.einsum("eco,nec->no", out, combine)         # back to tokens

        new_state = dict(state)
        new_state["aux_loss"] = aux.astype(jnp.float32)
        return y.reshape(orig[:-1] + y.shape[-1:]), new_state


def moe_aux_loss(state) -> jax.Array:
    """Sum every ``aux_loss`` entry in a (possibly nested) state pytree —
    the term to add to the objective, scaled by the aux weight."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_leaves_with_path(state):
        if any(getattr(k, "key", None) == "aux_loss" for k in path):
            total = total + jnp.asarray(leaf, jnp.float32)
    return total
