"""Embedding layers.

Reference capability: api/keras/layers/{Embedding,SparseEmbedding},
WordEmbedding.scala (pretrained GloVe tables).  TPU-first design decision
(SURVEY.md §7 "hard parts"): recsys/NLP embeddings are **dense gather
tables** — ``table[ids]`` lowers to an XLA gather that is fast on TPU and
shardable over the model axis for very large vocabularies; there is no
sparse-tensor path (BigDL's SparseEmbedding exists to save CPU memory
traffic, which the gather already avoids on TPU).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import StatelessLayer


class Embedding(StatelessLayer):
    """Integer ids -> dense vectors. Input (B, ...) int -> (B, ..., dim)."""

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 trainable: bool = True, weights: Optional[np.ndarray] = None,
                 zero_based: bool = True, dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.initializer = initializers.get(init)
        self.trainable = trainable
        self.pretrained = weights
        # The reference's Embedding is 1-based (Lua heritage); default here
        # is 0-based, with an opt-in shift for API parity.
        self.zero_based = zero_based
        self.dtype = dtype

    def build_params(self, rng, input_shape):
        if self.pretrained is not None:
            table = jnp.asarray(self.pretrained, self.dtype)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"pretrained weights {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.initializer(rng, (self.input_dim, self.output_dim), self.dtype)
        return {"table": table}

    def forward(self, params, ids, training=False, rng=None):
        from analytics_zoo_tpu.ops.embedding_bag import embedding_gather

        table = params["table"]
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        ids = ids.astype(jnp.int32)
        if not self.zero_based:
            ids = ids - 1
        # routed through the fused bag kernel on TPU (singleton bags);
        # exactly jnp.take elsewhere
        return embedding_gather(table, ids)


class WordEmbedding(Embedding):
    """Pretrained, frozen word embeddings (reference WordEmbedding.scala).

    Use ``WordEmbedding.from_glove(path, word_index)`` to load a GloVe text
    file filtered to a vocabulary.
    """

    def __init__(self, input_dim: int, output_dim: int,
                 weights: Optional[np.ndarray] = None, trainable: bool = False,
                 **kw):
        super().__init__(input_dim, output_dim, weights=weights,
                         trainable=trainable, **kw)

    @staticmethod
    def from_glove(path: str, word_index: dict, trainable: bool = False,
                   **kw) -> "WordEmbedding":
        dim = None
        vectors = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                word = parts[0]
                if word in word_index:
                    vec = np.asarray(parts[1:], dtype=np.float32)
                    dim = len(vec)
                    vectors[word] = vec
        if dim is None:
            raise ValueError(f"no vocabulary words found in {path}")
        n = max(word_index.values()) + 1
        table = np.zeros((n, dim), dtype=np.float32)
        for word, idx in word_index.items():
            if word in vectors:
                table[idx] = vectors[word]
        return WordEmbedding(n, dim, weights=table, trainable=trainable, **kw)


class SparseEmbedding(StatelessLayer):
    """Embedding over sparse multi-hot id rows
    (reference api/keras/layers/SparseEmbedding.scala — embeddings for
    SparseTensor input).

    TPU layout decision (SURVEY §7 risk #2): sparse ids are densified
    host-side to a fixed-width ``(B, max_nnz)`` int array padded with
    ``pad_id`` (default 0 — row 0 of the table is reserved/zeroed), and
    the lookup is a dense gather + masked combine — gathers are the
    MXU/HBM-friendly realisation of sparsity on TPU (no SparseCore
    dependency, shapes static for XLA).
    """

    def __init__(self, input_dim: int, output_dim: int,
                 combiner: str = "sum", init="uniform", pad_id: int = 0,
                 **kw):
        super().__init__(**kw)
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be sum|mean|sqrtn, got "
                             f"{combiner!r}")
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.combiner = combiner
        self.initializer = initializers.get(init)
        self.pad_id = pad_id

    def build_params(self, rng, input_shape):
        table = self.initializer(
            rng, (self.input_dim, self.output_dim), jnp.float32)
        table = table.at[self.pad_id].set(0.0)
        return {"table": table}

    def forward(self, params, x, training=False, rng=None):
        from analytics_zoo_tpu.ops.embedding_bag import embedding_bag

        ids = x.astype(jnp.int32)                     # (B, max_nnz)
        # fused gather+combine: the Pallas kernel on TPU (fused_embedding
        # knob), the XLA gather+masked-sum reference elsewhere
        return embedding_bag(params["table"], ids, self.combiner,
                             self.pad_id)


class EmbeddingBag(StatelessLayer):
    """Dense multi-hot lookup + combine in one layer: ``(B, n_ids)`` int
    input -> ``(B, dim)``, ``combine_j table[ids[b, j]]``.

    The combine-after-gather pattern the recommenders spell as
    ``Embedding`` followed by a sum (Wide&Deep's wide tower, NCF's
    flattened single-id lookups) — expressed as one op so the fused
    Pallas kernel (ops/embedding_bag.py) sees the whole bag and never
    materialises the (B, n_ids, dim) gathered rows.  ``pad_id=None``
    (default) counts every slot — dense multi-hot, e.g. cross-column
    feature ids; set a ``pad_id`` for ragged bags padded to fixed width.
    """

    def __init__(self, input_dim: int, output_dim: int,
                 combiner: str = "sum", init="uniform",
                 pad_id: Optional[int] = None, trainable: bool = True,
                 weights: Optional[np.ndarray] = None,
                 zero_based: bool = True, **kw):
        super().__init__(**kw)
        if combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be sum|mean|sqrtn, got "
                             f"{combiner!r}")
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.combiner = combiner
        self.initializer = initializers.get(init)
        self.pad_id = pad_id
        self.trainable = trainable
        self.pretrained = weights
        self.zero_based = zero_based

    def build_params(self, rng, input_shape):
        if self.pretrained is not None:
            table = jnp.asarray(self.pretrained, jnp.float32)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"pretrained weights {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.initializer(
                rng, (self.input_dim, self.output_dim), jnp.float32)
        if self.pad_id is not None:
            table = table.at[self.pad_id].set(0.0)
        return {"table": table}

    def forward(self, params, x, training=False, rng=None):
        from analytics_zoo_tpu.ops.embedding_bag import embedding_bag

        table = params["table"]
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        ids = x.astype(jnp.int32)
        if not self.zero_based:
            ids = ids - 1
        return embedding_bag(table, ids, self.combiner, self.pad_id)


class SparseDense(StatelessLayer):
    """Dense layer over sparse multi-hot inputs
    (reference api/keras/layers/SparseDense.scala: y = act(sparse_x W + b)).

    Input is ``(B, max_nnz)`` feature INDICES (padded with ``pad_id``),
    optionally paired with ``(B, max_nnz)`` float values for weighted
    multi-hot rows.  Realised as a gather of W's rows + segment sum —
    mathematically sparse W.T x, physically one dense gather (TPU-native
    sparsity, no scatter, static shapes).
    """

    def __init__(self, output_dim: int, input_dim: int,
                 activation=None, init="glorot_uniform", bias: bool = True,
                 pad_id: int = 0, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.input_dim = input_dim
        self.activation = activations.get(activation)
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.pad_id = pad_id

    def build_params(self, rng, *input_shapes):
        params = {"kernel": self.initializer(
            rng, (self.input_dim, self.output_dim), jnp.float32)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return params

    def call(self, params, state, indices, values=None, training=False,
             rng=None):
        ids = indices.astype(jnp.int32)
        mask = (ids != self.pad_id).astype(jnp.float32)
        w = jnp.take(params["kernel"], ids, axis=0)   # (B, nnz, out)
        coeff = mask if values is None else mask * values
        y = jnp.sum(w * coeff[..., None], axis=-2)
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return y, state
