"""Embedding layers.

Reference capability: api/keras/layers/{Embedding,SparseEmbedding},
WordEmbedding.scala (pretrained GloVe tables).  TPU-first design decision
(SURVEY.md §7 "hard parts"): recsys/NLP embeddings are **dense gather
tables** — ``table[ids]`` lowers to an XLA gather that is fast on TPU and
shardable over the model axis for very large vocabularies; there is no
sparse-tensor path (BigDL's SparseEmbedding exists to save CPU memory
traffic, which the gather already avoids on TPU).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn import initializers
from analytics_zoo_tpu.nn.module import StatelessLayer


class Embedding(StatelessLayer):
    """Integer ids -> dense vectors. Input (B, ...) int -> (B, ..., dim)."""

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 trainable: bool = True, weights: Optional[np.ndarray] = None,
                 zero_based: bool = True, dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.initializer = initializers.get(init)
        self.trainable = trainable
        self.pretrained = weights
        # The reference's Embedding is 1-based (Lua heritage); default here
        # is 0-based, with an opt-in shift for API parity.
        self.zero_based = zero_based
        self.dtype = dtype

    def build_params(self, rng, input_shape):
        if self.pretrained is not None:
            table = jnp.asarray(self.pretrained, self.dtype)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"pretrained weights {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.initializer(rng, (self.input_dim, self.output_dim), self.dtype)
        return {"table": table}

    def forward(self, params, ids, training=False, rng=None):
        table = params["table"]
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        ids = ids.astype(jnp.int32)
        if not self.zero_based:
            ids = ids - 1
        return jnp.take(table, ids, axis=0)


class WordEmbedding(Embedding):
    """Pretrained, frozen word embeddings (reference WordEmbedding.scala).

    Use ``WordEmbedding.from_glove(path, word_index)`` to load a GloVe text
    file filtered to a vocabulary.
    """

    def __init__(self, input_dim: int, output_dim: int,
                 weights: Optional[np.ndarray] = None, trainable: bool = False,
                 **kw):
        super().__init__(input_dim, output_dim, weights=weights,
                         trainable=trainable, **kw)

    @staticmethod
    def from_glove(path: str, word_index: dict, trainable: bool = False,
                   **kw) -> "WordEmbedding":
        dim = None
        vectors = {}
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                word = parts[0]
                if word in word_index:
                    vec = np.asarray(parts[1:], dtype=np.float32)
                    dim = len(vec)
                    vectors[word] = vec
        if dim is None:
            raise ValueError(f"no vocabulary words found in {path}")
        n = max(word_index.values()) + 1
        table = np.zeros((n, dim), dtype=np.float32)
        for word, idx in word_index.items():
            if word in vectors:
                table[idx] = vectors[word]
        return WordEmbedding(n, dim, weights=table, trainable=trainable, **kw)
