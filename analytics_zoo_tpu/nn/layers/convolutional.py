"""Convolution layers: Conv1D/2D/3D, atrous, separable, transposed, locally
connected, padding/cropping/upsampling.

Reference capability: api/keras/layers/{Convolution1D,Convolution2D,
Convolution3D,AtrousConvolution1D,AtrousConvolution2D,SeparableConvolution2D,
Deconvolution2D,LocallyConnected1D,LocallyConnected2D,Cropping*,ZeroPadding*,
UpSampling*}.scala (SURVEY.md §2.1 Keras-style API).

TPU-first design: the native data layout is **channels-last** (NWC/NHWC/NDHWC)
— the layout XLA tiles onto the MXU without relayout copies — and every conv
is a single ``lax.conv_general_dilated`` call so XLA fuses bias+activation
into the convolution epilogue.  The reference's BigDL layers default to
channels-first ("th"); here ``dim_ordering="th"`` is accepted for API parity
and handled by transposing at the layer boundary (the interior always runs
channels-last).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import StatelessLayer

IntOrPair = Union[int, Sequence[int]]


def _tuple(v: IntOrPair, n: int) -> Tuple[int, ...]:
    if isinstance(v, int):
        return (v,) * n
    t = tuple(v)
    assert len(t) == n, f"expected {n} values, got {t}"
    return t


def _to_channels_last(x, dim_ordering: str, spatial: int):
    """(B, C, *S) -> (B, *S, C) when dim_ordering='th'."""
    if dim_ordering != "th":
        return x
    perm = (0,) + tuple(range(2, 2 + spatial)) + (1,)
    return jnp.transpose(x, perm)


def _from_channels_last(x, dim_ordering: str, spatial: int):
    if dim_ordering != "th":
        return x
    perm = (0, 1 + spatial) + tuple(range(1, 1 + spatial))
    return jnp.transpose(x, perm)


def _dim_numbers(spatial: int):
    s = "".join("DHW"[-spatial:])
    return (f"N{s}C", f"{s}IO", f"N{s}C")


class ConvND(StatelessLayer):
    """Shared N-dimensional convolution machinery (channels-last interior)."""

    spatial: int = 2

    def __init__(self, nb_filter: int, kernel_size: Sequence[int],
                 activation=None, border_mode: str = "valid",
                 subsample: IntOrPair = 1, dilation: IntOrPair = 1,
                 init="glorot_uniform", bias: bool = True,
                 dim_ordering: str = "tf", w_regularizer=None,
                 b_regularizer=None, dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = _tuple(kernel_size, self.spatial)
        self.activation = activations.get(activation)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode}")
        self.border_mode = border_mode.upper()
        self.strides = _tuple(subsample, self.spatial)
        self.dilation = _tuple(dilation, self.spatial)
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.dim_ordering = dim_ordering
        self.dtype = dtype
        from analytics_zoo_tpu.nn import regularizers as _reg
        self.w_regularizer = _reg.get(w_regularizer)
        self.b_regularizer = _reg.get(b_regularizer)

    def _in_channels(self, input_shape) -> int:
        return (input_shape[1] if self.dim_ordering == "th"
                else input_shape[-1])

    def build_params(self, rng, input_shape):
        in_ch = self._in_channels(input_shape)
        params = {"kernel": self.initializer(
            rng, self.kernel_size + (in_ch, self.nb_filter), self.dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.nb_filter,), self.dtype)
        return params

    def _convolve(self, params, x):
        return lax.conv_general_dilated(
            x, params["kernel"], window_strides=self.strides,
            padding=self.border_mode, rhs_dilation=self.dilation,
            dimension_numbers=_dim_numbers(self.spatial))

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        y = self._convolve(params, x)
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, self.spatial)

    def regularization_loss(self, params):
        loss = 0.0
        if self.w_regularizer is not None:
            loss = loss + self.w_regularizer(params["kernel"])
        if self.b_regularizer is not None and self.use_bias:
            loss = loss + self.b_regularizer(params["bias"])
        return loss


class Convolution1D(ConvND):
    """1D convolution over (B, L, C).  Reference: Convolution1D.scala."""

    spatial = 1

    def __init__(self, nb_filter: int, filter_length: int, **kw):
        super().__init__(nb_filter, (filter_length,), **kw)


class Convolution2D(ConvND):
    """2D convolution.  Reference: Convolution2D.scala."""

    spatial = 2

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int, **kw):
        super().__init__(nb_filter, (nb_row, nb_col), **kw)


class SpaceToDepthStemConv(Convolution2D):
    """7x7/stride-2 SAME stem conv computed as a 4x4/stride-1 VALID conv
    over a space-to-depth(2) transform of the input.

    Mathematically identical to ``Convolution2D(O, 7, 7, subsample=2,
    border_mode='same')`` (the parameter keeps the canonical (7,7,C,O)
    shape, so checkpoints/importers are unaffected), but maps far better
    onto the MXU: 3 input channels pad to the 8-lane minimum and waste
    >60% of the systolic array, while the transformed conv works on
    4C=12 channels with a quarter the spatial positions.  The classic
    TPU ResNet trick (MLPerf space-to-depth stem).
    """

    def __init__(self, nb_filter: int, **kw):
        kw.setdefault("border_mode", "same")
        kw.setdefault("subsample", (2, 2))
        super().__init__(nb_filter, 7, 7, **kw)
        if (self.strides != (2, 2) or self.kernel_size != (7, 7)
                or self.border_mode != "SAME"
                or self.dilation != (1, 1)):
            raise ValueError(
                "SpaceToDepthStemConv is exactly the 7x7/stride-2/SAME "
                "undilated stem; use Convolution2D for anything else")

    def _convolve(self, params, x):
        w = params["kernel"]                         # (7, 7, C, O)
        b, h, wd, c = x.shape
        if h % 2 or wd % 2:
            return super()._convolve(params, x)      # odd sizes: plain conv
        # pad kernel to 8x8 at the top/left, then fold each 2x2 phase
        # into channels: w2[a, b, (u, v, c), o] = w8[2a+u, 2b+v, c, o]
        w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        o = w.shape[-1]
        w2 = (w8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
              .reshape(4, 4, 4 * c, o))
        # SAME padding for k=8/s=2 after the +1 kernel shift is (3, 3)
        xp = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
        hp, wp = h + 6, wd + 6
        x2 = (xp.reshape(b, hp // 2, 2, wp // 2, 2, c)
              .transpose(0, 1, 3, 2, 4, 5)
              .reshape(b, hp // 2, wp // 2, 4 * c))
        return lax.conv_general_dilated(
            x2, w2, window_strides=(1, 1), padding="VALID",
            dimension_numbers=_dim_numbers(2))


class Convolution3D(ConvND):
    """3D convolution.  Reference: Convolution3D.scala."""

    spatial = 3

    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, **kw):
        super().__init__(nb_filter, (kernel_dim1, kernel_dim2, kernel_dim3),
                         **kw)


class AtrousConvolution1D(Convolution1D):
    """Dilated 1D conv.  Reference: AtrousConvolution1D.scala."""

    def __init__(self, nb_filter: int, filter_length: int,
                 atrous_rate: int = 1, **kw):
        kw.setdefault("dilation", atrous_rate)
        super().__init__(nb_filter, filter_length, **kw)


class AtrousConvolution2D(Convolution2D):
    """Dilated 2D conv.  Reference: AtrousConvolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate: IntOrPair = (1, 1), **kw):
        kw.setdefault("dilation", atrous_rate)
        super().__init__(nb_filter, nb_row, nb_col, **kw)


class SeparableConvolution2D(StatelessLayer):
    """Depthwise conv followed by 1x1 pointwise conv.

    Reference: SeparableConvolution2D.scala.  The depthwise stage uses
    ``feature_group_count`` so XLA emits one grouped conv.
    """

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, border_mode: str = "valid",
                 subsample: IntOrPair = (1, 1), depth_multiplier: int = 1,
                 init="glorot_uniform", bias: bool = True,
                 dim_ordering: str = "tf", dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.activation = activations.get(activation)
        self.border_mode = border_mode.upper()
        self.strides = _tuple(subsample, 2)
        self.depth_multiplier = depth_multiplier
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.dim_ordering = dim_ordering
        self.dtype = dtype

    def build_params(self, rng, input_shape):
        in_ch = (input_shape[1] if self.dim_ordering == "th"
                 else input_shape[-1])
        self.in_ch = in_ch
        k1, k2 = jax.random.split(rng)
        params = {
            "depthwise": self.initializer(
                k1, self.kernel_size + (1, in_ch * self.depth_multiplier),
                self.dtype),
            "pointwise": self.initializer(
                k2, (1, 1, in_ch * self.depth_multiplier, self.nb_filter),
                self.dtype),
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.nb_filter,), self.dtype)
        return params

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        in_ch = x.shape[-1]
        dn = _dim_numbers(2)
        y = lax.conv_general_dilated(
            x, params["depthwise"], window_strides=self.strides,
            padding=self.border_mode, dimension_numbers=dn,
            feature_group_count=in_ch)
        y = lax.conv_general_dilated(
            y, params["pointwise"], window_strides=(1, 1),
            padding="VALID", dimension_numbers=dn)
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)


class Deconvolution2D(StatelessLayer):
    """Transposed 2D convolution.  Reference: Deconvolution2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample: IntOrPair = (1, 1),
                 border_mode: str = "valid", init="glorot_uniform",
                 bias: bool = True, dim_ordering: str = "tf",
                 dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.activation = activations.get(activation)
        self.strides = _tuple(subsample, 2)
        self.border_mode = border_mode.upper()
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.dim_ordering = dim_ordering
        self.dtype = dtype

    def build_params(self, rng, input_shape):
        in_ch = (input_shape[1] if self.dim_ordering == "th"
                 else input_shape[-1])
        params = {"kernel": self.initializer(
            rng, self.kernel_size + (in_ch, self.nb_filter), self.dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.nb_filter,), self.dtype)
        return params

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        y = lax.conv_transpose(
            x, params["kernel"], strides=self.strides,
            padding=self.border_mode, dimension_numbers=_dim_numbers(2))
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)


class LocallyConnected1D(StatelessLayer):
    """Conv1D with *unshared* weights per output position.

    Reference: LocallyConnected1D.scala.  Implemented as a patch-extract +
    batched matmul (one einsum → MXU) rather than a per-position loop.
    """

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, border_mode: str = "valid",
                 init="glorot_uniform", bias: bool = True,
                 dtype=jnp.float32, **kw):
        super().__init__(**kw)
        if border_mode != "valid":
            raise ValueError("LocallyConnected1D supports only border_mode='valid'")
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activations.get(activation)
        self.stride = subsample_length
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.dtype = dtype

    def _out_len(self, length: int) -> int:
        return (length - self.filter_length) // self.stride + 1

    def build_params(self, rng, input_shape):
        _, length, in_ch = input_shape
        out_len = self._out_len(length)
        params = {"kernel": self.initializer(
            rng, (out_len, self.filter_length * in_ch, self.nb_filter),
            self.dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((out_len, self.nb_filter), self.dtype)
        return params

    def forward(self, params, x, training=False, rng=None):
        b, length, in_ch = x.shape
        out_len = self._out_len(length)
        idx = (jnp.arange(out_len)[:, None] * self.stride
               + jnp.arange(self.filter_length)[None, :])
        patches = x[:, idx, :].reshape(b, out_len, -1)     # (B, O, K*C)
        y = jnp.einsum("bok,okf->bof", patches, params["kernel"])
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return y


class LocallyConnected2D(StatelessLayer):
    """Conv2D with unshared weights.  Reference: LocallyConnected2D.scala."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample: IntOrPair = (1, 1),
                 border_mode: str = "valid", init="glorot_uniform",
                 bias: bool = True, dim_ordering: str = "tf",
                 dtype=jnp.float32, **kw):
        super().__init__(**kw)
        if border_mode != "valid":
            raise ValueError("LocallyConnected2D supports only border_mode='valid'")
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.activation = activations.get(activation)
        self.strides = _tuple(subsample, 2)
        self.initializer = initializers.get(init)
        self.use_bias = bias
        self.dim_ordering = dim_ordering
        self.dtype = dtype

    def _out_hw(self, h: int, w: int) -> Tuple[int, int]:
        kh, kw_ = self.kernel_size
        sh, sw = self.strides
        return (h - kh) // sh + 1, (w - kw_) // sw + 1

    def build_params(self, rng, input_shape):
        if self.dim_ordering == "th":
            _, in_ch, h, w = input_shape
        else:
            _, h, w, in_ch = input_shape
        oh, ow = self._out_hw(h, w)
        kh, kw_ = self.kernel_size
        params = {"kernel": self.initializer(
            rng, (oh * ow, kh * kw_ * in_ch, self.nb_filter), self.dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((oh, ow, self.nb_filter), self.dtype)
        return params

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        b, h, w, c = x.shape
        kh, kw_ = self.kernel_size
        sh, sw = self.strides
        oh, ow = self._out_hw(h, w)
        ridx = jnp.arange(oh)[:, None] * sh + jnp.arange(kh)[None, :]
        cidx = jnp.arange(ow)[:, None] * sw + jnp.arange(kw_)[None, :]
        # (B, oh, kh, W, C) -> (B, oh, kh, ow, kw, C)
        patches = x[:, ridx, :, :][:, :, :, cidx, :]
        patches = jnp.transpose(patches, (0, 1, 3, 2, 4, 5)).reshape(
            b, oh * ow, kh * kw_ * c)
        y = jnp.einsum("bok,okf->bof", patches, params["kernel"]).reshape(
            b, oh, ow, self.nb_filter)
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)


class ZeroPaddingND(StatelessLayer):
    spatial = 2

    def __init__(self, padding, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.padding = padding
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        pad = [(0, 0)] + [(p, p) if isinstance(p, int) else tuple(p)
                          for p in self.padding] + [(0, 0)]
        y = jnp.pad(x, pad)
        return _from_channels_last(y, self.dim_ordering, self.spatial)


class ZeroPadding1D(ZeroPaddingND):
    spatial = 1

    def __init__(self, padding: int = 1, **kw):
        super().__init__([padding], **kw)


class ZeroPadding2D(ZeroPaddingND):
    spatial = 2

    def __init__(self, padding: IntOrPair = (1, 1), **kw):
        p = _tuple(padding, 2)
        super().__init__(list(p), **kw)


class ZeroPadding3D(ZeroPaddingND):
    spatial = 3

    def __init__(self, padding: IntOrPair = (1, 1, 1), **kw):
        p = _tuple(padding, 3)
        super().__init__(list(p), **kw)


class CroppingND(StatelessLayer):
    spatial = 2

    def __init__(self, cropping, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.cropping = cropping
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        idx = [slice(None)]
        for (lo, hi) in self.cropping:
            idx.append(slice(lo, x.shape[len(idx)] - hi or None))
        idx.append(slice(None))
        y = x[tuple(idx)]
        return _from_channels_last(y, self.dim_ordering, self.spatial)


class Cropping1D(CroppingND):
    spatial = 1

    def __init__(self, cropping=(1, 1), **kw):
        super().__init__([tuple(cropping)], **kw)


class Cropping2D(CroppingND):
    spatial = 2

    def __init__(self, heightCrop=(0, 0), widthCrop=(0, 0), **kw):
        super().__init__([tuple(heightCrop), tuple(widthCrop)], **kw)


class Cropping3D(CroppingND):
    spatial = 3

    def __init__(self, dim1Crop=(1, 1), dim2Crop=(1, 1), dim3Crop=(1, 1), **kw):
        super().__init__([tuple(dim1Crop), tuple(dim2Crop), tuple(dim3Crop)],
                         **kw)


class UpSamplingND(StatelessLayer):
    spatial = 2

    def __init__(self, size, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.size = size
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial)
        for axis, s in enumerate(self.size, start=1):
            x = jnp.repeat(x, s, axis=axis)
        return _from_channels_last(x, self.dim_ordering, self.spatial)


class UpSampling1D(UpSamplingND):
    spatial = 1

    def __init__(self, length: int = 2, **kw):
        super().__init__((length,), **kw)


class UpSampling2D(UpSamplingND):
    spatial = 2

    def __init__(self, size: IntOrPair = (2, 2), **kw):
        super().__init__(_tuple(size, 2), **kw)


class UpSampling3D(UpSamplingND):
    spatial = 3

    def __init__(self, size: IntOrPair = (2, 2, 2), **kw):
        super().__init__(_tuple(size, 3), **kw)


# Keras-2 style aliases (reference keras2 package exposes Conv1D/Conv2D names)
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D


class ResizeBilinear(StatelessLayer):
    """Bilinear spatial resize (reference api/keras/layers/
    ResizeBilinear.scala wrapping BigDL ResizeBilinear).

    ``align_corners=True`` maps corner pixels exactly (the BigDL/TF-v1
    convention); ``False`` uses the half-pixel convention of
    ``jax.image.resize``.
    """

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, dim_ordering: str = "tf",
                 **kw):
        super().__init__(**kw)
        self.output_height = output_height
        self.output_width = output_width
        self.align_corners = align_corners
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        oh, ow = self.output_height, self.output_width
        if not self.align_corners:
            y = jax.image.resize(x, (x.shape[0], oh, ow, x.shape[3]),
                                 method="bilinear")
        else:
            ih, iw = x.shape[1], x.shape[2]
            ys = jnp.linspace(0.0, ih - 1.0, oh)
            xs = jnp.linspace(0.0, iw - 1.0, ow)
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, ih - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, iw - 1)
            y1 = jnp.minimum(y0 + 1, ih - 1)
            x1 = jnp.minimum(x0 + 1, iw - 1)
            wy = (ys - y0).reshape(1, oh, 1, 1)
            wx = (xs - x0).reshape(1, 1, ow, 1)
            g = lambda yy, xx: x[:, yy][:, :, xx]
            y = ((1 - wy) * (1 - wx) * g(y0, x0)
                 + (1 - wy) * wx * g(y0, x1)
                 + wy * (1 - wx) * g(y1, x0)
                 + wy * wx * g(y1, x1))
        return _from_channels_last(y, self.dim_ordering, 2)


class ShareConvolution2D(Convolution2D):
    """API-parity alias for the reference's ShareConvolution2D
    (ShareConvolution.scala shares workspace buffers across JVM threads —
    a memory trick with no TPU analogue: XLA owns buffer reuse, and conv
    weights are a single HBM allocation under jit already)."""
