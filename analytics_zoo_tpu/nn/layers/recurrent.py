"""Recurrent layers: SimpleRNN, LSTM, GRU, Bidirectional, TimeDistributed.

Reference capability: api/keras/layers/{SimpleRNN,LSTM,GRU,Bidirectional,
TimeDistributed}.scala + InternalRecurrent.scala.

TPU-first design: the time loop is a single ``lax.scan`` — XLA compiles it
to one fused loop on-device (no per-step dispatch); the input projection
``x @ W`` for ALL timesteps is hoisted out of the scan as one big MXU
matmul (batch*time, features), so only the small recurrent matmul lives in
the loop.  Gate order follows Keras (i, f, c, o / z, r, h) so golden tests
against tf.keras pass weight-for-weight.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import Layer, StatelessLayer


class RNNBase(StatelessLayer):
    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences: bool = False,
                 go_backwards: bool = False, init="glorot_uniform",
                 inner_init="orthogonal", **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activations.get(activation)
        self.inner_activation = activations.get(inner_activation)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.initializer = initializers.get(init)
        self.inner_initializer = initializers.get(inner_init)

    num_gates = 1

    def build_params(self, rng, input_shape):
        f = input_shape[-1]
        h = self.output_dim
        k1, k2 = jax.random.split(rng)
        return {
            "kernel": self.initializer(k1, (f, self.num_gates * h), jnp.float32),
            "recurrent": self.inner_initializer(
                k2, (h, self.num_gates * h), jnp.float32),
            "bias": self._init_bias(h),
        }

    def _init_bias(self, h):
        return jnp.zeros((self.num_gates * h,), jnp.float32)

    def _step(self, params, carry, zx):
        """One timestep; ``zx`` is the precomputed input projection."""
        raise NotImplementedError

    def _init_carry(self, batch):
        return jnp.zeros((batch, self.output_dim), jnp.float32)

    def run(self, params, x, initial_carry=None, return_state: bool = False):
        """Scan over time with an optional initial carry — the seq2seq
        decoder hook (models/seq2seq.py feeds bridge states here)."""
        b, t, f = x.shape
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        # hoist the input projection out of the scan: one MXU matmul
        zx = (x.reshape(b * t, f) @ params["kernel"] + params["bias"]) \
            .reshape(b, t, -1).swapaxes(0, 1)  # (T, B, G*H)
        carry = (initial_carry if initial_carry is not None
                 else self._init_carry(b))

        def step(carry, z):
            return self._step(params, carry, z)

        last, ys = jax.lax.scan(step, carry, zx)
        out = ys.swapaxes(0, 1) if self.return_sequences \
            else self._carry_output(last)
        if return_state:
            return out, last
        return out

    def forward(self, params, x, training=False, rng=None):
        return self.run(params, x)

    def _carry_output(self, carry):
        return carry


class SimpleRNN(RNNBase):
    """h' = act(x W + h U + b)."""

    num_gates = 1

    def __init__(self, output_dim, activation="tanh", **kw):
        kw.pop("inner_activation", None)
        super().__init__(output_dim, activation=activation, **kw)

    def _step(self, params, h, z):
        h_new = self.activation(z + h @ params["recurrent"])
        return h_new, h_new


class LSTM(RNNBase):
    """Keras-v1 LSTM, gate order (i, f, c, o); unit forget bias."""

    num_gates = 4

    def _init_bias(self, h):
        # unit forget-gate bias (standard Keras trick for trainability)
        b = jnp.zeros((4 * h,), jnp.float32)
        return b.at[h:2 * h].set(1.0)

    def _init_carry(self, batch):
        z = jnp.zeros((batch, self.output_dim), jnp.float32)
        return (z, z)  # (h, c)

    def _step(self, params, carry, z):
        h_prev, c_prev = carry
        h = self.output_dim
        z = z + h_prev @ params["recurrent"]
        i = self.inner_activation(z[:, :h])
        f = self.inner_activation(z[:, h:2 * h])
        g = self.activation(z[:, 2 * h:3 * h])
        o = self.inner_activation(z[:, 3 * h:])
        c = f * c_prev + i * g
        h_new = o * self.activation(c)
        return (h_new, c), h_new

    def _carry_output(self, carry):
        return carry[0]


class GRU(RNNBase):
    """Keras-v1 GRU, gate order (z, r, h)."""

    num_gates = 3

    def _step(self, params, h_prev, zx):
        h = self.output_dim
        rec = params["recurrent"]
        zr = zx[:, :2 * h] + h_prev @ rec[:, :2 * h]
        zg = self.inner_activation(zr[:, :h])
        rg = self.inner_activation(zr[:, h:])
        hh = self.activation(zx[:, 2 * h:] + (rg * h_prev) @ rec[:, 2 * h:])
        h_new = zg * h_prev + (1.0 - zg) * hh
        return h_new, h_new


class Highway(StatelessLayer):
    """Highway layer (reference api/keras/layers/Highway.scala):
    y = t * act(x W_h) + (1 - t) * x, t = sigmoid(x W_t)."""

    def __init__(self, activation="tanh", init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.activation = activations.get(activation)
        self.initializer = initializers.get(init)

    def build_params(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        return {"kernel": self.initializer(k1, (d, d), jnp.float32),
                "bias": jnp.zeros((d,), jnp.float32),
                "t_kernel": self.initializer(k2, (d, d), jnp.float32),
                # negative transform bias: start close to identity
                "t_bias": jnp.full((d,), -2.0, jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        t = jax.nn.sigmoid(x @ params["t_kernel"] + params["t_bias"])
        h = self.activation(x @ params["kernel"] + params["bias"])
        return t * h + (1.0 - t) * x


class Bidirectional(Layer):
    """Run a recurrent layer forwards and backwards and merge
    (reference api/keras/layers/Bidirectional.scala)."""

    def __init__(self, layer: RNNBase, merge_mode: str = "concat", **kw):
        super().__init__(**kw)
        import copy

        self.fwd = layer
        self.bwd = copy.deepcopy(layer)
        self.bwd.name = layer.name + "_reverse"
        self.bwd.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        pf, sf = self.fwd.init(k1, input_shape)
        pb, sb = self.bwd.init(k2, input_shape)
        return {"fwd": pf, "bwd": pb}, {"fwd": sf, "bwd": sb}

    def call(self, params, state, x, training=False, rng=None):
        yf, sf = self.fwd.call(params["fwd"], state.get("fwd", {}), x,
                               training=training, rng=rng)
        yb, sb = self.bwd.call(params["bwd"], state.get("bwd", {}), x,
                               training=training, rng=rng)
        if self.fwd.return_sequences:
            yb = jnp.flip(yb, axis=1)  # re-align timesteps
        m = self.merge_mode
        if m == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif m == "sum":
            y = yf + yb
        elif m == "mul":
            y = yf * yb
        elif m in ("ave", "average"):
            y = (yf + yb) / 2.0
        else:
            raise ValueError(f"unknown merge_mode {m!r}")
        return y, {"fwd": sf, "bwd": sb}


class TimeDistributed(Layer):
    """Apply an inner layer to every timestep
    (reference api/keras/layers/TimeDistributed + InternalTimeDistributed).

    Implemented by folding time into the batch dim — XLA sees one big
    batched op instead of T small ones."""

    def __init__(self, layer: Layer, **kw):
        super().__init__(**kw)
        self.inner = layer

    def build(self, rng, input_shape):
        b, t = input_shape[0], input_shape[1]
        inner_shape = (b * t,) + tuple(input_shape[2:])
        return self.inner.init(rng, inner_shape)

    def call(self, params, state, x, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y, ns = self.inner.call(params, state, flat, training=training, rng=rng)
        return y.reshape((b, t) + y.shape[1:]), ns


class ConvLSTMND(StatelessLayer):
    """Convolutional LSTM over (B, T, spatial..., C) channels-last
    (reference api/keras/layers/ConvLSTM2D.scala / ConvLSTM3D.scala).

    TPU-first: the input-side convolution for ALL timesteps is hoisted out
    of the scan as one batched conv over (B*T, ...) — only the recurrent
    conv on the carry lives inside the ``lax.scan`` loop, mirroring the
    hoisted input projection of the dense RNNs above.  Gate order (i, f,
    c, o); SAME padding keeps the spatial shape step-invariant (the
    reference likewise pads to preserve shape).
    """

    spatial = 2

    def __init__(self, nb_filter: int, kernel_size, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 go_backwards=False, border_mode: str = "same",
                 subsample=1, init="glorot_uniform",
                 inner_init="orthogonal", **kw):
        super().__init__(**kw)
        if border_mode != "same":
            raise ValueError("ConvLSTM requires border_mode='same' (the "
                             "carry must keep a step-invariant shape)")
        self.nb_filter = nb_filter
        ks = (kernel_size,) * self.spatial if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.kernel_size = ks
        self.strides = (subsample,) * self.spatial \
            if isinstance(subsample, int) else tuple(subsample)
        self.activation = activations.get(activation)
        self.inner_activation = activations.get(inner_activation)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.initializer = initializers.get(init)
        self.inner_initializer = initializers.get(inner_init)

    def _dn(self):
        if self.spatial == 2:
            return ("NHWC", "HWIO", "NHWC")
        return ("NDHWC", "DHWIO", "NDHWC")

    def build_params(self, rng, input_shape):
        cin = input_shape[-1]
        f = self.nb_filter
        k1, k2 = jax.random.split(rng)
        bias = jnp.zeros((4 * f,), jnp.float32)
        bias = bias.at[f:2 * f].set(1.0)      # unit forget gate
        return {
            "kernel": self.initializer(
                k1, self.kernel_size + (cin, 4 * f), jnp.float32),
            "recurrent": self.inner_initializer(
                k2, self.kernel_size + (f, 4 * f), jnp.float32),
            "bias": bias,
        }

    def forward(self, params, x, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        f = self.nb_filter
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        dn = jax.lax.conv_dimension_numbers(
            x.shape[1:], params["kernel"].shape, self._dn())
        # hoisted input conv for all timesteps: (B*T, spatial..., 4F)
        zx = jax.lax.conv_general_dilated(
            x.reshape((b * t,) + x.shape[2:]), params["kernel"],
            window_strides=self.strides, padding="SAME",
            dimension_numbers=dn) + params["bias"]
        zx = zx.reshape((b, t) + zx.shape[1:]).swapaxes(0, 1)  # (T, B, ...)
        spatial_shape = zx.shape[2:-1]
        h0 = jnp.zeros((b,) + spatial_shape + (f,), jnp.float32)
        rec_dn = jax.lax.conv_dimension_numbers(
            h0.shape, params["recurrent"].shape, self._dn())

        def step(carry, z):
            h_prev, c_prev = carry
            z = z + jax.lax.conv_general_dilated(
                h_prev, params["recurrent"],
                window_strides=(1,) * self.spatial, padding="SAME",
                dimension_numbers=rec_dn)
            i = self.inner_activation(z[..., :f])
            fg = self.inner_activation(z[..., f:2 * f])
            g = self.activation(z[..., 2 * f:3 * f])
            o = self.inner_activation(z[..., 3 * f:])
            c = fg * c_prev + i * g
            h = o * self.activation(c)
            return (h, c), h

        (h_last, _), ys = jax.lax.scan(step, (h0, h0), zx)
        return ys.swapaxes(0, 1) if self.return_sequences else h_last


class ConvLSTM2D(ConvLSTMND):
    """Reference ConvLSTM2D.scala — input (B, T, H, W, C)."""

    spatial = 2


class ConvLSTM3D(ConvLSTMND):
    """Reference ConvLSTM3D.scala — input (B, T, D, H, W, C)."""

    spatial = 3
