"""Merge layers: concat/sum/mul/ave/max/min/dot over multiple inputs.

Reference capability: api/keras/layers/Merge.scala and keras2's
Maximum/Minimum/Average/... layers.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from analytics_zoo_tpu.nn.module import StatelessLayer


class Merge(StatelessLayer):
    """Merge a list of inputs. ``mode``: concat|sum|mul|ave|max|min|dot|cos."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1, **kw):
        super().__init__(**kw)
        self.mode = mode.lower()
        self.concat_axis = concat_axis

    def forward(self, params, *inputs, training=False, rng=None):
        m = self.mode
        if m == "concat":
            return jnp.concatenate(inputs, axis=self.concat_axis)
        if m == "sum":
            return sum(inputs[1:], inputs[0])
        if m == "mul":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if m in ("ave", "average"):
            return sum(inputs[1:], inputs[0]) / len(inputs)
        if m == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "dot":
            a, b = inputs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if m == "cos":
            a, b = inputs
            na = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-12)
            nb = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-12)
            return jnp.sum(na * nb, axis=-1, keepdims=True)
        raise ValueError(f"unknown merge mode {self.mode!r}")


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    """Functional merge over autograd Variables (reference api parity)."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(*inputs)


class Concatenate(Merge):
    def __init__(self, axis: int = -1, **kw):
        super().__init__(mode="concat", concat_axis=axis, **kw)


class Add(Merge):
    def __init__(self, **kw):
        super().__init__(mode="sum", **kw)


class Multiply(Merge):
    def __init__(self, **kw):
        super().__init__(mode="mul", **kw)


class Average(Merge):
    def __init__(self, **kw):
        super().__init__(mode="ave", **kw)


class Maximum(Merge):
    def __init__(self, **kw):
        super().__init__(mode="max", **kw)


class Minimum(Merge):
    def __init__(self, **kw):
        super().__init__(mode="min", **kw)
