"""Core layers: Dense, Dropout, Flatten, Activation, Reshape, Permute, ...

Reference capability: api/keras/layers/{Dense,Dropout,Flatten,Activation,
Reshape,Permute,RepeatVector,Masking}.scala.  Design is TPU-first: Dense is
a single ``jnp.dot`` (lowers to MXU), dropout uses threaded PRNG keys, and
everything is shape-polymorphic over leading dims so the same layer works
for 2D and sequence inputs (matching Keras semantics of operating on the
last axis).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.nn import activations, initializers
from analytics_zoo_tpu.nn.module import Layer, StatelessLayer


class Dense(StatelessLayer):
    """Fully connected layer: ``y = act(x @ W + b)``.

    Operates on the last axis (Keras semantics — a 3D input is treated as a
    batch of sequences and hits the MXU as one batched matmul).
    Reference: api/keras/layers/Dense (via KerasUtils string lowering).
    """

    def __init__(self, output_dim: int, activation=None, use_bias: bool = True,
                 init="glorot_uniform", w_regularizer=None, b_regularizer=None,
                 dtype=jnp.float32, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.initializer = initializers.get(init)
        self.dtype = dtype
        from analytics_zoo_tpu.nn import regularizers as _reg
        self.w_regularizer = _reg.get(w_regularizer)
        self.b_regularizer = _reg.get(b_regularizer)

    def build_params(self, rng, input_shape):
        in_dim = input_shape[-1]
        k_w, _ = jax.random.split(rng)
        params = {"kernel": self.initializer(k_w, (in_dim, self.output_dim), self.dtype)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.output_dim,), self.dtype)
        return params

    def forward(self, params, x, training=False, rng=None):
        kernel = params["kernel"]
        if isinstance(kernel, dict):
            # quantized serving leaf ({"q"|"q4", "scale"}, see
            # deploy.quantize_pytree): dequant fused into the matmul —
            # the Pallas kernel on TPU (ops/dequant_matmul.py), so the
            # kernel never materialises at f32 in HBM
            from analytics_zoo_tpu.ops.dequant_matmul import dequant_matmul

            if "q4" in kernel:
                y = dequant_matmul(x, kernel["q4"], kernel["scale"],
                                   bits=4, rows=x.shape[-1])
            else:
                y = dequant_matmul(x, kernel["q"], kernel["scale"])
        else:
            y = jnp.dot(x, kernel)
        if self.use_bias:
            y = y + params["bias"]
        if self.activation is not None:
            y = self.activation(y)
        return y

    def regularization_loss(self, params):
        loss = 0.0
        if self.w_regularizer is not None:
            loss = loss + self.w_regularizer(params["kernel"])
        if self.b_regularizer is not None and self.use_bias:
            loss = loss + self.b_regularizer(params["bias"])
        return loss


class Activation(StatelessLayer):
    def __init__(self, activation, **kw):
        super().__init__(**kw)
        self.activation = activations.get(activation)

    def forward(self, params, x, training=False, rng=None):
        return self.activation(x)


class Dropout(StatelessLayer):
    """Inverted dropout; identity at inference.

    Reference: api/keras/layers/Dropout.  Uses an explicit PRNG key threaded
    by the container — no global RNG state (XLA-friendly determinism).
    """

    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.rate = float(p)

    def forward(self, params, x, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"Dropout {self.name} needs an rng when training")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class Flatten(StatelessLayer):
    """Flatten all dims after the batch dim."""

    def forward(self, params, x, training=False, rng=None):
        return x.reshape(x.shape[0], -1)


class Reshape(StatelessLayer):
    """Reshape non-batch dims to ``target_shape`` (one dim may be -1)."""

    def __init__(self, target_shape: Sequence[int], **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def forward(self, params, x, training=False, rng=None):
        return x.reshape((x.shape[0],) + self.target_shape)


class Permute(StatelessLayer):
    """Permute non-batch dims; ``dims`` is 1-indexed like Keras."""

    def __init__(self, dims: Sequence[int], **kw):
        super().__init__(**kw)
        self.dims = tuple(dims)

    def forward(self, params, x, training=False, rng=None):
        return jnp.transpose(x, (0,) + self.dims)


class RepeatVector(StatelessLayer):
    """(B, F) -> (B, n, F)."""

    def __init__(self, n: int, **kw):
        super().__init__(**kw)
        self.n = n

    def forward(self, params, x, training=False, rng=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1)


class Masking(StatelessLayer):
    """Zero out timesteps equal to ``mask_value`` (soft masking)."""

    def __init__(self, mask_value: float = 0.0, **kw):
        super().__init__(**kw)
        self.mask_value = mask_value

    def forward(self, params, x, training=False, rng=None):
        mask = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(mask, x, 0.0)


class Lambda(StatelessLayer):
    """Wrap an arbitrary jax function as a layer.

    Reference: api/autograd/Lambda.scala.  The function must be traceable.
    """

    def __init__(self, fn, **kw):
        super().__init__(**kw)
        self.fn = fn

    def forward(self, params, *inputs, training=False, rng=None):
        return self.fn(*inputs)


class InputLayer(StatelessLayer):
    """Identity marker layer (Keras InputLayer parity)."""

    def forward(self, params, x, training=False, rng=None):
        return x


class MaxoutDense(StatelessLayer):
    """Maxout over ``nb_feature`` linear pieces
    (reference api/keras/layers/MaxoutDense.scala):
    y_j = max_k (x W_k + b_k)_j.

    One (in, nb_feature*out) matmul feeds the MXU; the max is a cheap
    fused reduce."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 init="glorot_uniform", bias: bool = True, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.initializer = initializers.get(init)
        self.use_bias = bias

    def build_params(self, rng, input_shape):
        d = input_shape[-1]
        params = {"kernel": self.initializer(
            rng, (d, self.nb_feature * self.output_dim), jnp.float32)}
        if self.use_bias:
            params["bias"] = jnp.zeros(
                (self.nb_feature * self.output_dim,), jnp.float32)
        return params

    def forward(self, params, x, training=False, rng=None):
        y = x @ params["kernel"]
        if self.use_bias:
            y = y + params["bias"]
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2)


class GaussianSampler(StatelessLayer):
    """Reparameterised gaussian sampling for VAEs
    (reference api/keras/layers/GaussianSampler.scala):
    inputs (mean, log_var) -> mean + exp(log_var/2) * eps."""

    def call(self, params, state, mean, log_var=None, training=False,
             rng=None):
        if log_var is None:   # single stacked input [mean, log_var]
            mean, log_var = mean
        if not training or rng is None:
            # deterministic eval: the distribution mean
            return mean, state
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps, state
