"""Normalization layers: BatchNormalization, LRN2D, L2 norm.

Reference capability: api/keras/layers/{BatchNormalization,LRN2D,
WithinChannelLRN2D}.scala.

TPU-first: BatchNorm keeps moving statistics in the layer *state* pytree —
updated functionally (no mutation) so the whole train step stays one pure
jitted program; with data parallelism the batch statistics are computed
per-shard (matching the reference, which normalizes per worker-replica —
InternalDistriOptimizer clones per core).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.nn.module import Layer, StatelessLayer


class BatchNormalization(Layer):
    """Batch normalization over the channel axis.

    Reference: api/keras/layers/BatchNormalization.scala.  ``axis`` follows
    channels-last by default (-1); pass ``dim_ordering='th'``/``axis=1`` for
    channels-first inputs.
    """

    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 beta_init="zero", gamma_init="one", axis: int = -1,
                 dim_ordering: str = "tf", scale: bool = True,
                 center: bool = True, stats_fraction: float = 1.0, **kw):
        """``stats_fraction < 1`` enables ghost-BN: training statistics
        are computed over the leading ``ceil(fraction * B)`` rows of the
        batch (normalization still covers every row).  On TPU the BN
        stats pass is pure HBM bandwidth (the r4 ResNet-50 roofline:
        ~9GB of ~20ms/step is BN traffic, docs/PERFORMANCE.md), so
        reading a quarter of the rows for stats removes most of one of
        BN's three activation passes.  Estimator numerics: subset stats
        are the ghost-BN regularizer (Hoffer et al. 2017) — equal or
        better validation accuracy at batch>=256 in our accuracy leg."""
        super().__init__(**kw)
        self.epsilon = epsilon
        self.momentum = momentum
        self.axis = 1 if dim_ordering == "th" else axis
        self.scale = scale
        self.center = center
        if not 0.0 < stats_fraction <= 1.0:
            raise ValueError(
                f"stats_fraction must be in (0, 1], got {stats_fraction}")
        self.stats_fraction = float(stats_fraction)

    def _dim(self, input_shape) -> int:
        return input_shape[self.axis]

    def build(self, rng, input_shape):
        d = self._dim(input_shape)
        params = {}
        if self.scale:
            params["gamma"] = jnp.ones((d,), jnp.float32)
        if self.center:
            params["beta"] = jnp.zeros((d,), jnp.float32)
        state = {"moving_mean": jnp.zeros((d,), jnp.float32),
                 "moving_var": jnp.ones((d,), jnp.float32)}
        return params, state

    def call(self, params, state, x, training: bool = False, rng=None):
        axis = self.axis % x.ndim
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]

        if training:
            xs = x
            if self.stats_fraction < 1.0 and x.shape[0] > 1:
                n = max(1, int(math.ceil(x.shape[0]
                                         * self.stats_fraction)))
                xs = x[:n]              # ghost-BN: stats from a slice
            mean = jnp.mean(xs, axis=reduce_axes)
            var = jnp.var(xs, axis=reduce_axes)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
                "moving_var": m * state["moving_var"] + (1 - m) * var,
            }
        else:
            mean, var = state["moving_mean"], state["moving_var"]
            new_state = state

        inv = lax.rsqrt(var + self.epsilon)
        if self.scale:
            inv = inv * params["gamma"]
        y = (x - mean.reshape(shape)) * inv.reshape(shape)
        if self.center:
            y = y + params["beta"].reshape(shape)
        return y, new_state


class LayerNorm(StatelessLayer):
    """Layer normalization over the last axis (used by Transformer/BERT —
    reference api/keras/layers/internal InternalLayerNorm)."""

    def __init__(self, epsilon: float = 1e-5, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon

    def build_params(self, rng, input_shape):
        d = input_shape[-1]
        return {"gamma": jnp.ones((d,), jnp.float32),
                "beta": jnp.zeros((d,), jnp.float32)}

    def forward(self, params, x, training=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.epsilon)
        return y * params["gamma"] + params["beta"]


class LRN2D(StatelessLayer):
    """Local response normalization across channels.

    Reference: api/keras/layers/LRN2D.scala (AlexNet-style).
    ``y = x / (k + alpha/n * sum(x^2 over n neighbouring channels))^beta``.
    """

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float = 0.75,
                 n: int = 5, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n
        self.dim_ordering = dim_ordering

    def forward(self, params, x, training=False, rng=None):
        ch_axis = 1 if self.dim_ordering == "th" else -1
        sq = jnp.square(x)
        # Sliding window over channels via pad + reduce_window on that axis.
        half = self.n // 2
        window = [1] * x.ndim
        window[ch_axis] = self.n
        pads = [(0, 0, 0)] * x.ndim
        pads[ch_axis] = (half, self.n - 1 - half, 0)
        summed = lax.reduce_window(
            lax.pad(sq, 0.0, pads), 0.0, lax.add, tuple(window),
            (1,) * x.ndim, "VALID")
        denom = jnp.power(self.k + self.alpha / self.n * summed, self.beta)
        return x / denom


class WithinChannelLRN2D(StatelessLayer):
    """LRN within each channel over a spatial window
    (reference api/keras/layers/WithinChannelLRN2D.scala)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 **kw):
        super().__init__(**kw)
        self.size, self.alpha, self.beta = size, alpha, beta

    def forward(self, params, x, training=False, rng=None):
        # NHWC: window over H, W
        sq = jnp.square(x)
        window = (1, self.size, self.size, 1)
        summed = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1),
                                   "SAME")
        denom = jnp.power(1.0 + self.alpha / (self.size ** 2) * summed,
                          self.beta)
        return x / denom
