from analytics_zoo_tpu.nn.layers.core import (  # noqa: F401
    Activation,
    Dense,
    Dropout,
    Flatten,
    InputLayer,
    Lambda,
    Masking,
    Permute,
    RepeatVector,
    Reshape,
)
from analytics_zoo_tpu.nn.layers.embedding import Embedding, WordEmbedding  # noqa: F401
from analytics_zoo_tpu.nn.layers.recurrent import (  # noqa: F401
    GRU,
    LSTM,
    Bidirectional,
    Highway,
    SimpleRNN,
    TimeDistributed,
)
from analytics_zoo_tpu.nn.layers.merge import (  # noqa: F401
    Add,
    Average,
    Concatenate,
    Maximum,
    Merge,
    Minimum,
    Multiply,
    merge,
)
