"""Row-sharded embedding table: the giant-vocabulary lookup layer.

``ShardedEmbeddingTable`` is the mesh-scale sibling of ``Embedding`` /
``EmbeddingBag`` (same id semantics, same fused-kernel lookup) whose
``table`` param is row-partitioned over the mesh's ``model`` axis by
``parallel.table_sharding.TableShardedStrategy``.  The layer itself is
topology-agnostic:

- its param shape is ALWAYS ``(padded_rows(input_dim), output_dim)``
  (rows rounded up to ``ROW_ALIGN``), so the checkpoint layout is
  identical whether the mesh shards the table 1/2/4/8 ways — that
  invariance is what lets a 2-way snapshot restore onto a 1-way or
  4-way mesh through the plain ``tree_put_global`` reshard path;
- at trace time it consults ``current_table_sharding()`` (published by
  the strategy's ``activate()``): when its own name is listed AND the
  live mesh actually shards its rows, the lookup lowers to the
  local-gather + single-psum exchange (``table_sharding.sharded_bag``);
  otherwise it falls back to the ordinary dense ``embedding_bag`` /
  ``embedding_gather`` lookup — same math, no collective.

The padding rows are inert: initialized, never indexed by valid ids
(vocab ids are ``< input_dim``), and their gradient is exactly zero, so
they cost ``ROW_ALIGN·D·4`` bytes at most and nothing else.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn import initializers
from analytics_zoo_tpu.nn.module import StatelessLayer
from analytics_zoo_tpu.parallel.mode import current_table_sharding
from analytics_zoo_tpu.parallel.table_sharding import (padded_rows,
                                                       resolve_table_ways)


class ShardedEmbeddingTable(StatelessLayer):
    """Integer ids -> dense vectors, shardable row-wise over the model
    mesh axis.

    ``combiner=None`` gives ``Embedding`` semantics: ``(B, ...)`` int
    ids -> ``(B, ..., dim)``.  ``combiner="sum"|"mean"|"sqrtn"`` gives
    ``EmbeddingBag`` semantics: ``(B, n_ids)`` -> ``(B, dim)`` with the
    bag combined in-kernel (``pad_id`` slots excluded).  Either way the
    sharded lowering exchanges only the combined ``(B, D)`` (or the
    gathered ``ids.shape + (D,)``) output via one psum — the table's
    rows never leave their owning shard replicated.
    """

    def __init__(self, input_dim: int, output_dim: int,
                 combiner: Optional[str] = None, init="uniform",
                 pad_id: Optional[int] = None, trainable: bool = True,
                 weights: Optional[np.ndarray] = None,
                 zero_based: bool = True, axis: str = "model",
                 dtype=jnp.float32, **kw):
        super().__init__(**kw)
        if combiner not in (None, "sum", "mean", "sqrtn"):
            raise ValueError(f"combiner must be None|sum|mean|sqrtn, got "
                             f"{combiner!r}")
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.combiner = combiner
        self.initializer = initializers.get(init)
        self.pad_id = pad_id
        self.trainable = trainable
        self.pretrained = weights
        self.zero_based = zero_based
        self.axis = axis
        self.dtype = dtype

    @property
    def table_rows(self) -> int:
        """The stored (ROW_ALIGN-padded) row count."""
        return padded_rows(self.input_dim)

    @property
    def table_nbytes(self) -> int:
        return self.table_rows * self.output_dim * \
            jnp.dtype(self.dtype).itemsize

    def build_params(self, rng, input_shape):
        rows = self.table_rows
        if self.pretrained is not None:
            given = np.asarray(self.pretrained, jnp.dtype(self.dtype).name)
            if given.shape not in ((self.input_dim, self.output_dim),
                                   (rows, self.output_dim)):
                raise ValueError(
                    f"pretrained weights {given.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
            if given.shape[0] < rows:    # pad tail rows with zeros
                given = np.concatenate(
                    [given, np.zeros((rows - given.shape[0],
                                      self.output_dim), given.dtype)])
            table = jnp.asarray(given, self.dtype)
        else:
            table = self.initializer(rng, (rows, self.output_dim),
                                     self.dtype)
        if self.pad_id is not None and 0 <= self.pad_id < rows:
            table = table.at[self.pad_id].set(0.0)
        return {"table": table}

    def _sharding_for_trace(self):
        """(mesh, axis) iff the active strategy shards THIS table on a
        mesh that can actually split its rows; else None."""
        mode = current_table_sharding()
        if mode is None or self.name not in mode.tables:
            return None
        if resolve_table_ways(mode.mesh, mode.axis, self.table_rows) <= 1:
            return None
        return mode.mesh, mode.axis

    def forward(self, params, ids, training=False, rng=None):
        from analytics_zoo_tpu.ops.embedding_bag import (embedding_bag,
                                                         embedding_gather)
        from analytics_zoo_tpu.parallel.table_sharding import (
            sharded_bag, sharded_gather)

        table = params["table"]
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        ids = ids.astype(jnp.int32)
        if not self.zero_based:
            ids = ids - 1
        shard = self._sharding_for_trace()
        if shard is None:                       # dense fallback
            if self.combiner is None:
                return embedding_gather(table, ids)
            return embedding_bag(table, ids, self.combiner, self.pad_id)
        mesh, axis = shard
        if self.combiner is None:
            return sharded_gather(table, ids, mesh=mesh, axis=axis)
        return sharded_bag(table, ids, self.combiner, self.pad_id,
                           mesh=mesh, axis=axis)

    def cached_forward(self, params, ids, cache, *, mesh=None,
                       axis: str = "model"):
        """Serving-side two-tier lookup through a ``parallel.hot_cache.
        HotRowCache``: numpy ids in, numpy vectors out — hot ids resolve
        from the chip-local replica (no psum), cold ids ride one bounded
        sharded program.  Read-only over ``params`` (the cache refresh
        path re-reads authoritative rows; training never calls this)."""
        from analytics_zoo_tpu.parallel.hot_cache import (
            cached_sharded_bag, cached_sharded_gather)

        ids = np.asarray(ids)
        if not self.zero_based:
            ids = ids - 1
        mesh = mesh if mesh is not None else cache.mesh
        if self.combiner is None:
            return cached_sharded_gather(cache, params["table"], ids,
                                         mesh=mesh, axis=axis)
        return cached_sharded_bag(cache, params["table"], ids,
                                  self.combiner, self.pad_id,
                                  mesh=mesh, axis=axis)
