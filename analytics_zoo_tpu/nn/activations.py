"""Activation registry (Keras-name compatible).

Reference capability: api/keras/layers/Activation + the activation strings
accepted by every layer's ``activation=`` arg.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

ActivationFn = Callable[[jnp.ndarray], jnp.ndarray]


def hard_sigmoid(x):
    """Keras-semantics hard sigmoid: clip(0.2x + 0.5, 0, 1).

    (jax.nn.hard_sigmoid uses slope 1/6 — different function; the Keras
    variant is required for golden parity with reference RNN gates.)
    """
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_REGISTRY = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "leaky_relu": jax.nn.leaky_relu,
    "exp": jnp.exp,
    "linear": lambda x: x,
    "identity": lambda x: x,
}


def get(act: Union[str, ActivationFn, None]) -> Optional[ActivationFn]:
    if act is None:
        return None
    if callable(act):
        return act
    key = act.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown activation {act!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
