"""Evaluation metrics.

Reference capability: api/keras/metrics/ — top-1/top-5/sparse/binary/
categorical accuracy, AUC (AUC.scala, 211 LoC), MAE.

Design: a metric is a pair of pure functions so it can run *inside* the
jitted eval step and aggregate across devices with a ``psum``-style sum:

    update(y_true, y_pred) -> stats pytree   (summable across batches/devices)
    finalize(stats)        -> scalar

Accuracy carries (correct, total); AUC carries a fixed-resolution
TP/FP histogram over thresholds (jit-friendly, no sorting of the full
score list on host).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Union

import jax
import jax.numpy as jnp


class Metric:
    name = "metric"

    def update(self, y_true, y_pred, mask=None) -> Any:
        """``mask`` (B,) float 0/1 excludes padded rows (SPMD padding)."""
        raise NotImplementedError

    def finalize(self, stats) -> jnp.ndarray:
        raise NotImplementedError


class Accuracy(Metric):
    """Top-1 accuracy with auto input handling (reference Accuracy +
    SparseCategoricalAccuracy): integer labels vs class scores, or binary
    labels vs single probability."""

    name = "accuracy"

    def __init__(self, zero_based_label: bool = True):
        self.zero_based = zero_based_label

    def update(self, y_true, y_pred, mask=None):
        if y_pred.ndim >= 2 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            labels = y_true.astype(jnp.int32).reshape(y_true.shape[0], -1)[:, 0]
            if not self.zero_based:
                labels = labels - 1
            if y_true.ndim >= 2 and y_true.shape[-1] == y_pred.shape[-1]:
                labels = jnp.argmax(y_true, axis=-1)  # one-hot targets
        else:
            pred = (y_pred.reshape(y_pred.shape[0], -1)[:, 0] > 0.5).astype(jnp.int32)
            labels = y_true.reshape(y_true.shape[0], -1)[:, 0].astype(jnp.int32)
        if mask is None:
            mask = jnp.ones((pred.shape[0],), jnp.float32)
        correct = jnp.sum((pred == labels).astype(jnp.float32) * mask)
        return {"correct": correct, "total": jnp.sum(mask)}

    def finalize(self, stats):
        return stats["correct"] / jnp.maximum(stats["total"], 1.0)


class BinaryAccuracy(Accuracy):
    name = "binary_accuracy"


class CategoricalAccuracy(Accuracy):
    name = "categorical_accuracy"


class SparseCategoricalAccuracy(Accuracy):
    name = "sparse_categorical_accuracy"


class Top5Accuracy(Metric):
    name = "top5_accuracy"

    def __init__(self, zero_based_label: bool = True):
        self.zero_based = zero_based_label

    def update(self, y_true, y_pred, mask=None):
        labels = y_true.astype(jnp.int32).reshape(y_true.shape[0], -1)[:, 0]
        if not self.zero_based:
            labels = labels - 1
        _, top5 = jax.lax.top_k(y_pred, 5)
        hit = jnp.any(top5 == labels[:, None], axis=-1).astype(jnp.float32)
        if mask is None:
            mask = jnp.ones((labels.shape[0],), jnp.float32)
        return {"correct": jnp.sum(hit * mask), "total": jnp.sum(mask)}

    def finalize(self, stats):
        return stats["correct"] / jnp.maximum(stats["total"], 1.0)


class MAE(Metric):
    name = "mae"

    def update(self, y_true, y_pred, mask=None):
        err = jnp.abs(y_pred - y_true).reshape(y_true.shape[0], -1)
        if mask is None:
            mask = jnp.ones((y_true.shape[0],), jnp.float32)
        per_row = err.shape[1]
        return {"abs_sum": jnp.sum(err * mask[:, None]),
                "total": jnp.sum(mask) * per_row}

    def finalize(self, stats):
        return stats["abs_sum"] / jnp.maximum(stats["total"], 1.0)


class Loss(Metric):
    """Wraps the model loss as a metric for eval reporting."""

    name = "loss"

    def __init__(self, loss_fn):
        from analytics_zoo_tpu.nn import objectives
        self.loss_fn = objectives.get(loss_fn)

    def update(self, y_true, y_pred, mask=None):
        n = jnp.asarray(y_true.shape[0], jnp.float32)
        return {"loss_sum": self.loss_fn(y_true, y_pred) * n, "total": n}

    def finalize(self, stats):
        return stats["loss_sum"] / jnp.maximum(stats["total"], 1.0)


class AUC(Metric):
    """Area under the ROC curve via a threshold histogram
    (reference api/keras/metrics/AUC.scala — same bucketed design, which is
    the jit/SPMD-friendly formulation: stats are summable across devices)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds

    def update(self, y_true, y_pred, mask=None):
        scores = y_pred.reshape(y_pred.shape[0], -1)[:, 0]
        labels = y_true.reshape(y_true.shape[0], -1)[:, 0].astype(jnp.float32)
        if mask is None:
            mask = jnp.ones((labels.shape[0],), jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, self.num_thresholds)
        pred_pos = (scores[None, :] >= thresholds[:, None]) * mask[None, :]
        tp = jnp.sum(pred_pos * labels[None, :], axis=1)
        fp = jnp.sum(pred_pos * (1.0 - labels)[None, :], axis=1)
        pos = jnp.sum(labels * mask)
        neg = jnp.sum(mask) - pos
        return {"tp": tp, "fp": fp, "pos": pos, "neg": neg}

    def finalize(self, stats):
        tpr = stats["tp"] / jnp.maximum(stats["pos"], 1.0)
        fpr = stats["fp"] / jnp.maximum(stats["neg"], 1.0)
        # thresholds ascend → fpr/tpr descend; integrate with trapezoids.
        return jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)


_REGISTRY: Dict[str, Callable[[], Metric]] = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "binary_accuracy": BinaryAccuracy,
    "categorical_accuracy": CategoricalAccuracy,
    "sparse_categorical_accuracy": SparseCategoricalAccuracy,
    "top5accuracy": Top5Accuracy,
    "top5_accuracy": Top5Accuracy,
    "mae": MAE,
    "auc": AUC,
}


def get(metric: Union[str, Metric]) -> Metric:
    if isinstance(metric, Metric):
        return metric
    key = metric.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown metric {metric!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]()
