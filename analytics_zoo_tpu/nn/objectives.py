"""Loss functions (Keras-named), pure jax.

Reference capability: api/keras/objectives/ — 15 Keras-named losses
(BinaryCrossEntropy, CategoricalCrossEntropy, SparseCategoricalCrossEntropy,
MeanSquaredError, ..., RankHinge) and ClassNLLCriterion.  All are pure
``fn(y_true, y_pred) -> scalar`` reduced by mean over the batch; every one
is trivially fusable by XLA into the backward pass.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

LossFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]

_EPS = 1e-7


def _align(y_true, y_pred):
    """Match a (B,) target against a (B, 1) prediction (and vice versa) so
    elementwise losses never silently broadcast to (B, B)."""
    y_true = jnp.asarray(y_true)
    if (y_pred.ndim == y_true.ndim + 1 and y_pred.shape[-1] == 1
            and y_pred.shape[:-1] == y_true.shape):
        y_pred = y_pred[..., 0]
    elif (y_true.ndim == y_pred.ndim + 1 and y_true.shape[-1] == 1
            and y_true.shape[:-1] == y_pred.shape):
        y_true = y_true[..., 0]
    return y_true, y_pred


def mean_squared_error(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    return jnp.mean(jnp.square(y_pred - y_true))


def mean_absolute_error(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    return jnp.mean(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    diff = jnp.abs((y_true - y_pred) / jnp.clip(jnp.abs(y_true), _EPS, None))
    return 100.0 * jnp.mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    a = jnp.log(jnp.clip(y_pred, _EPS, None) + 1.0)
    b = jnp.log(jnp.clip(y_true, _EPS, None) + 1.0)
    return jnp.mean(jnp.square(a - b))


def binary_crossentropy(y_true, y_pred):
    """y_pred are probabilities in (0, 1) (post-sigmoid), Keras semantics."""
    y_true, y_pred = _align(y_true, y_pred)
    p = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return -jnp.mean(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log1p(-p))


def binary_crossentropy_with_logits(y_true, logits):
    """Numerically stable BCE on logits (preferred on TPU)."""
    y_true, logits = _align(y_true, logits)
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * y_true + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def categorical_crossentropy(y_true, y_pred):
    """One-hot targets vs probability outputs."""
    p = jnp.clip(y_pred, _EPS, 1.0)
    return -jnp.mean(jnp.sum(y_true * jnp.log(p), axis=-1))


def _sparse_labels(y_true, preds):
    """Integer labels matching preds' leading dims: supports (B,) vs
    (B, C), (B, 1) vs (B, C), and sequence targets (B, T) vs (B, T, C)."""
    labels = y_true.astype(jnp.int32)
    if labels.ndim == preds.ndim:          # trailing singleton
        labels = labels[..., 0]
    if labels.shape != preds.shape[:-1]:
        raise ValueError(
            f"label shape {labels.shape} incompatible with predictions "
            f"{preds.shape}")
    return labels


def sparse_categorical_crossentropy(y_true, y_pred, zero_based_label=True):
    """Integer targets vs PROBABILITY outputs
    (reference SparseCategoricalCrossEntropy, 0/1-based switch).

    Pair logits heads — e.g. the models.image zoo (resnet50/inception/
    mobilenet/vgg16 end in a raw Dense) — with
    ``sparse_categorical_crossentropy_with_logits`` instead: feeding
    logits here clips through the log and the model silently memorizes
    without generalizing (r5 post-mortem in bench_resnet_accuracy)."""
    labels = _sparse_labels(y_true, y_pred)
    if not zero_based_label:
        labels = labels - 1
    p = jnp.clip(y_pred, _EPS, 1.0)
    ll = jnp.take_along_axis(jnp.log(p), labels[..., None], axis=-1)
    return -jnp.mean(ll)


def sparse_categorical_crossentropy_with_logits(y_true, logits):
    """Integer targets vs raw logits (fused log-softmax; stable + fast).
    Sequence targets (B, T) vs (B, T, V) are averaged over all positions."""
    labels = _sparse_labels(y_true, logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -jnp.mean(ll)


def class_nll(y_true, log_probs):
    """NLL on log-probabilities (reference ClassNLLCriterion, 197 LoC)."""
    labels = _sparse_labels(y_true, log_probs)
    ll = jnp.take_along_axis(log_probs, labels[..., None], axis=-1)
    return -jnp.mean(ll)


def kullback_leibler_divergence(y_true, y_pred):
    yt = jnp.clip(y_true, _EPS, 1.0)
    yp = jnp.clip(y_pred, _EPS, 1.0)
    return jnp.mean(jnp.sum(yt * jnp.log(yt / yp), axis=-1))


def poisson(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    return jnp.mean(y_pred - y_true * jnp.log(y_pred + _EPS))


def cosine_proximity(y_true, y_pred):
    yt = y_true / (jnp.linalg.norm(y_true, axis=-1, keepdims=True) + _EPS)
    yp = y_pred / (jnp.linalg.norm(y_pred, axis=-1, keepdims=True) + _EPS)
    return -jnp.mean(jnp.sum(yt * yp, axis=-1))


def hinge(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    y_true, y_pred = _align(y_true, y_pred)
    return jnp.mean(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


def rank_hinge(y_true, y_pred, margin: float = 1.0, mask=None):
    """Pairwise ranking hinge for (pos, neg) interleaved batches
    (reference objectives/RankHinge.scala; used by KNRM/Ranker).

    ``mask`` is an optional per-row validity vector (B,): a pair counts
    only when both its rows are real, so padded rows on a final partial
    batch are excluded exactly instead of approximated.
    """
    pos = y_pred[0::2]
    neg = y_pred[1::2]
    per_pair = jnp.maximum(margin - pos + neg, 0.0)
    if mask is None:
        return jnp.mean(per_pair)
    pair_mask = (mask[0::2] * mask[1::2]).reshape(
        (-1,) + (1,) * (per_pair.ndim - 1))
    denom = jnp.maximum(jnp.sum(pair_mask), 1.0) * (
        per_pair.size / per_pair.shape[0])
    return jnp.sum(per_pair * pair_mask) / denom


# rank_hinge couples rows across the batch — eval must not vmap it per-row.
rank_hinge.batch_structured = True
# accepts mask= for exact padded-row exclusion; pair count for aggregation:
rank_hinge.supports_mask = True
rank_hinge.mask_count = lambda mask: jnp.sum(mask[0::2] * mask[1::2])


_REGISTRY = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "bce": binary_crossentropy,
    "binary_crossentropy_with_logits": binary_crossentropy_with_logits,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_with_logits":
        sparse_categorical_crossentropy_with_logits,
    "class_nll": class_nll,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "rank_hinge": rank_hinge,
}


def get(loss: Union[str, LossFn]) -> LossFn:
    """String → loss lowering (reference KerasUtils.scala:165-167)."""
    if callable(loss):
        return loss
    key = loss.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown loss {loss!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
