"""Weight initializers (Keras-name compatible).

Reference capability: BigDL init methods exposed through the Keras layers'
``init=`` string args (e.g. api/keras/layers/Dense — "glorot_uniform").
Implemented directly over ``jax.nn.initializers``.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

Initializer = Callable[..., jnp.ndarray]

_REGISTRY = {
    "glorot_uniform": jax.nn.initializers.glorot_uniform(),
    "glorot_normal": jax.nn.initializers.glorot_normal(),
    "xavier": jax.nn.initializers.glorot_uniform(),
    "he_uniform": jax.nn.initializers.he_uniform(),
    "he_normal": jax.nn.initializers.he_normal(),
    "lecun_uniform": jax.nn.initializers.lecun_uniform(),
    "lecun_normal": jax.nn.initializers.lecun_normal(),
    "zero": jax.nn.initializers.zeros,
    "zeros": jax.nn.initializers.zeros,
    "one": jax.nn.initializers.ones,
    "ones": jax.nn.initializers.ones,
    "normal": jax.nn.initializers.normal(stddev=0.05),
    "uniform": jax.nn.initializers.uniform(scale=0.05),
    "orthogonal": jax.nn.initializers.orthogonal(),
}


def get(init: Union[str, Initializer]) -> Initializer:
    if callable(init):
        return init
    key = init.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown initializer {init!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
