"""Symbolic autograd DSL: ``Variable`` graph nodes + free-function math.

Reference capability: api/autograd/math.scala:32-363 (``AutoGrad`` free
functions), Variable operator overloading (:365-620), CustomLoss, Lambda.

TPU-native design: a ``Variable`` is a node in a lightweight DAG.  Layer
nodes carry a ``Layer`` (params allocated at ``Model.init``); lambda nodes
carry a pure jax function.  ``Model`` evaluates the DAG inside ``jit`` —
the DAG is *built once in Python* and traced once by XLA, so there is zero
per-step graph overhead.  Gradients come from ``jax.grad`` over the whole
evaluated program (the reference needed an explicit backward graph).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_var_ids = itertools.count()


class Variable:
    """A symbolic tensor in the model DAG."""

    def __init__(self, kind: str, parents: Sequence["Variable"] = (),
                 layer=None, fn: Optional[Callable] = None,
                 shape: Optional[Tuple[Optional[int], ...]] = None,
                 name: Optional[str] = None, dtype=jnp.float32):
        assert kind in ("input", "layer", "lambda", "param")
        self.kind = kind
        self.parents = tuple(parents)
        self.layer = layer
        self.fn = fn
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.id = next(_var_ids)
        self.name = name or f"var_{self.id}"

    # -- arithmetic (reference Variable.scala:365-620) --------------------
    def _binop(self, other, fn, name):
        if isinstance(other, Variable):
            return Variable("lambda", (self, other), fn=fn, name=name)
        const = other
        return Variable("lambda", (self,), fn=lambda a: fn(a, const), name=name)

    def __add__(self, o): return self._binop(o, lambda a, b: a + b, "add")
    def __radd__(self, o): return self._binop(o, lambda a, b: b + a, "radd")
    def __sub__(self, o): return self._binop(o, lambda a, b: a - b, "sub")
    def __rsub__(self, o): return self._binop(o, lambda a, b: b - a, "rsub")
    def __mul__(self, o): return self._binop(o, lambda a, b: a * b, "mul")
    def __rmul__(self, o): return self._binop(o, lambda a, b: b * a, "rmul")
    def __truediv__(self, o): return self._binop(o, lambda a, b: a / b, "div")
    def __rtruediv__(self, o): return self._binop(o, lambda a, b: b / a, "rdiv")
    def __pow__(self, o): return self._binop(o, lambda a, b: a ** b, "pow")
    def __neg__(self): return Variable("lambda", (self,), fn=lambda a: -a, name="neg")

    def __getitem__(self, idx):
        """Slicing on non-batch dims (reference Variable.slice/indexSelect)."""
        return Variable("lambda", (self,), fn=lambda a: a[idx], name="slice")

    def slice(self, dim: int, start: int, length: int):
        return Variable(
            "lambda", (self,),
            fn=lambda a: jax.lax.slice_in_dim(a, start, start + length, axis=dim),
            name="slice")

    def index_select(self, dim: int, index: int):
        return Variable("lambda", (self,),
                        fn=lambda a: jnp.take(a, index, axis=dim), name="index_select")

    def squeeze(self, dim: Optional[int] = None):
        return Variable("lambda", (self,),
                        fn=lambda a: jnp.squeeze(a, axis=dim), name="squeeze")

    def expand_dims(self, axis: int):
        return Variable("lambda", (self,),
                        fn=lambda a: jnp.expand_dims(a, axis), name="expand_dims")

    def reshape(self, *shape):
        return Variable("lambda", (self,),
                        fn=lambda a: a.reshape(shape), name="reshape")

    def __repr__(self):
        return f"Variable({self.name}, kind={self.kind}, shape={self.shape})"


def Input(shape: Sequence[int], name: Optional[str] = None,
          dtype=jnp.float32) -> Variable:
    """Create an input placeholder; ``shape`` excludes the batch dim
    (Keras convention, reference api/keras/models/Topology Input)."""
    return Variable("input", shape=(None,) + tuple(shape), name=name,
                    dtype=dtype)


def apply_layer(layer, args: Sequence[Variable]) -> Variable:
    return Variable("layer", args, layer=layer, name=layer.name)


def Parameter(shape: Sequence[int], init="glorot_uniform",
              name: Optional[str] = None) -> Variable:
    """A trainable free tensor (reference api/autograd/KerasParameter.scala).

    Realised as a zero-input layer node whose params are the tensor itself.
    """
    from analytics_zoo_tpu.nn import initializers
    from analytics_zoo_tpu.nn.module import StatelessLayer

    class _Param(StatelessLayer):
        def __init__(self, shape, init, **kw):
            super().__init__(**kw)
            self.shape = tuple(shape)
            self.initializer = initializers.get(init)

        def build_params(self, rng, *unused):
            return {"value": self.initializer(rng, self.shape, jnp.float32)}

        def forward(self, params, *unused, training=False, rng=None):
            return params["value"]

    layer = _Param(shape, init, name=name)
    return Variable("param", (), layer=layer, name=layer.name)


# ----------------------------------------------------------------------
# Free functions (reference AutoGrad object, api/autograd/math.scala:32-363)
# ----------------------------------------------------------------------

def _unary(v: Variable, fn, name) -> Variable:
    return Variable("lambda", (v,), fn=fn, name=name)


def abs(v): return _unary(v, jnp.abs, "abs")                 # noqa: A001
def square(v): return _unary(v, jnp.square, "square")
def sqrt(v): return _unary(v, jnp.sqrt, "sqrt")
def log(v): return _unary(v, jnp.log, "log")                 # noqa: A001
def exp(v): return _unary(v, jnp.exp, "exp")
def erf(v): return _unary(v, jax.scipy.special.erf, "erf")
def softsign(v): return _unary(v, jax.nn.soft_sign, "softsign")
def softplus(v): return _unary(v, jax.nn.softplus, "softplus")


def pow(v, a):                                               # noqa: A001
    return _unary(v, lambda x: x ** a, "pow")


def clip(v, min, max):                                       # noqa: A001
    return _unary(v, lambda x: jnp.clip(x, min, max), "clip")


def sum(v, axis: int = 0, keepdims: bool = False):           # noqa: A001
    return _unary(v, lambda x: jnp.sum(x, axis=axis, keepdims=keepdims), "sum")


def mean(v, axis: int = 0, keepdims: bool = False):
    return _unary(v, lambda x: jnp.mean(x, axis=axis, keepdims=keepdims), "mean")


def maximum(a, b):
    if isinstance(a, Variable) and isinstance(b, Variable):
        return Variable("lambda", (a, b), fn=jnp.maximum, name="maximum")
    if isinstance(a, Variable):
        return _unary(a, lambda x: jnp.maximum(x, b), "maximum")
    return _unary(b, lambda x: jnp.maximum(a, x), "maximum")


def stack(vars: Sequence[Variable], axis: int = 1) -> Variable:  # noqa: A002
    return Variable("lambda", tuple(vars),
                    fn=lambda *xs: jnp.stack(xs, axis=axis), name="stack")


def expand_dims(v, axis: int):
    return v.expand_dims(axis)


def contiguous(v):
    return v  # jax arrays are always "contiguous" values


def mm(a: Variable, b: Variable, axes: Optional[Tuple[int, int]] = None):
    """Batched matmul (reference AutoGrad.mm)."""
    if axes is None:
        return Variable("lambda", (a, b), fn=jnp.matmul, name="mm")

    def fn(x, y):
        return jax.lax.dot_general(
            x, y, dimension_numbers=(((axes[0],), (axes[1],)), ((0,), (0,))))
    return Variable("lambda", (a, b), fn=fn, name="mm")


def batch_dot(a: Variable, b: Variable, axes: Tuple[int, int] = (1, 1)):
    return mm(a, b, axes=axes)


def l2_normalize(v, axis: int = -1):
    return _unary(
        v, lambda x: x / (jnp.linalg.norm(x, axis=axis, keepdims=True) + 1e-12),
        "l2_normalize")


# ----------------------------------------------------------------------
# DAG evaluation (used by Model)
# ----------------------------------------------------------------------

def topo_sort(outputs: Sequence[Variable]) -> List[Variable]:
    seen: Dict[int, Variable] = {}
    order: List[Variable] = []

    def visit(v: Variable):
        if v.id in seen:
            return
        seen[v.id] = v
        for p in v.parents:
            visit(p)
        order.append(v)

    for out in outputs:
        visit(out)
    return order


def evaluate(order: List[Variable], env: Dict[int, Any], params, state,
             training: bool = False, rng=None) -> Tuple[Dict[int, Any], Dict]:
    """Evaluate a topo-sorted DAG. ``env`` seeds input nodes (by var id).

    Returns (full env, new_state).  ``params``/``state`` are dicts keyed by
    layer name.
    """
    new_state = dict(state)
    layer_nodes = [v for v in order if v.kind in ("layer", "param")]
    rngs = {}
    if rng is not None and layer_nodes:
        keys = jax.random.split(rng, len(layer_nodes))
        rngs = {v.id: k for v, k in zip(layer_nodes, keys)}

    for v in order:
        if v.id in env:
            continue
        if v.kind == "input":
            raise ValueError(f"missing value for input {v.name}")
        parent_vals = [env[p.id] for p in v.parents]
        if v.kind in ("layer", "param"):
            lp = params.get(v.layer.name, {})
            ls = state.get(v.layer.name, {})
            out, ns = v.layer.call(lp, ls, *parent_vals,
                                   training=training, rng=rngs.get(v.id))
            env[v.id] = out
            new_state[v.layer.name] = ns
        else:  # lambda
            env[v.id] = v.fn(*parent_vals)
    return env, new_state
