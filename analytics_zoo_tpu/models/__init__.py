from analytics_zoo_tpu.models.common import ZooModel, register_model  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    NeuralCF,
    Recommender,
    SessionRecommender,
    WideAndDeep,
    negative_sample,
)
