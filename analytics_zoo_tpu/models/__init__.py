from analytics_zoo_tpu.models.common import ZooModel, register_model  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    NeuralCF,
    Recommender,
    SessionRecommender,
    WideAndDeep,
    negative_sample,
    presample_implicit_epochs,
)
from analytics_zoo_tpu.models.text import (  # noqa: F401
    KNRM,
    Ranker,
    TextClassifier,
    mean_average_precision,
    ndcg,
)
from analytics_zoo_tpu.models.seq2seq import (  # noqa: F401
    Bridge,
    RNNDecoder,
    RNNEncoder,
    Seq2seq,
)
from analytics_zoo_tpu.models.anomalydetection import (  # noqa: F401
    AnomalyDetector,
    detect_anomalies,
    unroll,
)
