"""Seq2seq: RNNEncoder / RNNDecoder / Bridge / Seq2seq model + greedy infer.

Reference capability: models/seq2seq/ — ``Seq2seq`` (Seq2seq.scala:45-302),
``RNNEncoder``/``RNNDecoder`` (205/212 LoC: stacked LSTM/GRU with state
handoff), ``Bridge`` (156 LoC: "pass" or dense transform of encoder states)
and the chatbot example's greedy ``infer`` loop.

TPU-first: encoder and (teacher-forced) decoder are each ONE ``lax.scan``
— training is a single fused program; greedy inference re-uses the
decoder's per-step cell inside another ``lax.scan`` over generated tokens
(static ``max_seq_len``, no data-dependent Python loop).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.nn import initializers
from analytics_zoo_tpu.nn.layers.core import Dense
from analytics_zoo_tpu.nn.layers.embedding import Embedding
from analytics_zoo_tpu.nn.layers.recurrent import GRU, LSTM, RNNBase
from analytics_zoo_tpu.nn.module import Layer, StatelessLayer, split_rng
from analytics_zoo_tpu.nn.topology import KerasNet


def _make_cell(rnn_type: str, hidden: int, name: str) -> RNNBase:
    rnn_type = rnn_type.lower()
    if rnn_type == "lstm":
        return LSTM(hidden, return_sequences=True, name=name)
    if rnn_type == "gru":
        return GRU(hidden, return_sequences=True, name=name)
    raise ValueError(f"unknown rnn_type {rnn_type!r}; known: lstm, gru")


class _StackedRNN(StatelessLayer):
    """Shared stacked-cell construction/params for encoder and decoder."""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 128, **kw):
        super().__init__(**kw)
        self.cells = [_make_cell(rnn_type, hidden_size,
                                 f"{self.name}_l{i}")
                      for i in range(num_layers)]

    def build_params(self, rng, input_shape):
        params = {}
        shape = tuple(input_shape)
        for cell, r in zip(self.cells, split_rng(rng, len(self.cells))):
            params[cell.name] = cell.build_params(r, shape)
            shape = shape[:-1] + (cell.output_dim,)
        return params


class RNNEncoder(_StackedRNN):
    """Stacked RNN encoder returning (sequence_output, final_states)
    (reference models/seq2seq/RNNEncoder.scala)."""

    def forward(self, params, x, training=False, rng=None):
        states = []
        for cell in self.cells:
            x, st = cell.run(params[cell.name], x, return_state=True)
            states.append(st)
        return [x, states]


class Bridge(StatelessLayer):
    """Transform encoder final states into decoder initial states
    (reference models/seq2seq/Bridge.scala: "pass" | "dense")."""

    def __init__(self, bridge_type: str = "pass",
                 decoder_hidden_size: Optional[int] = None, **kw):
        super().__init__(**kw)
        if bridge_type not in ("pass", "dense"):
            raise ValueError(
                f"unknown bridge_type {bridge_type!r}; known: pass, dense")
        self.bridge_type = bridge_type
        self.decoder_hidden_size = decoder_hidden_size
        self.initializer = initializers.get("glorot_uniform")

    def build_state_params(self, rng, states):
        """Allocate dense kernels sized from a concrete states pytree."""
        if self.bridge_type == "pass":
            return {}
        leaves = jax.tree_util.tree_leaves(states)
        ks = jax.random.split(rng, len(leaves))
        out = {}
        for i, (leaf, k) in enumerate(zip(leaves, ks)):
            d_in = leaf.shape[-1]
            d_out = self.decoder_hidden_size or d_in
            out[f"w{i}"] = self.initializer(k, (d_in, d_out), jnp.float32)
            out[f"b{i}"] = jnp.zeros((d_out,), jnp.float32)
        return out

    def apply_states(self, params, states):
        if self.bridge_type == "pass":
            return states
        leaves, treedef = jax.tree_util.tree_flatten(states)
        new = [jnp.tanh(leaf @ params[f"w{i}"] + params[f"b{i}"])
               for i, leaf in enumerate(leaves)]
        return jax.tree_util.tree_unflatten(treedef, new)


class RNNDecoder(_StackedRNN):
    """Stacked RNN decoder consuming initial states per layer
    (reference models/seq2seq/RNNDecoder.scala)."""

    def run_with_states(self, params, x, init_states,
                        return_state: bool = False):
        states = []
        for cell, st in zip(self.cells, init_states):
            x, new_st = cell.run(params[cell.name], x, initial_carry=st,
                                 return_state=True)
            states.append(new_st)
        if return_state:
            return x, states
        return x

    def forward(self, params, x, training=False, rng=None):
        return self.run_with_states(
            params, x, [None] * len(self.cells))


class Seq2seqNet(KerasNet):
    """The jittable seq2seq program: ids → embed → encode → bridge →
    teacher-forced decode → vocab logits."""

    @property
    def layers(self):
        return [self.embedding, self.encoder, self.bridge, self.decoder,
                self.generator]

    def __init__(self, vocab_size: int, embed_dim: int, rnn_type: str,
                 num_layers: int, hidden_size: int, bridge_type: str,
                 **kw):
        super().__init__(**kw)
        self.vocab_size = vocab_size
        self.embedding = Embedding(vocab_size, embed_dim,
                                   name=f"{self.name}_embed")
        self.encoder = RNNEncoder(rnn_type, num_layers, hidden_size,
                                  name=f"{self.name}_enc")
        self.decoder = RNNDecoder(rnn_type, num_layers, hidden_size,
                                  name=f"{self.name}_dec")
        self.bridge = Bridge(bridge_type, hidden_size,
                             name=f"{self.name}_bridge")
        self.generator = Dense(vocab_size, name=f"{self.name}_gen")

    def build(self, rng, enc_shape, dec_shape):
        k_e, k_enc, k_dec, k_b, k_g = jax.random.split(rng, 5)
        params = {
            "embed": self.embedding.build_params(k_e, enc_shape),
            "enc": self.encoder.build_params(
                k_enc, tuple(enc_shape) + (self.embedding.output_dim,)),
            "dec": self.decoder.build_params(
                k_dec, tuple(dec_shape) + (self.embedding.output_dim,)),
        }
        # size bridge kernels from real encoder state shapes
        dummy = jnp.zeros((2,) + tuple(enc_shape)[1:], jnp.int32)
        emb = self.embedding.forward(params["embed"], dummy)
        _, states = self.encoder.forward(params["enc"], emb)
        params["bridge"] = self.bridge.build_state_params(k_b, states)
        params["gen"] = self.generator.build_params(
            k_g, (2, self.decoder.cells[-1].output_dim))
        return params, {}

    def call(self, params, state, enc_ids, dec_ids, training=False,
             rng=None):
        enc_emb = self.embedding.forward(params["embed"], enc_ids)
        dec_emb = self.embedding.forward(params["embed"], dec_ids)
        _, enc_states = self.encoder.forward(params["enc"], enc_emb)
        init_states = self.bridge.apply_states(params["bridge"], enc_states)
        dec_out = self.decoder.run_with_states(params["dec"], dec_emb,
                                               init_states)
        logits = self.generator.forward(params["gen"], dec_out)
        return logits, state

    # -- greedy inference --------------------------------------------------
    def infer(self, params, enc_ids, start_sign: int, max_seq_len: int,
              stop_sign: Optional[int] = None) -> jnp.ndarray:
        """Greedy decode (reference Seq2seq.infer / chatbot example):
        feed <start>, repeatedly take argmax, for ``max_seq_len`` steps —
        one lax.scan, fixed shapes.  With ``stop_sign``, positions after a
        sequence emits the stop token are padded with it (the scan still
        runs max_seq_len steps — static shape — but post-stop logits no
        longer leak into the output)."""
        enc_emb = self.embedding.forward(params["embed"], enc_ids)
        _, enc_states = self.encoder.forward(params["enc"], enc_emb)
        states = self.bridge.apply_states(params["bridge"], enc_states)
        b = enc_ids.shape[0]
        tok0 = jnp.full((b, 1), start_sign, jnp.int32)
        done0 = jnp.zeros((b,), bool)

        def step(carry, _):
            tok, states, done = carry
            emb = self.embedding.forward(params["embed"], tok)  # (B,1,E)
            out, new_states = self.decoder.run_with_states(
                params["dec"], emb, states, return_state=True)
            logits = self.generator.forward(params["gen"], out[:, -1])
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if stop_sign is not None:
                nxt = jnp.where(done, jnp.int32(stop_sign), nxt)
                done = done | (nxt == stop_sign)
            return (nxt[:, None], new_states, done), nxt

        (_, _, _), toks = jax.lax.scan(step, (tok0, states, done0), None,
                                       length=max_seq_len)
        return toks.swapaxes(0, 1)  # (B, max_seq_len)

    # -- beam search (beyond reference: Seq2seq.scala only greedy-decodes)
    def beam_search(self, params, enc_ids, start_sign: int,
                    max_seq_len: int, beam_size: int = 4,
                    stop_sign: Optional[int] = None,
                    length_penalty: float = 0.0):
        """Fixed-shape beam search as one ``lax.scan`` (XLA-friendly: no
        dynamic shapes, no host round trips; backtrace is a second scan).

        Returns ``(tokens (B, max_seq_len), scores (B,))`` for the best
        beam.  ``length_penalty`` > 0 divides scores by (length**p) at
        the end (GNMT-style), favouring longer sequences.
        """
        V, K = self.vocab_size, beam_size
        b = enc_ids.shape[0]
        NEG = -1e30

        enc_emb = self.embedding.forward(params["embed"], enc_ids)
        _, enc_states = self.encoder.forward(params["enc"], enc_emb)
        states = self.bridge.apply_states(params["bridge"], enc_states)
        # replicate encoder states across beams: (B, ...) -> (B*K, ...)
        states = jax.tree_util.tree_map(
            lambda s: jnp.repeat(s, K, axis=0), states)

        tok0 = jnp.full((b * K, 1), start_sign, jnp.int32)
        # beam 0 starts live, others -inf so step 1 fans out of one beam
        score0 = jnp.tile(jnp.asarray([0.0] + [NEG] * (K - 1),
                                      jnp.float32), (b, 1))     # (B, K)
        done0 = jnp.zeros((b, K), bool)
        len0 = jnp.zeros((b, K), jnp.float32)

        def gather_beams(tree, beam_idx):
            # tree leaves (B*K, ...) -> pick beam_idx (B, K) per batch
            def g(s):
                sk = s.reshape((b, K) + s.shape[1:])
                idx = beam_idx.reshape(
                    (b, K) + (1,) * (s.ndim - 1)).astype(jnp.int32)
                return jnp.take_along_axis(
                    sk, jnp.broadcast_to(idx, (b, K) + s.shape[1:]),
                    axis=1).reshape(s.shape)
            return jax.tree_util.tree_map(g, tree)

        def step(carry, _):
            tok, states, scores, done, lens = carry
            emb = self.embedding.forward(params["embed"], tok)  # (B*K,1,E)
            out, new_states = self.decoder.run_with_states(
                params["dec"], emb, states, return_state=True)
            logits = self.generator.forward(params["gen"], out[:, -1])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            logp = logp.reshape(b, K, V)
            if stop_sign is not None:
                # a finished beam can only extend with stop_sign, free
                pad = jnp.full((V,), NEG).at[stop_sign].set(0.0)
                logp = jnp.where(done[:, :, None], pad[None, None, :], logp)
            total = scores[:, :, None] + logp                   # (B, K, V)
            flat = total.reshape(b, K * V)
            new_scores, top = jax.lax.top_k(flat, K)            # (B, K)
            beam_idx = (top // V).astype(jnp.int32)
            token = (top % V).astype(jnp.int32)
            new_states = gather_beams(new_states, beam_idx)
            # length/done histories follow the beams they came from —
            # gather with beam_idx BEFORE extending, so slot k's counter
            # tracks one hypothesis even as beams reorder
            done = jnp.take_along_axis(done, beam_idx, axis=1)
            lens = jnp.take_along_axis(lens, beam_idx, axis=1)
            if stop_sign is not None:
                # count tokens strictly before the stop token
                lens = lens + jnp.where(done | (token == stop_sign),
                                        0.0, 1.0)
                done = done | (token == stop_sign)
            else:
                lens = lens + 1.0
            return ((token.reshape(b * K, 1), new_states, new_scores,
                     done, lens), (token, beam_idx))

        (_, _, scores, done, lengths), (toks, parents) = jax.lax.scan(
            step, (tok0, states, score0, done0, len0), None,
            length=max_seq_len)                  # toks (T, B, K)

        if length_penalty > 0 and stop_sign is not None:
            scores = scores / jnp.maximum(lengths, 1.0) ** length_penalty

        best = jnp.argmax(scores, axis=-1).astype(jnp.int32)    # (B,)

        # backtrace: follow parent pointers from the best final beam
        def back(beam, t_rev):
            tk = jnp.take_along_axis(toks[t_rev], beam[:, None],
                                     axis=1)[:, 0]
            beam = jnp.take_along_axis(parents[t_rev], beam[:, None],
                                       axis=1)[:, 0]
            return beam, tk

        _, seq_rev = jax.lax.scan(back, best,
                                  jnp.arange(max_seq_len - 1, -1, -1))
        seq = seq_rev[::-1].swapaxes(0, 1)                      # (B, T)
        best_scores = jnp.take_along_axis(scores, best[:, None],
                                          axis=1)[:, 0]
        return seq, best_scores


@register_model
class Seq2seq(ZooModel):
    """Sequence-to-sequence ZooModel (reference models/seq2seq/Seq2seq.scala).

    fit() takes ``[encoder_ids, decoder_ids]`` (teacher forcing) with
    targets = decoder ids shifted left; ``infer`` greedy-decodes.
    """

    def __init__(self, vocab_size: int, embed_dim: int = 64,
                 rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 128, bridge_type: str = "pass"):
        super().__init__()
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.rnn_type = rnn_type
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.bridge_type = bridge_type
        self.model = Seq2seqNet(vocab_size, embed_dim, rnn_type, num_layers,
                                hidden_size, bridge_type, name="seq2seq")

    def config(self):
        return {"vocab_size": self.vocab_size, "embed_dim": self.embed_dim,
                "rnn_type": self.rnn_type, "num_layers": self.num_layers,
                "hidden_size": self.hidden_size,
                "bridge_type": self.bridge_type}

    def infer(self, enc_ids: np.ndarray, start_sign: int,
              max_seq_len: int = 30,
              stop_sign: Optional[int] = None) -> np.ndarray:
        est = self.model.estimator
        est._ensure_built([np.asarray(enc_ids),
                           np.asarray(enc_ids)])  # dec shape == enc shape ok
        if not hasattr(self, "_infer_jit"):
            # one persistent jit cache — re-wrapping the bound method per
            # call would recompile the whole decode program every time
            self._infer_jit = jax.jit(self.model.infer,
                                      static_argnums=(2, 3, 4))
        out = self._infer_jit(est.params, jnp.asarray(enc_ids), start_sign,
                              max_seq_len, stop_sign)
        return np.asarray(out)

    def infer_beam(self, enc_ids: np.ndarray, start_sign: int,
                   max_seq_len: int = 30, beam_size: int = 4,
                   stop_sign: Optional[int] = None,
                   length_penalty: float = 0.0):
        """Beam-search decode; returns (tokens (B, T), scores (B,))."""
        est = self.model.estimator
        est._ensure_built([np.asarray(enc_ids), np.asarray(enc_ids)])
        if not hasattr(self, "_beam_jit"):
            self._beam_jit = jax.jit(self.model.beam_search,
                                     static_argnums=(2, 3, 4, 5, 6))
        seq, scores = self._beam_jit(est.params, jnp.asarray(enc_ids),
                                     start_sign, max_seq_len, beam_size,
                                     stop_sign, length_penalty)
        return np.asarray(seq), np.asarray(scores)
