"""Text models: TextClassifier (CNN/LSTM/GRU), KNRM kernel-pooling ranker,
Ranker evaluation (NDCG / MAP).

Reference capability: models/textclassification/TextClassifier.scala (192
LoC: embedding → {CNN|LSTM|GRU} encoder → dense softmax),
models/textmatching/KNRM.scala (192 LoC: shared embedding, translation
matrix Q·Dᵀ, RBF kernel pooling, learning-to-rank head) and
common/Ranker.scala (175 LoC: evaluateNDCG/evaluateMAP).

TPU-first: every encoder is a fixed-shape batched program; KNRM's kernel
pooling — the hot op — is expressed as one einsum + exp stack that XLA
fuses (the reference needed a dedicated "kernel-pooling" candidate for a
Pallas kernel per SURVEY §2.3, but the fused XLA form already saturates the
VPU at these sizes).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.nn import Input, Model, Sequential
from analytics_zoo_tpu.nn.layers.convolutional import Convolution1D
from analytics_zoo_tpu.nn.layers.core import Dense, Dropout, Flatten, Lambda
from analytics_zoo_tpu.nn.layers.embedding import Embedding
from analytics_zoo_tpu.nn.layers.pooling import GlobalMaxPooling1D
from analytics_zoo_tpu.nn.layers.recurrent import GRU, LSTM


@register_model
class TextClassifier(ZooModel):
    """Embedding → encoder → Dense(class_num) softmax
    (reference models/textclassification/TextClassifier.scala:45-120).

    ``encoder``: "cnn" (Conv1D + global max pool), "lstm", or "gru".
    """

    def __init__(self, class_num: int, token_length: int = 200,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256, max_words_num: int = 5000,
                 embedding_weights: Optional[np.ndarray] = None):
        super().__init__()
        self.class_num = class_num
        self.token_length = token_length
        self.sequence_length = sequence_length
        self.encoder = encoder.lower()
        self.encoder_output_dim = encoder_output_dim
        self.max_words_num = max_words_num

        # explicit names — save/load must not depend on auto-name counters
        layers = [Embedding(max_words_num + 1, token_length,
                            weights=embedding_weights, name="tc_embed",
                            input_shape=(sequence_length,))]
        if self.encoder == "cnn":
            layers += [
                Convolution1D(encoder_output_dim, 5, activation="relu",
                              name="tc_conv"),
                GlobalMaxPooling1D(name="tc_pool"),
            ]
        elif self.encoder == "lstm":
            layers += [LSTM(encoder_output_dim, name="tc_lstm")]
        elif self.encoder == "gru":
            layers += [GRU(encoder_output_dim, name="tc_gru")]
        else:
            raise ValueError(
                f"unknown encoder {encoder!r}; known: cnn, lstm, gru")
        layers += [Dropout(0.2, name="tc_drop"),
                   Dense(128, activation="relu", name="tc_fc"),
                   Dense(class_num, name="tc_out")]
        self.model = Sequential(layers, name=f"text_classifier_{encoder}")

    def config(self):
        return {"class_num": self.class_num,
                "token_length": self.token_length,
                "sequence_length": self.sequence_length,
                "encoder": self.encoder,
                "encoder_output_dim": self.encoder_output_dim,
                "max_words_num": self.max_words_num}


@register_model
class KNRM(ZooModel):
    """Kernel-pooling neural ranking model
    (reference models/textmatching/KNRM.scala:45-150; Xiong et al. 2017).

    Inputs: query ids (B, text1_length), doc ids (B, text2_length).
    Output: (B, 1) ranking score (sigmoid if ``target_mode='classification'``).
    """

    def __init__(self, text1_length: int, text2_length: int,
                 max_words_num: int = 5000, embed_size: int = 100,
                 embedding_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        super().__init__()
        if kernel_num <= 1:
            raise ValueError(
                f"kernel_num must be > 1, got {kernel_num} "
                "(reference KNRM.scala requires kernelNum > 1)")
        self.text1_length = text1_length
        self.text2_length = text2_length
        self.max_words_num = max_words_num
        self.embed_size = embed_size
        self.kernel_num = kernel_num
        self.sigma = sigma
        self.exact_sigma = exact_sigma
        self.target_mode = target_mode

        # RBF kernel centers spread over cosine range [-1, 1]; the last
        # kernel (mu=1.0) is the exact-match kernel with its own sigma
        # (KNRM.scala:101-110).
        mus, sigmas = [], []
        for i in range(kernel_num):
            mu = 1.0 / (kernel_num - 1) + (2.0 * i) / (kernel_num - 1) - 1.0
            if mu > 1.0:
                mu, sg = 1.0, exact_sigma
            else:
                sg = sigma
            mus.append(mu)
            sigmas.append(sg)
        mus_arr = jnp.asarray(mus, jnp.float32)
        sig_arr = jnp.asarray(sigmas, jnp.float32)

        q_in = Input(shape=(text1_length,), name="query")
        d_in = Input(shape=(text2_length,), name="doc")
        embed = Embedding(max_words_num + 1, embed_size,
                          weights=embedding_weights, trainable=train_embed,
                          name="shared_embedding")
        q = embed(q_in)
        d = embed(d_in)

        def kernel_pooling(qe, de):
            # translation matrix of cosine similarities (B, Lq, Ld)
            qn = qe / jnp.maximum(
                jnp.linalg.norm(qe, axis=-1, keepdims=True), 1e-8)
            dn = de / jnp.maximum(
                jnp.linalg.norm(de, axis=-1, keepdims=True), 1e-8)
            mm = jnp.einsum("bqe,bde->bqd", qn, dn)
            # RBF kernels: (B, Lq, Ld, K) -> log-sum pooling (KNRM eq. 4-6)
            diff = mm[..., None] - mus_arr
            k = jnp.exp(-0.5 * diff * diff / (sig_arr * sig_arr))
            kq = jnp.sum(k, axis=2)                      # (B, Lq, K)
            soft_tf = jnp.sum(jnp.log1p(jnp.maximum(kq - 1e-10, 0.0)),
                              axis=1)                     # (B, K)
            return soft_tf * 0.01

        pooled = Lambda(kernel_pooling, name="kernel_pooling")(q, d)
        act = "sigmoid" if target_mode == "classification" else None
        out = Dense(1, activation=act, name="score")(pooled)
        self.model = Model([q_in, d_in], out, name="knrm")

    def config(self):
        return {"text1_length": self.text1_length,
                "text2_length": self.text2_length,
                "max_words_num": self.max_words_num,
                "embed_size": self.embed_size,
                "kernel_num": self.kernel_num, "sigma": self.sigma,
                "exact_sigma": self.exact_sigma,
                "target_mode": self.target_mode}


# ---------------------------------------------------------------- ranking --

def ndcg(y_true: np.ndarray, y_score: np.ndarray, k: int = 10) -> float:
    """NDCG@k for one query (reference common/Ranker.scala evaluateNDCG)."""
    order = np.argsort(-np.asarray(y_score))
    gains = (2.0 ** np.asarray(y_true)[order] - 1.0)[:k]
    discounts = 1.0 / np.log2(np.arange(2, gains.size + 2))
    dcg = float(np.sum(gains * discounts))
    ideal = (2.0 ** np.sort(np.asarray(y_true))[::-1] - 1.0)[:k]
    idcg = float(np.sum(ideal * discounts[:ideal.size]))
    return dcg / idcg if idcg > 0 else 0.0


def mean_average_precision(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """AP for one query, relevance>0 = relevant
    (reference Ranker.scala evaluateMAP)."""
    order = np.argsort(-np.asarray(y_score))
    rel = np.asarray(y_true)[order] > 0
    if not rel.any():
        return 0.0
    prec = np.cumsum(rel) / np.arange(1, rel.size + 1)
    return float(np.sum(prec * rel) / rel.sum())


class Ranker:
    """Batch evaluation over (query_id, label, score) triples
    (reference models/common/Ranker.scala:40-175)."""

    @staticmethod
    def _group(qids, labels, scores):
        groups: Dict = {}
        for q, l, s in zip(qids, labels, scores):
            groups.setdefault(q, ([], []))
            groups[q][0].append(l)
            groups[q][1].append(s)
        return groups

    @classmethod
    def evaluate_ndcg(cls, qids, labels, scores, k: int = 10) -> float:
        groups = cls._group(qids, labels, scores)
        return float(np.mean([ndcg(l, s, k) for l, s in groups.values()]))

    @classmethod
    def evaluate_map(cls, qids, labels, scores) -> float:
        groups = cls._group(qids, labels, scores)
        return float(np.mean([mean_average_precision(l, s)
                              for l, s in groups.values()]))
