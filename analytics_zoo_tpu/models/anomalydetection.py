"""Anomaly detection: stacked-LSTM forecaster + threshold detection.

Reference capability: models/anomalydetection/AnomalyDetector.scala (222
LoC: 2-3 stacked LSTMs with dropout → Dense(1) next-value prediction;
``detectAnomalies`` ranks |y - ŷ| and flags the top ``anomalySize``) and
its ``Utils.unroll`` windowing (pyzoo mirror zoo/models/anomalydetection).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers.core import Dense, Dropout
from analytics_zoo_tpu.nn.layers.recurrent import LSTM


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: x[i] = data[i : i+L], y[i] = data[i+L+step-1, 0]
    (reference AnomalyDetector.unroll)."""
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError(
            f"series of {len(data)} too short for unroll_length "
            f"{unroll_length} + predict_step {predict_step}")
    x = np.stack([data[i:i + unroll_length] for i in range(n)])
    y = data[unroll_length + predict_step - 1:
             unroll_length + predict_step - 1 + n, 0]
    return x, y.astype(np.float32)


def detect_anomalies(y_true: np.ndarray, y_pred: np.ndarray,
                     anomaly_size: Optional[int] = None,
                     threshold: Optional[float] = None) -> np.ndarray:
    """Indices of anomalous points — either the top-``anomaly_size`` by
    absolute error, or all points with |error| > ``threshold``
    (reference AnomalyDetector.detectAnomalies)."""
    err = np.abs(np.asarray(y_true).ravel() - np.asarray(y_pred).ravel())
    if threshold is not None:
        return np.nonzero(err > threshold)[0]
    if anomaly_size is None:
        anomaly_size = max(1, int(0.01 * err.size))
    return np.argsort(-err)[:anomaly_size]


@register_model
class AnomalyDetector(ZooModel):
    """LSTM forecaster over unrolled windows
    (reference models/anomalydetection/AnomalyDetector.scala:45-120).

    ``feature_shape`` = (unroll_length, feature_num);
    ``hidden_layers``/``dropouts`` mirror the reference's constructor.
    """

    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        super().__init__()
        if len(hidden_layers) != len(dropouts):
            raise ValueError("hidden_layers and dropouts must align")
        self.feature_shape = tuple(feature_shape)
        self.hidden_layers = tuple(hidden_layers)
        self.dropouts = tuple(dropouts)

        # explicit names: load_model rebuilds with fresh auto-name counters,
        # so params must be keyed independently of global naming state
        layers: List = []
        for i, (h, p) in enumerate(zip(hidden_layers, dropouts)):
            last = i == len(hidden_layers) - 1
            kw = {"input_shape": self.feature_shape} if i == 0 else {}
            layers.append(LSTM(h, return_sequences=not last,
                               name=f"ad_lstm{i}", **kw))
            layers.append(Dropout(p, name=f"ad_drop{i}"))
        layers.append(Dense(1, name="ad_out"))
        self.model = Sequential(layers, name="anomaly_detector")

    def config(self):
        return {"feature_shape": list(self.feature_shape),
                "hidden_layers": list(self.hidden_layers),
                "dropouts": list(self.dropouts)}

    def detect_anomalies(self, y_true, y_pred, anomaly_size=None,
                         threshold=None):
        return detect_anomalies(y_true, y_pred, anomaly_size, threshold)
