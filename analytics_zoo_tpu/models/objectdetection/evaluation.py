"""Detection evaluation: VOC-style mean average precision.

Reference capability: models/image/objectdetection/common/
{MeanAveragePrecision.scala:95, PascalVocEvaluator.scala:125}.

Host-side numpy (evaluation is not a hot path): greedy matching of
score-ranked detections to ground truth at an IoU threshold, AP by either
11-point interpolation (VOC2007) or the continuous area method.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def _iou_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * \
        np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * \
        np.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


def average_precision(recalls: np.ndarray, precisions: np.ndarray,
                      use_07_metric: bool = False) -> float:
    """AP from a PR curve (reference MeanAveragePrecision.computeAP)."""
    if use_07_metric:
        ap = 0.0
        for t in np.linspace(0, 1, 11):
            mask = recalls >= t
            ap += (precisions[mask].max() if mask.any() else 0.0) / 11.0
        return float(ap)
    mrec = np.concatenate([[0.0], recalls, [1.0]])
    mpre = np.concatenate([[0.0], precisions, [0.0]])
    mpre = np.maximum.accumulate(mpre[::-1])[::-1]
    idx = np.nonzero(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


class MeanAveragePrecision:
    """Accumulate per-image detections and compute mAP
    (reference MeanAveragePrecision.scala; PascalVocEvaluator drives it
    per-class over the VOC val set)."""

    def __init__(self, num_classes: int, iou_threshold: float = 0.5,
                 use_07_metric: bool = False):
        self.num_classes = num_classes
        self.iou_threshold = iou_threshold
        self.use_07 = use_07_metric
        # per class: list of (score, is_tp); gt counts
        self._dets: Dict[int, List[Tuple[float, bool]]] = \
            {c: [] for c in range(1, num_classes + 1)}
        self._gt_count = {c: 0 for c in range(1, num_classes + 1)}

    def add(self, det_boxes, det_scores, det_labels,
            gt_boxes, gt_labels) -> None:
        det_boxes = np.asarray(det_boxes, np.float32).reshape(-1, 4)
        det_scores = np.asarray(det_scores, np.float32).ravel()
        det_labels = np.asarray(det_labels).ravel()
        gt_boxes = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gt_labels = np.asarray(gt_labels).ravel()
        for c in range(1, self.num_classes + 1):
            gt_c = gt_boxes[gt_labels == c]
            self._gt_count[c] += len(gt_c)
            sel = det_labels == c
            boxes_c = det_boxes[sel]
            scores_c = det_scores[sel]
            order = np.argsort(-scores_c)
            matched = np.zeros(len(gt_c), bool)
            for i in order:
                if len(gt_c) == 0:
                    self._dets[c].append((float(scores_c[i]), False))
                    continue
                ious = _iou_np(boxes_c[i:i + 1], gt_c)[0]
                j = int(np.argmax(ious))
                if ious[j] >= self.iou_threshold and not matched[j]:
                    matched[j] = True
                    self._dets[c].append((float(scores_c[i]), True))
                else:
                    self._dets[c].append((float(scores_c[i]), False))

    def per_class_ap(self) -> Dict[int, float]:
        aps = {}
        for c, dets in self._dets.items():
            npos = self._gt_count[c]
            if npos == 0:
                continue
            if not dets:
                aps[c] = 0.0
                continue
            dets_sorted = sorted(dets, key=lambda t: -t[0])
            tps = np.asarray([tp for _, tp in dets_sorted], np.float32)
            fps = 1.0 - tps
            tp_cum = np.cumsum(tps)
            fp_cum = np.cumsum(fps)
            recalls = tp_cum / npos
            precisions = tp_cum / np.maximum(tp_cum + fp_cum, 1e-9)
            aps[c] = average_precision(recalls, precisions, self.use_07)
        return aps

    def result(self) -> float:
        aps = self.per_class_ap()
        return float(np.mean(list(aps.values()))) if aps else 0.0


class PascalVocEvaluator(MeanAveragePrecision):
    """VOC-2007 protocol (11-point AP) over the 20 VOC classes
    (reference PascalVocEvaluator.scala)."""

    CLASSES = ("aeroplane", "bicycle", "bird", "boat", "bottle", "bus",
               "car", "cat", "chair", "cow", "diningtable", "dog", "horse",
               "motorbike", "person", "pottedplant", "sheep", "sofa",
               "train", "tvmonitor")

    def __init__(self, iou_threshold: float = 0.5):
        super().__init__(num_classes=len(self.CLASSES),
                         iou_threshold=iou_threshold, use_07_metric=True)

    def summary(self) -> Dict[str, float]:
        aps = self.per_class_ap()
        out = {self.CLASSES[c - 1]: ap for c, ap in aps.items()}
        out["mAP"] = self.result()
        return out
