from analytics_zoo_tpu.models.objectdetection.bbox import (  # noqa: F401
    decode_boxes,
    encode_boxes,
    generate_priors,
    iou_matrix,
    match_priors,
)
from analytics_zoo_tpu.models.objectdetection.nms import (  # noqa: F401
    batched_class_nms,
    nms,
)
from analytics_zoo_tpu.models.objectdetection.loss import (  # noqa: F401
    MultiBoxLoss,
    multibox_loss,
    smooth_l1,
)
from analytics_zoo_tpu.models.objectdetection.ssd import (  # noqa: F401
    SSD300_CONFIG,
    ObjectDetector,
    SSDTargetAssigner,
    build_ssd,
)
from analytics_zoo_tpu.models.objectdetection.evaluation import (  # noqa: F401
    MeanAveragePrecision,
    PascalVocEvaluator,
    average_precision,
)
from analytics_zoo_tpu.models.objectdetection.visualizer import (  # noqa: F401
    draw_detections,
    save_detection_images,
)
