"""Detection visualization (reference
examples/objectdetection/inference/Predict.scala's ``Visualizer``:
draw detected boxes + class/score captions onto images and save them).

In-process cv2 drawing — the reference shipped images through a Spark
``ImageFrame`` to a JVM Visualizer; here the arrays are already local.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

# deterministic class palette (BGR) — stable across runs for diffable
# output images
_PALETTE = [(66, 133, 244), (52, 168, 83), (251, 188, 5), (234, 67, 53),
            (154, 160, 166), (255, 112, 67), (0, 172, 193), (171, 71, 188)]


def draw_detections(image: np.ndarray, boxes: np.ndarray,
                    scores: np.ndarray, labels: np.ndarray,
                    class_names: Optional[Sequence[str]] = None,
                    normalized: bool = True,
                    thickness: int = 2) -> np.ndarray:
    """Return a copy of ``image`` (H, W, 3 uint8 or float in [0,1]) with
    one rectangle + ``class score`` caption per detection."""
    import cv2

    img = np.asarray(image)
    if img.dtype != np.uint8:
        img = (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)
    img = np.ascontiguousarray(img.copy())
    h, w = img.shape[:2]
    for box, score, label in zip(np.asarray(boxes), np.asarray(scores),
                                 np.asarray(labels)):
        x1, y1, x2, y2 = box
        if normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        p1 = (int(round(x1)), int(round(y1)))
        p2 = (int(round(x2)), int(round(y2)))
        color = _PALETTE[int(label) % len(_PALETTE)]
        cv2.rectangle(img, p1, p2, color, thickness)
        name = (class_names[int(label)] if class_names
                and int(label) < len(class_names) else str(int(label)))
        caption = f"{name} {float(score):.2f}"
        cv2.putText(img, caption, (p1[0], max(12, p1[1] - 4)),
                    cv2.FONT_HERSHEY_SIMPLEX, 0.4, color, 1)
    return img


def save_detection_images(out_dir: str, images, detections,
                          class_names: Optional[Sequence[str]] = None,
                          prefix: str = "detection",
                          normalized: bool = True) -> list:
    """Draw + write one annotated file per image
    (``{prefix}_{i}.jpg``); returns the written paths."""
    import cv2

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i, (img, (boxes, scores, labels)) in enumerate(
            zip(images, detections)):
        drawn = draw_detections(img, boxes, scores, labels,
                                class_names=class_names,
                                normalized=normalized)
        path = os.path.join(out_dir, f"{prefix}_{i}.jpg")
        cv2.imwrite(path, drawn)
        paths.append(path)
    return paths
