"""SSD object detector: VGG backbone + multi-scale heads + priors.

Reference capability: models/image/objectdetection/ssd/{SSD.scala:214,
SSDGraph.scala:220, SSDVgg} — SSD-300 with a VGG-16 base, 6 feature maps,
per-map (loc, conf) conv heads, prior boxes and decode+NMS post-processing
(ObjectDetector wrapper + config, ObjectDetectionConfig.scala).

TPU-first: the backbone+heads are one NHWC graph Model; priors are a
constant baked at build; target assignment (prior matching) is vmapped
jnp so train batches stay fully on-device; post-processing reuses the
fixed-shape NMS (nms.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.models.objectdetection.bbox import (
    decode_boxes, generate_priors, match_priors)
from analytics_zoo_tpu.models.objectdetection.loss import MultiBoxLoss
from analytics_zoo_tpu.models.objectdetection.nms import batched_class_nms
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers.convolutional import Convolution2D
from analytics_zoo_tpu.nn.layers.core import Lambda
from analytics_zoo_tpu.nn.layers.merge import merge
from analytics_zoo_tpu.nn.layers.normalization import BatchNormalization
from analytics_zoo_tpu.nn.layers.pooling import MaxPooling2D

# SSD-300 pyramid config (Liu et al. 2016, reference SSDVgg)
SSD300_CONFIG = {
    "image_size": 300,
    "feature_sizes": (38, 19, 10, 5, 3, 1),
    "min_sizes": (30, 60, 111, 162, 213, 264),
    "max_sizes": (60, 111, 162, 213, 264, 315),
    "aspect_ratios": ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,)),
}


def _anchors_per_cell(ars: Sequence[float]) -> int:
    return 2 + 2 * len(ars)


def _conv_block(x, filters, k, name, strides=1, padding="same"):
    x = Convolution2D(filters, k, k, subsample=(strides, strides),
                      border_mode=padding, bias=False,
                      name=f"{name}_conv")(x)
    x = BatchNormalization(name=f"{name}_bn")(x)
    from analytics_zoo_tpu.nn.layers.core import Activation
    return Activation("relu")(x)


def build_ssd(class_num: int, config=SSD300_CONFIG,
              width_mult: float = 1.0) -> Tuple[Model, np.ndarray]:
    """Build the SSD graph and its priors.

    Output: a Model mapping image (B, S, S, 3) →
    [loc (B, P, 4), conf (B, P, class_num)].
    ``width_mult`` scales channel widths (tests use small nets).
    """
    S = config["image_size"]
    fsizes = config["feature_sizes"]
    ars = config["aspect_ratios"]

    def c(f):
        return max(8, int(f * width_mult))

    inp = Input(shape=(S, S, 3), name="image")
    x = inp
    feats = []
    sizes = []  # analytically tracked spatial size of each feature map
    s = S
    # VGG-ish trunk down to 38x38 (3 stride-2 stages for S=300)
    for i, f in enumerate((64, 128, 256)):
        x = _conv_block(x, c(f), 3, f"stage{i}a")
        x = _conv_block(x, c(f), 3, f"stage{i}b")
        x = MaxPooling2D((2, 2), border_mode="same")(x)
        s = -(-s // 2)
    x = _conv_block(x, c(512), 3, "conv4")
    feats.append(x); sizes.append(s)                  # ~38x38
    x = MaxPooling2D((2, 2), border_mode="same")(x)
    s = -(-s // 2)
    x = _conv_block(x, c(512), 3, "conv5")
    feats.append(x); sizes.append(s)                  # ~19x19
    x = _conv_block(x, c(256), 1, "conv6r")
    x = _conv_block(x, c(512), 3, "conv6", strides=2)
    s = -(-s // 2)
    feats.append(x); sizes.append(s)                  # ~10x10
    x = _conv_block(x, c(128), 1, "conv7r")
    x = _conv_block(x, c(256), 3, "conv7", strides=2)
    s = -(-s // 2)
    feats.append(x); sizes.append(s)                  # ~5x5
    x = _conv_block(x, c(128), 1, "conv8r")
    x = _conv_block(x, c(256), 3, "conv8", strides=2)
    s = -(-s // 2)
    feats.append(x); sizes.append(s)                  # ~3x3
    x = _conv_block(x, c(128), 1, "conv9r")
    if s == 3:
        # canonical SSD300 tail: 3x3 VALID stride-1 maps 3x3 -> 1x1;
        # other sizes keep the stride-2 SAME tail (ceil(s/2))
        x = _conv_block(x, c(256), 3, "conv9", strides=1, padding="valid")
        s = s - 2
    else:
        x = _conv_block(x, c(256), 3, "conv9", strides=2)
        s = -(-s // 2)
    feats.append(x); sizes.append(s)                  # 1x1

    if tuple(sizes) != tuple(fsizes):
        raise ValueError(
            f"SSD trunk produces feature maps {tuple(sizes)} but config "
            f"declares feature_sizes={tuple(fsizes)}; priors would not "
            "match the head outputs")

    locs, confs = [], []
    for i, (feat, ar) in enumerate(zip(feats, ars)):
        k = _anchors_per_cell(ar)
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"loc{i}")(feat)
        conf = Convolution2D(k * class_num, 3, 3, border_mode="same",
                             name=f"conf{i}")(feat)
        locs.append(Lambda(lambda t: t.reshape(t.shape[0], -1, 4),
                           name=f"loc_flat{i}")(loc))
        confs.append(Lambda(
            lambda t, _c=class_num: t.reshape(t.shape[0], -1, _c),
            name=f"conf_flat{i}")(conf))
    loc_all = merge(locs, mode="concat", concat_axis=1)
    conf_all = merge(confs, mode="concat", concat_axis=1)
    model = Model(inp, [loc_all, conf_all], name="ssd")

    priors = generate_priors(fsizes, S, config["min_sizes"],
                             config["max_sizes"], ars)
    head_priors = sum(sz * sz * _anchors_per_cell(ar)
                      for sz, ar in zip(sizes, ars))
    assert head_priors == priors.shape[0], (
        f"head prior count {head_priors} != generated priors "
        f"{priors.shape[0]}")
    return model, priors


class SSDTargetAssigner:
    """Convert (gt_boxes, gt_labels) padded batches into per-prior targets
    — the host-facing half of MultiBoxLoss (reference MultiBoxLoss's
    matching stage, vmapped and jitted here)."""

    def __init__(self, priors: np.ndarray, iou_threshold: float = 0.5):
        self.priors = jnp.asarray(priors)
        self.iou_threshold = iou_threshold
        self._assign = jax.jit(jax.vmap(
            lambda b, l: match_priors(b, l, self.priors,
                                      self.iou_threshold)))

    def __call__(self, gt_boxes: np.ndarray, gt_labels: np.ndarray
                 ) -> np.ndarray:
        """(B, G, 4), (B, G) → (B, P, 5) [loc targets | class target]."""
        loc_t, cls_t = self._assign(jnp.asarray(gt_boxes, jnp.float32),
                                    jnp.asarray(gt_labels, jnp.int32))
        return np.asarray(jnp.concatenate(
            [loc_t, cls_t[..., None].astype(jnp.float32)], axis=-1))


@register_model
class ObjectDetector(ZooModel):
    """SSD-based detector with bundled post-processing
    (reference models/image/objectdetection/ObjectDetector.scala +
    SSD.scala).  ``detect`` returns per-image (boxes, scores, labels)."""

    def __init__(self, class_num: int, config=None, width_mult: float = 1.0,
                 iou_threshold: float = 0.5):
        super().__init__()
        self.class_num = class_num
        self.config_dict = dict(config or SSD300_CONFIG)
        self.width_mult = width_mult
        self.iou_threshold = iou_threshold
        cfg = dict(self.config_dict)
        cfg["feature_sizes"] = tuple(cfg["feature_sizes"])
        cfg["min_sizes"] = tuple(cfg["min_sizes"])
        cfg["max_sizes"] = tuple(cfg["max_sizes"])
        cfg["aspect_ratios"] = tuple(tuple(a) for a in cfg["aspect_ratios"])
        self.model, self.priors = build_ssd(class_num, cfg, width_mult)
        self.assigner = SSDTargetAssigner(self.priors, iou_threshold)
        self._post = None

    def config(self):
        cd = self.config_dict
        return {"class_num": self.class_num,
                "config": {k: (list(v) if isinstance(v, (tuple, list))
                               else v) for k, v in cd.items()},
                "width_mult": self.width_mult,
                "iou_threshold": self.iou_threshold}

    def loss(self, neg_pos_ratio: float = 3.0) -> MultiBoxLoss:
        return MultiBoxLoss(neg_pos_ratio=neg_pos_ratio)

    def fit_detection(self, images, gt_boxes, gt_labels, **fit_kw):
        """Train: assigns per-prior targets then runs the estimator."""
        targets = self.assigner(gt_boxes, gt_labels)
        return self.model.fit(images, targets, **fit_kw)

    def detect(self, images: np.ndarray, batch_size: int = 8,
               score_threshold: float = 0.3, nms_threshold: float = 0.45,
               max_detections: int = 100):
        """Forward + decode + per-class NMS → list of
        (boxes (D, 4), scores (D,), labels (D,)) with D=max_detections."""
        est = self.model.estimator
        est._ensure_built([np.asarray(images)])
        if self._post is None:
            priors = jnp.asarray(self.priors)

            def post(loc, conf):
                boxes = decode_boxes(loc, priors)
                probs = jax.nn.softmax(conf, axis=-1)
                return jax.vmap(
                    lambda b, s: batched_class_nms(
                        b, s, iou_threshold=nms_threshold,
                        score_threshold=score_threshold,
                        max_total=max_detections))(boxes, probs)

            self._post = jax.jit(post)
        out = []
        n = len(images)
        for s in range(0, n, batch_size):
            chunk = np.asarray(images[s:s + batch_size], np.float32)
            loc, conf = est.predict_raw(chunk, batch_size=chunk.shape[0])
            b, sc, lb = self._post(loc, conf)
            for i in range(chunk.shape[0]):
                keep = np.asarray(sc[i]) > 0
                out.append((np.asarray(b[i])[keep], np.asarray(sc[i])[keep],
                            np.asarray(lb[i])[keep]))
        return out
