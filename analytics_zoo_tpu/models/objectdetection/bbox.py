"""Bounding-box utilities: IoU, prior (anchor) generation, encode/decode.

Reference capability: models/image/objectdetection/common/BboxUtil.scala
(1,033 LoC: bboxTransform/decode with variances, jaccard overlap, prior
matching) and ssd/PriorBox generation.

TPU-first: everything is vectorized jnp over fixed-size arrays — the IoU
matrix is one broadcasted min/max block, encode/decode are elementwise —
so the whole detection head stays inside one XLA program.  Boxes are
(x1, y1, x2, y2) normalized to [0, 1] throughout.
"""

from __future__ import annotations

import itertools
import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def iou_matrix(a, b):
    """Pairwise IoU. a (N, 4), b (M, 4) → (N, M)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0, None) * \
        jnp.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0, None) * \
        jnp.clip(b[:, 3] - b[:, 1], 0, None)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _corner_to_center(boxes):
    cx = (boxes[..., 0] + boxes[..., 2]) / 2.0
    cy = (boxes[..., 1] + boxes[..., 3]) / 2.0
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    return cx, cy, w, h


def encode_boxes(matched, priors, variances=(0.1, 0.2)):
    """gt corner boxes → loc regression targets relative to priors
    (reference BboxUtil encodeBoxes with SSD variances)."""
    gcx, gcy, gw, gh = _corner_to_center(matched)
    pcx, pcy, pw, ph = _corner_to_center(priors)
    eps = 1e-8
    dx = (gcx - pcx) / jnp.maximum(pw, eps) / variances[0]
    dy = (gcy - pcy) / jnp.maximum(ph, eps) / variances[0]
    dw = jnp.log(jnp.maximum(gw, eps) / jnp.maximum(pw, eps)) / variances[1]
    dh = jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ph, eps)) / variances[1]
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def decode_boxes(loc, priors, variances=(0.1, 0.2)):
    """loc predictions → corner boxes (inverse of encode_boxes)."""
    pcx, pcy, pw, ph = _corner_to_center(priors)
    cx = loc[..., 0] * variances[0] * pw + pcx
    cy = loc[..., 1] * variances[0] * ph + pcy
    w = pw * jnp.exp(loc[..., 2] * variances[1])
    h = ph * jnp.exp(loc[..., 3] * variances[1])
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def match_priors(gt_boxes, gt_labels, priors, iou_threshold: float = 0.5,
                 variances=(0.1, 0.2)):
    """Assign each prior a gt box/label (0 = background) — the SSD matching
    step (reference BboxUtil.matchBbox): best-prior-per-gt is forced
    matched, then any prior with IoU ≥ threshold.

    gt rows with label 0 are padding and never matched.
    Returns (loc_targets (P, 4), cls_targets (P,) int32).
    """
    gt_boxes = jnp.asarray(gt_boxes, jnp.float32)
    gt_labels = jnp.asarray(gt_labels, jnp.int32)
    valid = gt_labels > 0
    iou = iou_matrix(priors, gt_boxes) * valid[None, :]  # (P, G)

    best_gt_per_prior = jnp.argmax(iou, axis=1)          # (P,)
    best_iou_per_prior = jnp.max(iou, axis=1)
    # force each gt's best prior to match it
    best_prior_per_gt = jnp.argmax(iou, axis=0)          # (G,)
    g_idx = jnp.arange(gt_boxes.shape[0])
    best_gt_per_prior = best_gt_per_prior.at[best_prior_per_gt].set(
        jnp.where(valid, g_idx, best_gt_per_prior[best_prior_per_gt]))
    best_iou_per_prior = best_iou_per_prior.at[best_prior_per_gt].set(
        jnp.where(valid, 2.0, best_iou_per_prior[best_prior_per_gt]))

    matched_boxes = gt_boxes[best_gt_per_prior]
    matched_labels = gt_labels[best_gt_per_prior]
    cls_targets = jnp.where(best_iou_per_prior >= iou_threshold,
                            matched_labels, 0)
    loc_targets = encode_boxes(matched_boxes, priors, variances)
    return loc_targets, cls_targets.astype(jnp.int32)


def generate_priors(feature_sizes: Sequence[int], image_size: int,
                    min_sizes: Sequence[float], max_sizes: Sequence[float],
                    aspect_ratios: Sequence[Sequence[float]],
                    clip: bool = True) -> np.ndarray:
    """SSD prior boxes for a pyramid of feature maps
    (reference ssd/SSDVgg PriorBox params; Liu et al. 2016 §2.2).

    Per cell: square min_size anchor, sqrt(min*max) anchor, plus two per
    aspect ratio.  Returns (P, 4) corner boxes, normalized.
    """
    priors: List[Tuple[float, float, float, float]] = []
    for k, fsize in enumerate(feature_sizes):
        step = image_size / fsize
        s_min = min_sizes[k] / image_size
        s_max = max_sizes[k] / image_size
        for i, j in itertools.product(range(fsize), repeat=2):
            cx = (j + 0.5) * step / image_size
            cy = (i + 0.5) * step / image_size
            sizes = [(s_min, s_min), (math.sqrt(s_min * s_max),) * 2]
            for ar in aspect_ratios[k]:
                r = math.sqrt(ar)
                sizes.append((s_min * r, s_min / r))
                sizes.append((s_min / r, s_min * r))
            for w, h in sizes:
                priors.append((cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2))
    out = np.asarray(priors, np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return out
