"""Non-maximum suppression — fixed-shape, jit/vmap-able.

Reference capability: models/image/objectdetection/common/Nms.scala
(greedy IoU suppression inside BboxUtil post-processing).

TPU-first: NMS is notoriously serial; here it is a ``lax.fori_loop`` over
a *fixed* ``max_output`` count with an O(N) suppression mask update per
step — no dynamic shapes, no host round-trip, vmap-able over batch and
class.  (SURVEY §2.3 lists NMS as a Pallas candidate; the fori_loop form
already keeps the whole detection post-process on-device, and XLA fuses
the mask updates — revisit with a kernel only if profiling demands.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from analytics_zoo_tpu.models.objectdetection.bbox import iou_matrix


def nms(boxes, scores, iou_threshold: float = 0.45,
        score_threshold: float = 0.01, max_output: int = 100
        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS. boxes (N, 4), scores (N,) →
    (indices (max_output,) int32 with -1 padding, count ()).

    Deterministic, fixed output size — callers mask on index >= 0.
    """
    boxes = jnp.asarray(boxes, jnp.float32)
    scores = jnp.asarray(scores, jnp.float32)
    n = boxes.shape[0]
    iou = iou_matrix(boxes, boxes)                     # (N, N)
    alive = scores >= score_threshold
    m = min(max_output, n)

    def body(i, carry):
        alive, out, count = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        out = out.at[i].set(jnp.where(ok, best.astype(jnp.int32), -1))
        count = count + ok.astype(jnp.int32)
        # suppress the chosen box and all overlapping ones
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(n) == best)
        alive = alive & jnp.where(ok, ~suppress, alive)
        return alive, out, count

    out0 = jnp.full((m,), -1, jnp.int32)
    _, out, count = lax.fori_loop(0, m, body, (alive, out0,
                                               jnp.int32(0)))
    if m < max_output:
        out = jnp.concatenate(
            [out, jnp.full((max_output - m,), -1, jnp.int32)])
    return out, count


def batched_class_nms(boxes, class_scores, iou_threshold: float = 0.45,
                      score_threshold: float = 0.01,
                      max_per_class: int = 50, max_total: int = 100):
    """Per-class NMS over one image's decoded boxes.

    boxes (P, 4), class_scores (P, C) with class 0 = background.
    Returns (boxes (max_total, 4), scores (max_total,),
    labels (max_total,) int32 — 0 where padded).
    """
    P, C = class_scores.shape

    def per_class(c_scores):
        idx, _ = nms(boxes, c_scores, iou_threshold, score_threshold,
                     max_per_class)
        sel = jnp.clip(idx, 0, P - 1)
        valid = idx >= 0
        return boxes[sel], jnp.where(valid, c_scores[sel], -jnp.inf)

    # vmap over foreground classes (skip background column 0)
    cls_boxes, cls_scores = jax.vmap(per_class, in_axes=1)(
        class_scores[:, 1:])
    n_fg = C - 1
    labels = jnp.broadcast_to(jnp.arange(1, C)[:, None],
                              (n_fg, max_per_class))
    flat_boxes = cls_boxes.reshape(-1, 4)
    flat_scores = cls_scores.reshape(-1)
    flat_labels = labels.reshape(-1)
    top = jnp.argsort(-flat_scores)[:max_total]
    out_scores = flat_scores[top]
    keep = jnp.isfinite(out_scores)
    return (jnp.where(keep[:, None], flat_boxes[top], 0.0),
            jnp.where(keep, out_scores, 0.0),
            jnp.where(keep, flat_labels[top], 0).astype(jnp.int32))
