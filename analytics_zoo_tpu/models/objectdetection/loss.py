"""MultiBox loss: smooth-L1 localization + softmax confidence with hard
negative mining.

Reference capability: models/image/objectdetection/common/MultiBoxLoss.scala
(622 LoC).  The reference mines negatives with host-side sorts per image;
here mining is a fully vectorized top-k-by-rank trick inside the jitted
loss — no dynamic shapes (the negative count varies per image, but ranks
are compared against a per-image scalar, which XLA handles as data).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


def multibox_loss(loc_preds, cls_logits, loc_targets, cls_targets,
                  neg_pos_ratio: float = 3.0, loc_weight: float = 1.0):
    """SSD training loss.

    loc_preds (B, P, 4), cls_logits (B, P, C),
    loc_targets (B, P, 4), cls_targets (B, P) int (0 = background).
    """
    pos = cls_targets > 0                                    # (B, P)
    num_pos = jnp.sum(pos, axis=1)                           # (B,)

    # localization: smooth L1 over positive priors only
    loc_l = jnp.sum(smooth_l1(loc_preds - loc_targets), axis=-1)
    loc_loss = jnp.sum(loc_l * pos, axis=1)

    # confidence: per-prior CE
    logp = jax.nn.log_softmax(cls_logits, axis=-1)
    ce = -jnp.take_along_axis(logp, cls_targets[..., None],
                              axis=-1)[..., 0]               # (B, P)

    # hard negative mining: keep the neg_pos_ratio * num_pos highest-loss
    # background priors (rank trick: a negative is kept iff its CE rank
    # among negatives < limit)
    neg_ce = jnp.where(pos, -jnp.inf, ce)
    order = jnp.argsort(-neg_ce, axis=1)
    ranks = jnp.argsort(order, axis=1)                       # rank of each
    num_neg = jnp.minimum(neg_pos_ratio * num_pos,
                          jnp.sum(~pos, axis=1)).astype(jnp.int32)
    neg_keep = ranks < num_neg[:, None]
    conf_loss = jnp.sum(ce * (pos | (neg_keep & ~pos)), axis=1)

    denom = jnp.maximum(num_pos.astype(jnp.float32), 1.0)
    return jnp.mean((loc_weight * loc_loss + conf_loss) / denom)


class MultiBoxLoss:
    """Loss object binding priors: call with (y_true, y_pred) where
    y_true = (gt_boxes (B, G, 4), gt_labels (B, G)) already matched into
    per-prior targets by ``SSDTargetAssigner`` — see ssd.py."""

    def __init__(self, neg_pos_ratio: float = 3.0, loc_weight: float = 1.0):
        self.neg_pos_ratio = neg_pos_ratio
        self.loc_weight = loc_weight
        self.batch_structured = True  # couples priors across the batch mean

    def __call__(self, y_true, y_pred):
        loc_preds, cls_logits = y_pred
        loc_t = y_true[..., :4]
        cls_t = y_true[..., 4].astype(jnp.int32)
        return multibox_loss(loc_preds, cls_logits, loc_t, cls_t,
                             self.neg_pos_ratio, self.loc_weight)
