"""ZooModel base — save/load + predict conveniences for built-in models.

Reference capability: models/common/ZooModel.scala (save/load with a model
registry; KerasZooModel:183 wraps a KerasNet).  Here a ZooModel owns a
``KerasNet`` (Sequential/Model) plus its hyper-parameters; persistence is
the framework checkpoint format + a JSON config so ``ZooModel.load``
reconstructs the architecture then restores weights.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

_MODEL_REGISTRY: Dict[str, type] = {}


def register_model(cls):
    _MODEL_REGISTRY[cls.__name__] = cls
    return cls


class ZooModel:
    """Base for built-in zoo models."""

    def __init__(self):
        self.model = None  # KerasNet, set by subclass build()

    # -- construction -----------------------------------------------------
    def build(self):
        raise NotImplementedError

    def config(self) -> Dict[str, Any]:
        """JSON-serializable constructor kwargs."""
        raise NotImplementedError

    # -- training facade --------------------------------------------------
    def compile(self, *a, **kw):
        self.model.compile(*a, **kw)
        self._restore_pending_weights()
        return self

    def fit(self, *a, **kw):
        return self.model.fit(*a, **kw)

    def evaluate(self, *a, **kw):
        return self.model.evaluate(*a, **kw)

    def predict(self, *a, **kw):
        return self.model.predict(*a, **kw)

    def set_checkpoint(self, path: str, over_write: bool = True):
        self.model.set_checkpoint(path, over_write=over_write)
        return self

    def set_tensorboard(self, log_dir: str, app_name: str = "zoo"):
        self.model.set_tensorboard(log_dir, app_name)
        return self

    @property
    def estimator(self):
        return self.model.estimator

    # -- persistence ------------------------------------------------------
    def save_model(self, path: str) -> None:
        """Save config + weights (reference ZooModel.saveModel)."""
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump({"class": type(self).__name__, "config": self.config()},
                      f, indent=2)
        est = self.model._estimator
        if est is not None and est.params is not None:
            from analytics_zoo_tpu.train import checkpoint as ckpt
            ckpt.save_pytree(os.path.join(path, "weights.npz"),
                             {"params": est.params, "state": est.state})

    @classmethod
    def load_model(cls, path: str) -> "ZooModel":
        with open(os.path.join(path, "config.json")) as f:
            blob = json.load(f)
        model_cls = _MODEL_REGISTRY.get(blob["class"])
        if model_cls is None:
            raise ValueError(f"unknown model class {blob['class']}; "
                             f"registered: {sorted(_MODEL_REGISTRY)}")
        inst = model_cls(**blob["config"])
        wpath = os.path.join(path, "weights.npz")
        if os.path.exists(wpath):
            from analytics_zoo_tpu.train import checkpoint as ckpt
            tree = ckpt.load_pytree(wpath)
            inst._pending_weights = tree
        return inst

    def _restore_pending_weights(self):
        """Hand loaded weights to the estimator (applied at first build,
        or immediately if already built)."""
        tree = getattr(self, "_pending_weights", None)
        if tree is None:
            return
        self.model.estimator.set_initial_weights(tree["params"],
                                                 tree.get("state", {}))
        self._pending_weights = None
