"""Recommendation models: NeuralCF, WideAndDeep, SessionRecommender.

Reference capability: models/recommendation/ —
``NeuralCF`` (NeuralCF.scala:45-103: GMF embeddings ⊙ + MLP tower, concat,
class-softmax head), ``WideAndDeep`` (WideAndDeep.scala, 365 LoC),
``SessionRecommender`` (209 LoC, GRU over session item sequences),
``Recommender`` base with recommendForUser/recommendForItem (105 LoC) and
negative-sampling utilities (Utils.scala:325).

TPU-first notes: embeddings are dense gather tables (XLA gather on the
vector unit); the concat+MLP lowers to a handful of MXU matmuls; the whole
forward is one fused program.  Ratings/classes follow the reference's
1-based convention at the API surface, 0-based internally.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.core.context import explicit_prng_key
from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers.core import Dense, Dropout, Flatten
from analytics_zoo_tpu.nn.layers.embedding import Embedding, EmbeddingBag
from analytics_zoo_tpu.nn.layers.merge import merge
from analytics_zoo_tpu.nn.layers.recurrent import GRU
from analytics_zoo_tpu.nn.layers.sharded_embedding import ShardedEmbeddingTable

TABLE_PLACEMENTS = ("auto", "replicated", "sharded")


def _route_tables(requested: str, tables: Sequence[Tuple[str, int, int]]
                  ) -> Tuple[str, ...]:
    """Which of ``(name, rows, dim)`` embedding tables get the sharded
    layer.  ``replicated`` keeps every table on the original dense
    layers (byte-for-byte the pre-sharding build); ``sharded`` forces
    the sharded layer for ALL tables (its ROW_ALIGN-padded param shape
    is topology-invariant, so checkpoints move across mesh widths even
    if the current mesh can't actually split the rows — the layer just
    lowers dense); ``auto`` asks the placement router per table from
    its nbytes vs the device budget and the live mesh
    (parallel/table_sharding.py, counted in
    ``table_placement_selected_total``)."""
    if requested not in TABLE_PLACEMENTS:
        raise ValueError(f"table_placement must be one of "
                         f"{TABLE_PLACEMENTS}, got {requested!r}")
    if requested == "replicated":
        return ()
    from analytics_zoo_tpu.parallel.table_sharding import (
        choose_table_placement, padded_rows)
    picked = []
    for name, rows, dim in tables:
        nbytes = padded_rows(rows) * dim * 4
        decision = choose_table_placement(nbytes=nbytes, rows=rows,
                                          requested=requested)
        if requested == "sharded" or decision.placement in ("sharded",
                                                            "stream"):
            picked.append(name)
    return tuple(picked)


class Recommender(ZooModel):
    """Base with pair-scoring / top-K recommendation helpers
    (reference models/recommendation/Recommender.scala)."""

    def predict_user_item_pair(self, user_ids: np.ndarray,
                               item_ids: np.ndarray,
                               batch_size: int = 1024) -> np.ndarray:
        """Class probabilities for (user, item) pairs."""
        u = np.asarray(user_ids).reshape(-1, 1).astype(np.int32)
        i = np.asarray(item_ids).reshape(-1, 1).astype(np.int32)
        return self.model.predict([u, i], batch_size=batch_size)

    def recommend_for_user(self, user_id: int, candidate_items: np.ndarray,
                           max_items: int = 10) -> List[Tuple[int, float]]:
        items = np.asarray(candidate_items)
        users = np.full_like(items, user_id)
        probs = self.predict_user_item_pair(users, items)
        # score = P(high rating): expected normalized rating
        if probs.shape[-1] > 1:
            classes = np.arange(1, probs.shape[-1] + 1)
            scores = (probs * classes).sum(-1)
        else:
            scores = probs[:, 0]
        order = np.argsort(-scores)[:max_items]
        return [(int(items[j]), float(scores[j])) for j in order]

    def recommend_for_item(self, item_id: int, candidate_users: np.ndarray,
                           max_users: int = 10) -> List[Tuple[int, float]]:
        users = np.asarray(candidate_users)
        items = np.full_like(users, item_id)
        probs = self.predict_user_item_pair(users, items)
        if probs.shape[-1] > 1:
            classes = np.arange(1, probs.shape[-1] + 1)
            scores = (probs * classes).sum(-1)
        else:
            scores = probs[:, 0]
        order = np.argsort(-scores)[:max_users]
        return [(int(users[j]), float(scores[j])) for j in order]


@register_model
class NeuralCF(Recommender):
    """Neural Collaborative Filtering (reference NeuralCF.scala:45-103).

    Two towers over (user, item) ids:
      - GMF: mf embeddings, elementwise product
      - MLP: embeddings concat -> hidden stack
    concat -> Dense(num_classes, softmax).  ``include_mf=False`` drops GMF.
    """

    def __init__(self, user_count: int, item_count: int, class_num: int = 5,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20,
                 dropout: float = 0.0, table_placement: str = "auto"):
        super().__init__()
        if class_num < 2:
            # softmax over 1 class is constant 1.0 — the model would
            # train to nothing, silently; binary tasks use class_num=2
            raise ValueError(
                f"class_num must be >= 2, got {class_num} (the head is "
                "a softmax; use class_num=2 with int {0,1} labels for "
                "binary ratings)")
        if not 0.0 <= dropout < 1.0:
            # dropout=1.0 would zero the whole MLP tower every training
            # step — silent degradation, like class_num=1 above
            raise ValueError(f"dropout must be in [0, 1), got {dropout}")
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = tuple(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed
        # regularization knob beyond the reference (its NeuralCF has no
        # dropout); applied between MLP tower layers at training time
        self.dropout = dropout
        self.table_placement = table_placement
        self.build()

    def config(self):
        return dict(user_count=self.user_count, item_count=self.item_count,
                    class_num=self.class_num, user_embed=self.user_embed,
                    item_embed=self.item_embed,
                    hidden_layers=list(self.hidden_layers),
                    include_mf=self.include_mf, mf_embed=self.mf_embed,
                    dropout=self.dropout,
                    table_placement=self.table_placement)

    def build(self):
        user = Input(shape=(1,), dtype=jnp.int32, name="user")
        item = Input(shape=(1,), dtype=jnp.int32, name="item")

        # +1: ids are 1-based at the API surface (MovieLens convention kept
        # from the reference); row 0 is an unused pad row.
        specs = [("mlp_user_embed", self.user_count + 1, self.user_embed),
                 ("mlp_item_embed", self.item_count + 1, self.item_embed)]
        if self.include_mf:
            specs += [("mf_user_embed", self.user_count + 1, self.mf_embed),
                      ("mf_item_embed", self.item_count + 1, self.mf_embed)]
        sharded = _route_tables(self.table_placement, specs)

        def embed(name, rows, dim, ids):
            if name in sharded:
                return ShardedEmbeddingTable(rows, dim, name=name)(ids)
            return Embedding(rows, dim, name=name)(ids)

        mlp_u = Flatten()(embed("mlp_user_embed", self.user_count + 1,
                                self.user_embed, user))
        mlp_i = Flatten()(embed("mlp_item_embed", self.item_count + 1,
                                self.item_embed, item))
        h = merge([mlp_u, mlp_i], mode="concat")
        for k, width in enumerate(self.hidden_layers):
            h = Dense(width, activation="relu", name=f"mlp_dense_{k}")(h)
            if self.dropout > 0:
                h = Dropout(self.dropout, name=f"mlp_drop_{k}")(h)

        if self.include_mf:
            mf_u = Flatten()(embed("mf_user_embed", self.user_count + 1,
                                   self.mf_embed, user))
            mf_i = Flatten()(embed("mf_item_embed", self.item_count + 1,
                                   self.mf_embed, item))
            gmf = merge([mf_u, mf_i], mode="mul")
            h = merge([gmf, h], mode="concat")

        out = Dense(self.class_num, activation="softmax", name="ncf_head")(h)
        self.model = Model([user, item], out, name="NeuralCF")
        # manifests the Estimator reads: which tables shard over the
        # model axis (strategy wrap), and which may grow rows elastically
        # between a snapshot and a restore
        self.model._sharded_tables = sharded
        self.model._elastic_tables = tuple(n for n, _, _ in specs)
        return self


@register_model
class WideAndDeep(Recommender):
    """Wide & Deep (reference WideAndDeep.scala).

    wide: sparse cross-features via a linear layer on multi-hot indices —
    realised as an Embedding(dim=class_num) summed over the wide indices
    (a gather+sum, equivalent to sparse W·x on TPU).
    deep: embedding columns + continuous features -> MLP.
    ``model_type``: "wide" | "deep" | "wide_n_deep".
    """

    def __init__(self, class_num: int, model_type: str = "wide_n_deep",
                 wide_base_dims: Sequence[int] = (),
                 wide_cross_dims: Sequence[int] = (),
                 indicator_dims: Sequence[int] = (),
                 embed_in_dims: Sequence[int] = (),
                 embed_out_dims: Sequence[int] = (),
                 continuous_cols: int = 0,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 table_placement: str = "auto"):
        super().__init__()
        if class_num < 2:
            raise ValueError(
                f"class_num must be >= 2, got {class_num} (softmax head; "
                "use class_num=2 for binary targets)")
        self.class_num = class_num
        self.model_type = model_type
        self.wide_base_dims = tuple(wide_base_dims)
        self.wide_cross_dims = tuple(wide_cross_dims)
        self.indicator_dims = tuple(indicator_dims)
        self.embed_in_dims = tuple(embed_in_dims)
        self.embed_out_dims = tuple(embed_out_dims)
        self.continuous_cols = continuous_cols
        self.hidden_layers = tuple(hidden_layers)
        self.table_placement = table_placement
        self.build()

    def config(self):
        return dict(class_num=self.class_num, model_type=self.model_type,
                    wide_base_dims=list(self.wide_base_dims),
                    wide_cross_dims=list(self.wide_cross_dims),
                    indicator_dims=list(self.indicator_dims),
                    embed_in_dims=list(self.embed_in_dims),
                    embed_out_dims=list(self.embed_out_dims),
                    continuous_cols=self.continuous_cols,
                    hidden_layers=list(self.hidden_layers),
                    table_placement=self.table_placement)

    def build(self):
        inputs = []
        towers = []
        wide_dims = self.wide_base_dims + self.wide_cross_dims

        specs = []
        if self.model_type in ("wide", "wide_n_deep") and wide_dims:
            specs.append(("wide_linear", int(np.sum(wide_dims)),
                          self.class_num))
        if self.model_type in ("deep", "wide_n_deep"):
            specs += [(f"deep_embed_{k}", in_d + 1, out_d)
                      for k, (in_d, out_d) in enumerate(
                          zip(self.embed_in_dims, self.embed_out_dims))]
        sharded = _route_tables(self.table_placement, specs)

        if self.model_type in ("wide", "wide_n_deep") and wide_dims:
            # wide input: one id per wide column, offset into a shared table
            wide_in = Input(shape=(len(wide_dims),), dtype=jnp.int32,
                            name="wide_input")
            inputs.append(wide_in)
            total = int(np.sum(wide_dims))
            # one fused gather+sum (ops/embedding_bag.py) instead of an
            # Embedding followed by a Lambda-sum: the (B, n_wide,
            # class_num) gathered rows never materialise.  pad_id=None —
            # every wide id is a live feature (offsets start at 0).
            if "wide_linear" in sharded:
                wide_sum = ShardedEmbeddingTable(
                    total, self.class_num, combiner="sum", init="zero",
                    pad_id=None, name="wide_linear")(wide_in)
            else:
                wide_sum = EmbeddingBag(total, self.class_num,
                                        combiner="sum", init="zero",
                                        pad_id=None,
                                        name="wide_linear")(wide_in)
            towers.append(wide_sum)

        if self.model_type in ("deep", "wide_n_deep"):
            deep_parts = []
            if self.indicator_dims:
                ind_in = Input(shape=(int(np.sum(self.indicator_dims)),),
                               name="indicator_input")
                inputs.append(ind_in)
                deep_parts.append(ind_in)
            if self.embed_in_dims:
                embed_in = Input(shape=(len(self.embed_in_dims),),
                                 dtype=jnp.int32, name="embed_input")
                inputs.append(embed_in)
                for k, (in_d, out_d) in enumerate(
                        zip(self.embed_in_dims, self.embed_out_dims)):
                    col = embed_in.slice(1, k, 1)
                    name = f"deep_embed_{k}"
                    layer = (ShardedEmbeddingTable(in_d + 1, out_d,
                                                   name=name)
                             if name in sharded
                             else Embedding(in_d + 1, out_d, name=name))
                    deep_parts.append(Flatten()(layer(col)))
            if self.continuous_cols:
                cont_in = Input(shape=(self.continuous_cols,),
                                name="continuous_input")
                inputs.append(cont_in)
                deep_parts.append(cont_in)
            h = (merge(deep_parts, mode="concat")
                 if len(deep_parts) > 1 else deep_parts[0])
            for k, width in enumerate(self.hidden_layers):
                h = Dense(width, activation="relu", name=f"deep_dense_{k}")(h)
            deep_out = Dense(self.class_num, name="deep_head")(h)
            towers.append(deep_out)

        logits = towers[0] if len(towers) == 1 else merge(towers, mode="sum")
        from analytics_zoo_tpu.nn.layers.core import Activation
        out = Activation("softmax", name="wnd_softmax")(logits)
        self.model = Model(inputs, out, name="WideAndDeep")
        self.model._sharded_tables = sharded
        self.model._elastic_tables = tuple(n for n, _, _ in specs)
        return self


@register_model
class SessionRecommender(ZooModel):
    """Session-based recommender (reference SessionRecommender.scala):
    GRU over the session item sequence (optionally + history mlp) ->
    softmax over items."""

    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 10, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 5):
        super().__init__()
        self.item_count = item_count
        self.item_embed = item_embed
        self.rnn_hidden_layers = tuple(rnn_hidden_layers)
        self.session_length = session_length
        self.include_history = include_history
        self.mlp_hidden_layers = tuple(mlp_hidden_layers)
        self.history_length = history_length
        self.build()

    def config(self):
        return dict(item_count=self.item_count, item_embed=self.item_embed,
                    rnn_hidden_layers=list(self.rnn_hidden_layers),
                    session_length=self.session_length,
                    include_history=self.include_history,
                    mlp_hidden_layers=list(self.mlp_hidden_layers),
                    history_length=self.history_length)

    def build(self):
        session = Input(shape=(self.session_length,), dtype=jnp.int32,
                        name="session_input")
        inputs = [session]
        h = Embedding(self.item_count + 1, self.item_embed,
                      name="session_embed")(session)
        for k, width in enumerate(self.rnn_hidden_layers[:-1]):
            h = GRU(width, return_sequences=True, name=f"session_gru_{k}")(h)
        h = GRU(self.rnn_hidden_layers[-1], name="session_gru_last")(h)

        if self.include_history:
            hist = Input(shape=(self.history_length,), dtype=jnp.int32,
                         name="history_input")
            inputs.append(hist)
            g = Flatten()(Embedding(self.item_count + 1, self.item_embed,
                                    name="history_embed")(hist))
            for k, width in enumerate(self.mlp_hidden_layers):
                g = Dense(width, activation="relu", name=f"history_mlp_{k}")(g)
            h = merge([h, g], mode="concat")

        out = Dense(self.item_count + 1, activation="softmax",
                    name="session_head")(h)
        self.model = Model(inputs, out, name="SessionRecommender")
        return self

    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 10):
        probs = self.model.predict(np.asarray(sessions, np.int32))
        top = np.argsort(-probs, axis=-1)[:, :max_items]
        return [[(int(i), float(p[i])) for i in row]
                for row, p in zip(top, probs)]


# ---------------------------------------------------------------------------
# Data utilities (reference models/recommendation/Utils.scala:325)
# ---------------------------------------------------------------------------

def negative_sample(user_ids: np.ndarray, item_ids: np.ndarray,
                    item_count: int, neg_per_pos: int = 1, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Generate implicit-feedback negatives: for each positive (u, i) pair,
    sample ``neg_per_pos`` items the user has not interacted with.
    Returns (users, items, labels) with labels 1/0 (1-based ratings keep
    their value for positives in the multi-class setup)."""
    rs = np.random.RandomState(seed)
    seen = {}
    for u, i in zip(user_ids, item_ids):
        seen.setdefault(int(u), set()).add(int(i))
    neg_u, neg_i = [], []
    for u in user_ids:
        s = seen[int(u)]
        for _ in range(neg_per_pos):
            j = int(rs.randint(1, item_count + 1))
            tries = 0
            while j in s and tries < 10:
                j = int(rs.randint(1, item_count + 1))
                tries += 1
            neg_u.append(u)
            neg_i.append(j)
    users = np.concatenate([user_ids, np.asarray(neg_u)])
    items = np.concatenate([item_ids, np.asarray(neg_i)])
    labels = np.concatenate([np.ones(len(user_ids)), np.zeros(len(neg_u))])
    perm = rs.permutation(len(users))
    return users[perm], items[perm], labels[perm]


def presample_implicit_epochs(user_ids, item_ids, item_count: int, *,
                              epochs: int, neg_per_pos: int = 1,
                              seed: int = 0, trim_multiple: int = 1,
                              user_count: Optional[int] = None):
    """Device-resident negative sampling for ALL epochs in one jitted
    program (the reference samples on the Spark executors per epoch,
    models/recommendation/Utils.scala:325 — here the chip does it).

    For each epoch: every positive (u, i) contributes itself plus
    ``neg_per_pos`` uniform negatives, re-sampled against the user's seen
    set (three fixed rejection rounds over a dense seen-matrix gather —
    residual collision odds after three rounds are (seen/item_count)^4,
    i.e. ~1e-7 for MovieLens-1M densities), then the epoch stream is
    shuffled on device.  Returns ``(users, items, labels)`` int32 device
    arrays of shape (epochs, S) with S trimmed to a multiple of
    ``trim_multiple`` (pass batch*steps_per_execution so ``fit`` drops
    nothing).  Feeding epoch slices straight to ``Estimator.fit`` keeps
    the whole training run device-resident: zero host→device bytes per
    epoch.
    """
    import jax

    n_pos = int(len(user_ids))
    uc = int(user_count if user_count is not None else np.max(user_ids))
    seen = np.zeros((uc + 1, item_count + 1), np.bool_)
    seen[np.asarray(user_ids, np.int64),
         np.asarray(item_ids, np.int64)] = True
    seen[:, 0] = True                          # pad item never sampled
    pos_u = jnp.asarray(np.asarray(user_ids, np.int32))
    pos_i = jnp.asarray(np.asarray(item_ids, np.int32))
    seen_d = jnp.asarray(seen)
    s_raw = n_pos * (1 + neg_per_pos)
    s_out = (s_raw // trim_multiple) * trim_multiple
    if s_out == 0:
        raise ValueError(
            f"trim_multiple={trim_multiple} exceeds the epoch stream "
            f"({s_raw} samples = {n_pos} positives x (1+{neg_per_pos})); "
            "no multiple fits — lower batch*steps_per_execution")

    def one_epoch(key):
        k_neg, k_rej, k_perm = jax.random.split(key, 3)
        neg_u = jnp.repeat(pos_u, neg_per_pos)
        neg_i = jax.random.randint(k_neg, (n_pos * neg_per_pos,), 1,
                                   item_count + 1, jnp.int32)
        for _ in range(3):                     # fixed rejection rounds
            k_rej, k_draw = jax.random.split(k_rej)
            redraw = jax.random.randint(k_draw, neg_i.shape, 1,
                                        item_count + 1, jnp.int32)
            neg_i = jnp.where(seen_d[neg_u, neg_i], redraw, neg_i)
        users = jnp.concatenate([pos_u, neg_u])
        items = jnp.concatenate([pos_i, neg_i])
        labels = jnp.concatenate(
            [jnp.ones((n_pos,), jnp.int32),
             jnp.zeros((n_pos * neg_per_pos,), jnp.int32)])
        perm = jax.random.permutation(k_perm, users.shape[0])[:s_out]
        return users[perm], items[perm], labels[perm]

    keys = jax.random.split(explicit_prng_key(seed), epochs)
    return jax.jit(jax.vmap(one_epoch))(keys)
