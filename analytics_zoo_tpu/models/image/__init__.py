from analytics_zoo_tpu.models.image.imageclassification import (  # noqa: F401
    ImageClassifier,
    inception_v1,
    mobilenet,
    resnet50,
    vgg16,
)
