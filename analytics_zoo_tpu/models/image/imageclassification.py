"""Image classification: ImageClassifier + ResNet/Inception-v1/MobileNet/VGG.

Reference capability: models/image/imageclassification/ — ``ImageClassifier``
with per-model preprocessing configs (ImageClassificationConfig.scala:190)
and the Scala examples' Inception-v1 (examples/inception/Train.scala) and
ResNet trainers.

TPU-first: all nets are NHWC, every conv+BN+relu block is left for XLA to
fuse, and the default width/batch guidance targets MXU-friendly shapes
(channels multiples of 128 at the wide layers of ResNet-50).  Builders
return graph ``Model``s over the autograd DSL.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.common import ZooModel, register_model
from analytics_zoo_tpu.nn import Input, Model, Sequential
from analytics_zoo_tpu.nn.layers.convolutional import Convolution2D, SeparableConvolution2D
from analytics_zoo_tpu.nn.layers.core import Activation, Dense, Dropout, Flatten
from analytics_zoo_tpu.nn.layers.merge import merge
from analytics_zoo_tpu.nn.layers.normalization import BatchNormalization
from analytics_zoo_tpu.nn.layers.pooling import (
    AveragePooling2D, GlobalAveragePooling2D, MaxPooling2D)


def _conv_bn(x, filters, k, strides=1, activation="relu", name=None,
             border_mode="same", bn_stats_fraction=1.0, bn_momentum=0.99):
    x = Convolution2D(filters, k, k, subsample=(strides, strides),
                      border_mode=border_mode, bias=False,
                      name=None if name is None else f"{name}_conv")(x)
    x = BatchNormalization(name=None if name is None else f"{name}_bn",
                           stats_fraction=bn_stats_fraction,
                           momentum=bn_momentum)(x)
    if activation:
        x = Activation(activation)(x)
    return x


# ---------------------------------------------------------------- ResNet --

def _bottleneck(x, filters, strides=1, downsample=False, name="",
                bn_stats_fraction=1.0, bn_momentum=0.99):
    shortcut = x
    if downsample:
        shortcut = Convolution2D(filters * 4, 1, 1,
                                 subsample=(strides, strides),
                                 border_mode="same", bias=False,
                                 name=f"{name}_proj")(x)
        shortcut = BatchNormalization(
            name=f"{name}_proj_bn", momentum=bn_momentum,
            stats_fraction=bn_stats_fraction)(shortcut)
    y = _conv_bn(x, filters, 1, strides=strides, name=f"{name}_a",
                 bn_stats_fraction=bn_stats_fraction,
                 bn_momentum=bn_momentum)
    y = _conv_bn(y, filters, 3, name=f"{name}_b",
                 bn_stats_fraction=bn_stats_fraction,
                 bn_momentum=bn_momentum)
    y = Convolution2D(filters * 4, 1, 1, border_mode="same", bias=False,
                      name=f"{name}_c_conv")(y)
    y = BatchNormalization(name=f"{name}_c_bn", momentum=bn_momentum,
                           stats_fraction=bn_stats_fraction)(y)
    out = merge([y, shortcut], mode="sum")
    return Activation("relu")(out)


def resnet50(class_num: int = 1000,
             input_shape: Sequence[int] = (224, 224, 3),
             space_to_depth_stem: bool = True,
             bn_stats_fraction: float = 1.0,
             bn_momentum: float = 0.99) -> Model:
    """ResNet-50 (bottleneck [3,4,6,3]).  Reference: examples/resnet/ and
    ImageClassificationConfig 'resnet-50' entry.

    ``space_to_depth_stem`` computes the 7x7/s2 stem as a mathematically
    identical 4x4/s1 conv over a space-to-depth input (same params, same
    outputs — see SpaceToDepthStemConv) for MXU utilisation; disable to
    run the literal 7x7 conv."""
    from analytics_zoo_tpu.nn.layers.convolutional import SpaceToDepthStemConv

    inp = Input(shape=tuple(input_shape), name="input")
    if space_to_depth_stem and input_shape[0] % 2 == 0 \
            and input_shape[1] % 2 == 0:
        x = SpaceToDepthStemConv(64, bias=False, name="stem_conv")(inp)
    else:
        x = Convolution2D(64, 7, 7, subsample=(2, 2), border_mode="same",
                          bias=False, name="stem_conv")(inp)
    x = BatchNormalization(name="stem_bn", momentum=bn_momentum,
                           stats_fraction=bn_stats_fraction)(x)
    x = Activation("relu")(x)
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    for stage, (blocks, filters) in enumerate(
            [(3, 64), (4, 128), (6, 256), (3, 512)]):
        for b in range(blocks):
            strides = 2 if (b == 0 and stage > 0) else 1
            x = _bottleneck(x, filters, strides=strides, downsample=(b == 0),
                            name=f"s{stage}b{b}",
                            bn_stats_fraction=bn_stats_fraction,
                            bn_momentum=bn_momentum)
    x = GlobalAveragePooling2D()(x)
    x = Dense(class_num, name="fc")(x)
    return Model(inp, x, name="resnet50")


# ------------------------------------------------------------- Inception --

def _inception_block(x, c1, c3r, c3, c5r, c5, pp, name=""):
    b1 = _conv_bn(x, c1, 1, name=f"{name}_1x1")
    b3 = _conv_bn(x, c3r, 1, name=f"{name}_3x3r")
    b3 = _conv_bn(b3, c3, 3, name=f"{name}_3x3")
    b5 = _conv_bn(x, c5r, 1, name=f"{name}_5x5r")
    b5 = _conv_bn(b5, c5, 5, name=f"{name}_5x5")
    bp = MaxPooling2D((3, 3), strides=(1, 1), border_mode="same")(x)
    bp = _conv_bn(bp, pp, 1, name=f"{name}_pool")
    return merge([b1, b3, b5, bp], mode="concat", concat_axis=-1)


def inception_v1(class_num: int = 1000,
                 input_shape: Sequence[int] = (224, 224, 3)) -> Model:
    """GoogLeNet / Inception-v1 (reference examples/inception/Train.scala,
    BN variant for stable large-batch TPU training)."""
    inp = Input(shape=tuple(input_shape), name="input")
    x = _conv_bn(inp, 64, 7, strides=2, name="stem1")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = _conv_bn(x, 64, 1, name="stem2")
    x = _conv_bn(x, 192, 3, name="stem3")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = _inception_block(x, 64, 96, 128, 16, 32, 32, name="3a")
    x = _inception_block(x, 128, 128, 192, 32, 96, 64, name="3b")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = _inception_block(x, 192, 96, 208, 16, 48, 64, name="4a")
    x = _inception_block(x, 160, 112, 224, 24, 64, 64, name="4b")
    x = _inception_block(x, 128, 128, 256, 24, 64, 64, name="4c")
    x = _inception_block(x, 112, 144, 288, 32, 64, 64, name="4d")
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, name="4e")
    x = MaxPooling2D((3, 3), strides=(2, 2), border_mode="same")(x)
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, name="5a")
    x = _inception_block(x, 384, 192, 384, 48, 128, 128, name="5b")
    x = GlobalAveragePooling2D()(x)
    x = Dropout(0.4)(x)
    x = Dense(class_num, name="fc")(x)
    return Model(inp, x, name="inception_v1")


# -------------------------------------------------------------- MobileNet --

def mobilenet(class_num: int = 1000,
              input_shape: Sequence[int] = (224, 224, 3),
              alpha: float = 1.0) -> Model:
    """MobileNet-v1 via separable convs (reference ImageClassificationConfig
    'mobilenet' entries)."""
    def c(f):
        return max(8, int(f * alpha))

    inp = Input(shape=tuple(input_shape), name="input")
    x = _conv_bn(inp, c(32), 3, strides=2, name="stem")
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]
    for i, (f, s) in enumerate(cfg):
        x = SeparableConvolution2D(c(f), 3, 3, subsample=(s, s),
                                   border_mode="same", bias=False,
                                   name=f"sep{i}")(x)
        x = BatchNormalization(name=f"sep{i}_bn")(x)
        x = Activation("relu")(x)
    x = GlobalAveragePooling2D()(x)
    x = Dense(class_num, name="fc")(x)
    return Model(inp, x, name="mobilenet")


# ------------------------------------------------------------------- VGG --

def vgg16(class_num: int = 1000,
          input_shape: Sequence[int] = (224, 224, 3)) -> Model:
    """VGG-16 (reference ImageClassificationConfig 'vgg-16')."""
    inp = Input(shape=tuple(input_shape), name="input")
    x = inp
    for block, (reps, f) in enumerate(
            [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]):
        for r in range(reps):
            x = Convolution2D(f, 3, 3, border_mode="same", activation="relu",
                              name=f"b{block}c{r}")(x)
        x = MaxPooling2D((2, 2))(x)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(class_num, name="fc")(x)
    return Model(inp, x, name="vgg16")


_BUILDERS = {
    "resnet-50": resnet50,
    "inception-v1": inception_v1,
    "mobilenet": mobilenet,
    "vgg-16": vgg16,
}

# Per-model preprocessing configs (reference ImageClassificationConfig.scala:
# mean/std + crop sizes per architecture).
PREPROCESS_CONFIG = {
    "resnet-50": {"size": 224, "mean": (123.68, 116.779, 103.939),
                  "std": (1.0, 1.0, 1.0)},
    "inception-v1": {"size": 224, "mean": (123.68, 116.779, 103.939),
                     "std": (1.0, 1.0, 1.0)},
    "mobilenet": {"size": 224, "mean": (123.68, 116.78, 103.94),
                  "std": (58.624, 57.344, 57.6)},
    "vgg-16": {"size": 224, "mean": (123.68, 116.779, 103.939),
               "std": (1.0, 1.0, 1.0)},
}


@register_model
class ImageClassifier(ZooModel):
    """Built-in image-classification model with bundled preprocessing
    (reference models/image/imageclassification/ImageClassifier.scala)."""

    def __init__(self, model_name: str = "resnet-50", class_num: int = 1000,
                 input_shape: Optional[Sequence[int]] = None):
        super().__init__()
        if model_name not in _BUILDERS:
            raise ValueError(f"unknown model {model_name}; "
                             f"available: {sorted(_BUILDERS)}")
        self.model_name = model_name
        self.class_num = class_num
        cfg = PREPROCESS_CONFIG[model_name]
        self.input_shape = tuple(input_shape or (cfg["size"], cfg["size"], 3))
        self.model = _BUILDERS[model_name](class_num, self.input_shape)

    def config(self):
        return {"model_name": self.model_name, "class_num": self.class_num,
                "input_shape": list(self.input_shape)}

    def preprocessing(self):
        """Default inference preprocessing chain for this architecture."""
        from analytics_zoo_tpu.data.image import (
            ImageAspectScale, ImageCenterCrop, ImageChannelNormalize,
            ImageSetToSample)

        cfg = PREPROCESS_CONFIG[self.model_name]
        size = self.input_shape[0]
        return (ImageAspectScale(int(size * 256 / 224))
                | ImageCenterCrop(size, size)
                | ImageChannelNormalize(*cfg["mean"], *cfg["std"])
                | ImageSetToSample())

    def predict_image_set(self, image_set, batch_size: int = 32,
                          top_k: int = 1) -> np.ndarray:
        """Classify an ImageSet → (N, top_k) class indices (0-based)."""
        ims = image_set.transform(self.preprocessing())
        x, _ = ims.to_arrays()
        logits = self.model.predict(x, batch_size=batch_size)
        return np.argsort(-logits, axis=-1)[:, :top_k]
