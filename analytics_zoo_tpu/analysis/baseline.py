"""zoolint baseline: the committed debt ledger and the diff gate.

``lint_baseline.json`` holds the findings the repo has explicitly
accepted (ideally: almost none).  Keys are line-number-free —
``rule :: path :: scope :: message`` with a count — so moving code
around a file doesn't invalidate entries, but changing the violation
itself (or adding another of the same shape) does.

``--check`` (the CI gate) fails on any finding not covered by the
baseline, and *warns* on stale entries so the ledger shrinks as debt
is paid instead of silently rotting.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from analytics_zoo_tpu.analysis.findings import Finding

BASELINE_VERSION = 1


def _key_str(key: Tuple[str, str, str, str]) -> str:
    return " :: ".join(key)


def findings_to_baseline(findings: List[Finding]) -> Dict[str, object]:
    counts: Dict[str, int] = {}
    for f in findings:
        k = _key_str(f.key())
        counts[k] = counts.get(k, 0) + 1
    return {"version": BASELINE_VERSION,
            "accepted": {k: counts[k] for k in sorted(counts)}}


def load_baseline(path: str) -> Dict[str, int]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return {}
    accepted = data.get("accepted", {})
    return {str(k): int(v) for k, v in accepted.items()}


def save_baseline(path: str, findings: List[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(findings_to_baseline(findings), fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


def diff_against_baseline(findings: List[Finding], accepted: Dict[str, int]
                          ) -> Tuple[List[Finding], List[str]]:
    """(new_findings, stale_keys): findings beyond the accepted counts,
    and accepted entries the code no longer produces."""
    remaining = dict(accepted)
    new: List[Finding] = []
    for f in findings:
        k = _key_str(f.key())
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    stale = [k for k, v in sorted(remaining.items()) if v > 0]
    return new, stale
