"""zoolint JG-* rules: tracer purity, recompile hazards, host transfers.

All rules key off :class:`~analytics_zoo_tpu.analysis.scopes.ModuleModel`:
the jitted-scope fixpoint says *where* tracer semantics apply, and a
lightweight per-function taint pass says *which names* hold tracers
(params minus static_argnums, propagated through assignments;
``.shape``/``.dtype``/``len()`` un-taint because they are static at
trace time — ``np.sqrt(head_dim)`` must stay quiet).

JG-TRANSFER-HOT applies outside jitted scopes, but only in *hot
modules* — the per-batch/per-request paths (estimator, prefetch,
serving) where one implicit sync per iteration serializes host and
device.  A file can also opt in with a ``# zoolint: hot-path`` comment
(the fixture corpus uses this).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from analytics_zoo_tpu.analysis.findings import Finding
from analytics_zoo_tpu.analysis.scopes import (ModuleModel, dotted_name,
                                               walk_own)

# modules whose per-batch loops are performance-critical by construction
HOT_SUFFIXES = ("train/estimator.py", "train/prefetch.py",
                "deploy/serving.py")
_HOT_MARKER = re.compile(r"#\s*zoolint:\s*hot-path")

# step-handle names the estimator/serving layers bind compiled fns to
_STEP_NAME_RE = re.compile(
    r"^(_train_step|_multi_step|_eval_step|_predict_step|_resident_epoch"
    r"|step_fn|epoch_fn)$")

_IMPURE_EXACT = {"print", "input", "open", "breakpoint", "exec", "eval"}
_IMPURE_PREFIXES = ("time.", "logging.", "logger.", "os.", "sys.",
                    "random.", "np.random.", "numpy.random.", "TIMERS.",
                    "count_event", "warnings.warn")
_PURE_EXEMPT_PREFIXES = ("jax.debug.",)

_SYNC_FUNCS = {"float", "int", "bool", "complex",
               "np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "onp.asarray", "onp.array", "jax.device_get", "device_get"}
_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}

# attribute reads that are static at trace time (break the taint chain)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding",
                 "weak_type"}
_UNTAINT_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                  "id", "repr", "str"}


def is_hot_module(model: ModuleModel) -> bool:
    rel = model.relpath.replace("\\", "/")
    return rel.endswith(HOT_SUFFIXES) or \
        bool(_HOT_MARKER.search(model.source))


# --------------------------------------------------------------------------
# taint
# --------------------------------------------------------------------------


def _target_names(target: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


_RETURNS_TAINTED_CACHE = "_zoolint_returns_tainted"


def _returns_tainted(model: ModuleModel, qual: str) -> bool:
    """Does a traced callee's return value carry taint when its params
    do?  A predicate like ``_is_qleaf`` returns a static bool however
    traced its argument is, so its callers' branches stay quiet."""
    cache: Dict[str, bool] = getattr(model, _RETURNS_TAINTED_CACHE, None)
    if cache is None:
        cache = {}
        setattr(model, _RETURNS_TAINTED_CACHE, cache)
    if qual in cache:
        return cache[qual]
    cache[qual] = True  # cycle guard: assume tainted while computing
    taint = _Taint(model, qual)
    info = model.functions[qual]
    tainted = False
    for n in walk_own(info.node):
        if isinstance(n, ast.Return) and n.value is not None and \
                taint.expr_tainted(n.value):
            tainted = True
            break
    cache[qual] = tainted
    return tainted


class _Taint:
    """Names holding traced values inside one jitted function."""

    def __init__(self, model: ModuleModel, qual: str):
        self.model = model
        self.qual = qual
        self.info = model.functions[qual]
        self.names: Set[str] = model.traced_params(qual)
        self._propagate()

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in _UNTAINT_CALLS:
                return False
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_METHODS:
                return False  # result lives on host (the sync rule fires)
            # taint flows through array ops and through defs we know are
            # traced; NOT through arbitrary helpers (a pytree-structure
            # predicate like `_is_qleaf(x)` returns a static Python bool
            # even when x is a tracer) — precision over recall here
            if not dn.startswith(("jnp.", "jax.", "lax.")):
                target = self.model.resolve_callable(node.func, self.qual)
                if target not in self.model.jitted or \
                        not _returns_tainted(self.model, target):
                    return False
            return any(self.expr_tainted(a) for a in node.args) or \
                any(self.expr_tainted(k.value) for k in node.keywords)
        if isinstance(node, (ast.BinOp,)):
            return self.expr_tainted(node.left) or \
                self.expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.expr_tainted(node.left) or \
                any(self.expr_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.expr_tainted(node.body) or \
                self.expr_tainted(node.orelse)
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        return False

    def _propagate(self) -> None:
        # fixpoint over assignments (loops create forward references)
        for _ in range(8):
            changed = False
            for node in walk_own(self.info.node):
                tgts: Set[str] = set()
                if isinstance(node, ast.Assign) and \
                        self.expr_tainted(node.value):
                    for t in node.targets:
                        tgts |= _target_names(t)
                elif isinstance(node, ast.AugAssign) and \
                        (self.expr_tainted(node.value) or
                         self.expr_tainted(node.target)):
                    tgts |= _target_names(node.target)
                elif isinstance(node, ast.AnnAssign) and node.value is not \
                        None and self.expr_tainted(node.value):
                    tgts |= _target_names(node.target)
                elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                        self.expr_tainted(node.iter):
                    tgts |= _target_names(node.target)
                new = tgts - self.names
                if new:
                    self.names |= new
                    changed = True
            if not changed:
                break


# --------------------------------------------------------------------------
# rule passes
# --------------------------------------------------------------------------


def _finding(model: ModuleModel, rule: str, node: ast.AST, scope: str,
             message: str) -> Finding:
    return Finding(rule, model.relpath, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0), scope, message)


def _check_jitted_scope(model: ModuleModel, qual: str,
                        out: List[Finding]) -> None:
    info = model.functions[qual]
    jit = model.jitted[qual]
    taint = _Taint(model, qual)

    for node in walk_own(info.node):
        # JG-GLOBAL-MUT -----------------------------------------------------
        if isinstance(node, ast.Global):
            out.append(_finding(
                model, "JG-GLOBAL-MUT", node, qual,
                f"`global {', '.join(node.names)}` inside jitted scope "
                f"({jit.reason}); tracer functions must be pure"))
            continue

        # JG-TRACED-BRANCH ---------------------------------------------------
        if isinstance(node, (ast.If, ast.While)) and \
                taint.expr_tainted(node.test):
            kw = "while" if isinstance(node, ast.While) else "if"
            out.append(_finding(
                model, "JG-TRACED-BRANCH", node, qual,
                f"Python `{kw}` on a traced value inside jitted scope "
                f"({jit.reason}); use lax.cond/jnp.where"))

        if not isinstance(node, ast.Call):
            continue
        dn = dotted_name(node.func)

        # JG-IMPURE-CALL ------------------------------------------------------
        if dn and not dn.startswith(_PURE_EXEMPT_PREFIXES):
            impure = dn in _IMPURE_EXACT or dn.startswith(_IMPURE_PREFIXES)
            if impure:
                out.append(_finding(
                    model, "JG-IMPURE-CALL", node, qual,
                    f"call to `{dn}` inside jitted scope ({jit.reason}) "
                    f"runs at trace time only"))
                continue

        # JG-HOST-SYNC ---------------------------------------------------------
        if dn in _SYNC_FUNCS and node.args and \
                taint.expr_tainted(node.args[0]):
            out.append(_finding(
                model, "JG-HOST-SYNC", node, qual,
                f"`{dn}()` on a traced value inside jitted scope "
                f"({jit.reason})"))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and \
                taint.expr_tainted(node.func.value):
            out.append(_finding(
                model, "JG-HOST-SYNC", node, qual,
                f"`.{node.func.attr}()` on a traced value inside jitted "
                f"scope ({jit.reason})"))


def _check_jit_in_loop(model: ModuleModel, out: List[Finding]) -> None:
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if model._is_jit_expr(node) is not node:
            continue
        cur = model.parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                      ast.Module)):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.append(_finding(
                    model, "JG-JIT-IN-LOOP", node,
                    model.qualname_of(node),
                    "jax.jit(...) constructed inside a loop body "
                    "recompiles every iteration"))
                break
            cur = model.parents.get(cur)


def _check_static_unstable(model: ModuleModel, out: List[Finding]) -> None:
    unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                  ast.SetComp, ast.GeneratorExp)
    by_name: Dict[str, Set[int]] = {}
    for h in model.handles:
        if h.static:
            by_name.setdefault(h.name, set()).update(h.static)
    if not by_name:
        return
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = dotted_name(node.func).rpartition(".")[2]
        static = by_name.get(tail)
        if not static:
            continue
        for i in static:
            if i < len(node.args) and isinstance(node.args[i], unhashable):
                out.append(_finding(
                    model, "JG-STATIC-UNSTABLE", node.args[i],
                    model.qualname_of(node),
                    f"unhashable literal passed to `{tail}` at static "
                    f"position {i}; static args must hash into the "
                    f"compile cache key"))


def _enclosing_loop(model: ModuleModel, node: ast.AST) -> bool:
    cur = model.parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Module)):
        if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
            return True
        cur = model.parents.get(cur)
    return False


def _check_transfer_hot(model: ModuleModel, out: List[Finding]) -> None:
    if not is_hot_module(model):
        return
    handle_names = {h.name for h in model.handles} | \
        {h for h in (f.name for f in model.functions.values())
         if _STEP_NAME_RE.match(h)}

    for qual, info in model.functions.items():
        if qual in model.jitted:
            continue
        # names assigned from a compiled-step dispatch hold device values
        device_names: Set[str] = set()
        for node in walk_own(info.node):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                tail = dotted_name(node.value.func).rpartition(".")[2]
                if tail in handle_names or _STEP_NAME_RE.match(tail):
                    for t in node.targets:
                        device_names |= _target_names(t)

        for node in walk_own(info.node):
            if not isinstance(node, ast.Call) or \
                    not _enclosing_loop(model, node):
                continue
            dn = dotted_name(node.func)
            if dn in ("jax.device_get", "device_get"):
                out.append(_finding(
                    model, "JG-TRANSFER-HOT", node, qual,
                    "jax.device_get inside a hot-path loop forces a "
                    "device->host sync every iteration"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "block_until_ready":
                out.append(_finding(
                    model, "JG-TRANSFER-HOT", node, qual,
                    ".block_until_ready() inside a hot-path loop "
                    "serializes dispatch"))
            elif dn in ("float", "int", "np.asarray", "np.array",
                        "numpy.asarray", "numpy.array") and node.args and \
                    isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in device_names:
                out.append(_finding(
                    model, "JG-TRANSFER-HOT", node, qual,
                    f"`{dn}()` on step output `{node.args[0].id}` inside "
                    f"a hot-path loop blocks on the device every "
                    f"iteration"))


def _check_donate_reuse(model: ModuleModel, out: List[Finding]) -> None:
    donating = {h.name: h.donate for h in model.handles if h.donate}
    if not donating:
        return
    for qual, info in model.functions.items():
        if qual in model.jitted:
            continue
        for node in walk_own(info.node):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted_name(node.func).rpartition(".")[2]
            donate = donating.get(tail)
            if not donate:
                continue
            donated_names = {node.args[i].id for i in donate
                             if i < len(node.args)
                             and isinstance(node.args[i], ast.Name)}
            if not donated_names:
                continue
            # names rebound by the call's own assignment are safe
            parent = model.parents.get(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    donated_names -= _target_names(t)
            if not donated_names:
                continue
            # first subsequent Store per name ends the danger window
            first_store: Dict[str, int] = {}
            loads: List[ast.Name] = []
            for n in walk_own(info.node):
                if isinstance(n, ast.Name) and n.id in donated_names and \
                        n.lineno > node.lineno:
                    if isinstance(n.ctx, ast.Store):
                        first_store[n.id] = min(
                            first_store.get(n.id, n.lineno), n.lineno)
                    else:
                        loads.append(n)
            for n in sorted(loads, key=lambda x: (x.lineno, x.col_offset)):
                if n.lineno < first_store.get(n.id, 10 ** 9):
                    out.append(_finding(
                        model, "JG-DONATE-REUSE", n, qual,
                        f"`{n.id}` was donated to `{tail}` (buffer "
                        f"invalidated at dispatch) and read before being "
                        f"rebound"))


def check_jax(model: ModuleModel) -> List[Finding]:
    out: List[Finding] = []
    for qual in sorted(model.jitted):
        if qual in model.functions:
            _check_jitted_scope(model, qual, out)
    _check_jit_in_loop(model, out)
    _check_static_unstable(model, out)
    _check_transfer_hot(model, out)
    _check_donate_reuse(model, out)
    return out
