"""zoolint — JAX-aware static analyzer + concurrency lint.

Stdlib-``ast`` only (no new dependencies).  Two rule families:

- **JG-\\*** — tracer discipline: impure calls / global mutation / host
  syncs / Python branches inside jitted scopes, jit-in-loop recompile
  hazards, unhashable static args, implicit transfers in hot per-batch
  loops, donated-buffer use-after-dispatch.
- **THR-\\*** — lock discipline over the threaded serving/robust/train
  layers: guarded-by inference, blocking calls under a lock,
  inconsistent lock order, unguarded cross-thread mutation.

Entry points: ``python -m analytics_zoo_tpu.analysis`` (CLI; see
``--help``) and :func:`analyze` (the pytest gate uses this).  Rule
catalog and workflow: docs/ANALYSIS.md.
"""

from analytics_zoo_tpu.analysis.findings import (Finding, Rule,  # noqa: F401
                                                 all_rules, get_rule)
from analytics_zoo_tpu.analysis.runner import (analyze,  # noqa: F401
                                               analyze_file,
                                               default_root, repo_root)
from analytics_zoo_tpu.analysis.baseline import (  # noqa: F401
    diff_against_baseline, findings_to_baseline, load_baseline,
    save_baseline)

__all__ = ["Finding", "Rule", "all_rules", "get_rule", "analyze",
           "analyze_file", "default_root", "repo_root",
           "diff_against_baseline", "findings_to_baseline",
           "load_baseline", "save_baseline"]
