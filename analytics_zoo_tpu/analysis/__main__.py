"""zoolint CLI — ``python -m analytics_zoo_tpu.analysis``.

Modes:

- (default)            report every finding; exit 1 if any
- ``--check``          diff against the committed baseline; exit 1 only
                       on NEW findings (the CI gate)
- ``--write-baseline`` accept the current findings as the new baseline
- ``--json``           strict-JSON output for tooling
- ``--list-rules``     print the rule catalog
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List

from analytics_zoo_tpu.analysis import baseline as baseline_mod
from analytics_zoo_tpu.analysis import runner
from analytics_zoo_tpu.analysis.findings import Finding, all_rules


def _render_text(findings: List[Finding], elapsed_s: float,
                 n_files: int) -> str:
    lines = [f.render() + (f"\n    fix: {f.hint}" if f.hint else "")
             for f in findings]
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items())) \
        or "clean"
    lines.append(f"zoolint: {len(findings)} finding(s) in {n_files} "
                 f"file(s) [{summary}] ({elapsed_s:.2f}s)")
    return "\n".join(lines)


def _render_json(findings: List[Finding], elapsed_s: float,
                 n_files: int) -> str:
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return json.dumps({"version": 1,
                       "files": n_files,
                       "elapsed_s": round(elapsed_s, 3),
                       "counts": {k: counts[k] for k in sorted(counts)},
                       "findings": [f.to_json() for f in findings]},
                      indent=2, sort_keys=False)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m analytics_zoo_tpu.analysis",
        description="zoolint: JAX-aware static analyzer + concurrency "
                    "lint for analytics_zoo_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the installed "
                         "analytics_zoo_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit strict JSON instead of human text")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: fail only on findings NOT in the "
                         "baseline")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: <repo>/"
                         "lint_baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings as the new baseline")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}: {r.summary}\n    fix: {r.hint}")
        return 0

    paths = args.paths or [runner.default_root()]
    baseline_path = args.baseline or os.path.join(runner.repo_root(),
                                                  "lint_baseline.json")
    t0 = time.monotonic()
    files = runner.iter_py_files(paths)
    findings = runner.analyze(paths)
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        baseline_mod.save_baseline(baseline_path, findings)
        print(f"zoolint: wrote {len(findings)} accepted finding(s) to "
              f"{baseline_path}")
        return 0

    if args.check:
        accepted = baseline_mod.load_baseline(baseline_path)
        new, stale = baseline_mod.diff_against_baseline(findings, accepted)
        if args.as_json:
            print(_render_json(new, elapsed, len(files)))
        else:
            if new:
                print(_render_text(new, elapsed, len(files)))
            for k in stale:
                print(f"zoolint: stale baseline entry (no longer "
                      f"produced): {k}", file=sys.stderr)
            if not new:
                print(f"zoolint: OK — no findings beyond baseline "
                      f"({len(findings)} accepted, {len(files)} files, "
                      f"{elapsed:.2f}s)")
        return 1 if new else 0

    if args.as_json:
        print(_render_json(findings, elapsed, len(files)))
    else:
        print(_render_text(findings, elapsed, len(files)))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
