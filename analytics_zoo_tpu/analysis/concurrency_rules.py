"""zoolint THR-* rules: lock discipline for the threaded layers.

The model is per-class.  A *lock field* is any attribute assigned a
``threading.Lock/RLock/Condition``; a *held set* is computed for every
statement by walking function bodies and tracking both acquisition
forms the repo uses (``with self._lock:`` blocks and linear
``acquire()``/``release()`` pairs).  Three pieces of inference keep the
repo's real idioms quiet without weakening the rules:

- methods named ``*_locked`` are contract-documented as "caller holds
  the lock" and analyzed with the class's locks held;
- a private method whose every intra-class call site holds lock L is
  analyzed with L held (``DynamicBatcher._ready`` is only called inside
  ``with self._cv``);
- fields of intrinsically thread-safe types (Queue, Event, Condition,
  Thread, deque...) are exempt from guard inference — their safety is
  the type's, not a lock's.

Rules:

- **THR-GUARD** — guarded-by: a field written at least once under lock
  L (outside ``__init__`` construction) is inferred guarded by L; any
  non-init access without L is flagged.
- **THR-BLOCK** — blocking call (sleep, Thread.join, queue get/put,
  Event.wait, device_get/block_until_ready) while holding a lock.
  ``Condition.wait()`` on the *held* condition is exempt (wait releases
  it); plain filesystem ops are deliberately out of the default set
  (the checkpoint manager serializes fs mutation under ``_fs_lock`` by
  design).
- **THR-ORDER** — the same two locks nested in opposite orders anywhere
  in one module.
- **THR-SHARED-MUT** — a plain field written from a thread-target
  function (``threading.Thread(target=...)`` / executor ``submit``)
  with no lock, and accessed from non-thread code: readers can see
  stale state and compound updates race.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from analytics_zoo_tpu.analysis.findings import Finding
from analytics_zoo_tpu.analysis.scopes import (FunctionInfo, ModuleModel,
                                               dotted_name)

LockId = Tuple[str, str]  # (class qualname or '' for module-level, name)

_LOCK_TAILS = {"Lock", "RLock", "Condition"}
_SAFE_TAILS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
               "Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "Thread", "Timer", "deque", "local", "ThreadPoolExecutor"}
_QUEUE_TAILS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
_EVENT_TAILS = {"Event"}
_THREAD_TAILS = {"Thread", "Timer"}


def _ctor_tail(value: ast.AST) -> str:
    if isinstance(value, ast.Call):
        return dotted_name(value.func).rpartition(".")[2]
    return ""


@dataclasses.dataclass
class Access:
    attr: str
    is_store: bool
    node: ast.AST
    func_qual: str
    held: FrozenSet[LockId]
    is_init: bool
    is_thread_ctx: bool


@dataclasses.dataclass
class BlockingCall:
    node: ast.AST
    func_qual: str
    held: FrozenSet[LockId]
    what: str


@dataclasses.dataclass
class OrderEdge:
    outer: LockId
    inner: LockId
    node: ast.AST
    func_qual: str


class ClassModel:
    def __init__(self, qual: str, node: ast.ClassDef):
        self.qual = qual
        self.node = node
        self.methods: Set[str] = set()
        self.locks: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.event_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.accesses: Dict[str, List[Access]] = {}


class ConcurrencyAnalyzer:
    def __init__(self, model: ModuleModel):
        self.model = model
        self.classes: Dict[str, ClassModel] = {}
        self.module_locks: Set[str] = set()
        self.thread_ctx: Set[str] = set()       # function qualnames
        self.base_held: Dict[str, FrozenSet[LockId]] = {}
        self.call_sites: Dict[str, List[FrozenSet[LockId]]] = {}
        self.blocking: List[BlockingCall] = []
        self.order_edges: List[OrderEdge] = []
        self._build_class_models()
        self._find_thread_contexts()
        self._infer_base_held()

    # -- model building ------------------------------------------------------

    def _build_class_models(self) -> None:
        for stmt in self.model.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    _ctor_tail(stmt.value) in _LOCK_TAILS:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
        for qual, cnode in self.model.classes.items():
            cm = ClassModel(qual, cnode)
            self.classes[qual] = cm
            for fq, info in self.model.functions.items():
                if info.parent_qual == qual:
                    cm.methods.add(info.name)
            for node in ast.walk(cnode):
                if not isinstance(node, ast.Assign):
                    continue
                tail = _ctor_tail(node.value)
                if not tail:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        if tail in _LOCK_TAILS:
                            cm.locks.add(t.attr)
                        elif tail in _SAFE_TAILS:
                            cm.safe_attrs.add(t.attr)
                            if tail in _QUEUE_TAILS:
                                cm.queue_attrs.add(t.attr)
                            elif tail in _EVENT_TAILS:
                                cm.event_attrs.add(t.attr)
                            elif tail in _THREAD_TAILS:
                                cm.thread_attrs.add(t.attr)

    def _find_thread_contexts(self) -> None:
        for node in ast.walk(self.model.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted_name(node.func).rpartition(".")[2]
            target: Optional[ast.AST] = None
            if tail in _THREAD_TAILS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif tail == "submit" and node.args:
                target = node.args[0]
            if target is None:
                continue
            q = self.model.resolve_callable(target,
                                            self.model.qualname_of(node))
            if q:
                self.thread_ctx.add(q)
        # nested defs inside a thread target run on that thread too
        changed = True
        while changed:
            changed = False
            for fq, info in self.model.functions.items():
                if fq not in self.thread_ctx and \
                        info.parent_qual in self.thread_ctx:
                    self.thread_ctx.add(fq)
                    changed = True

    def _class_of(self, info: FunctionInfo) -> Optional[ClassModel]:
        return self.classes.get(info.class_qual)

    def _infer_base_held(self) -> None:
        for fq, info in self.model.functions.items():
            cm = self._class_of(info)
            if cm and info.name.endswith("_locked"):
                self.base_held[fq] = frozenset(
                    (cm.qual, lk) for lk in cm.locks)
            else:
                self.base_held[fq] = frozenset()
        # fixpoint: a private method whose every intra-class call site
        # holds L runs with L held
        for _ in range(3):
            self.call_sites = {}
            self._walk_all(collect_events=False)
            changed = False
            for fq, sites in self.call_sites.items():
                info = self.model.functions.get(fq)
                if info is None or not info.name.startswith("_") or \
                        info.name.startswith("__") or not sites:
                    continue
                common = frozenset.intersection(*sites)
                if common - self.base_held[fq]:
                    self.base_held[fq] = self.base_held[fq] | common
                    changed = True
            if not changed:
                break

    # -- the walk --------------------------------------------------------------

    def run(self) -> None:
        self.blocking = []
        self.order_edges = []
        self._walk_all(collect_events=True)

    def _walk_all(self, collect_events: bool) -> None:
        self._collect = collect_events
        for fq, info in self.model.functions.items():
            self._cur_fq = fq
            self._cur_info = info
            self._cur_cm = self._class_of(info)
            self._cur_init = (self._cur_cm is not None and
                              fq == f"{self._cur_cm.qual}.__init__")
            self._cur_thread = fq in self.thread_ctx
            self._aliases = self._local_aliases(info)
            self._walk_stmts(info.node.body, self.base_held[fq])

    def _local_aliases(self, info: FunctionInfo) -> Dict[str, Tuple[str,
                                                                    str]]:
        """name -> ('lock', id) / ('queue'|'event'|'thread', '') for
        simple local binds (``t = self._thread``, ``q = queue.Queue()``)."""
        out: Dict[str, Tuple[str, str]] = {}
        cm = self._cur_cm
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Assign) and
                    len(node.targets) == 1 and
                    isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            v = node.value
            tail = _ctor_tail(v)
            if tail in _LOCK_TAILS:
                out[name] = ("lock", f"local:{name}")
            elif tail in _QUEUE_TAILS:
                out[name] = ("queue", "")
            elif tail in _EVENT_TAILS:
                out[name] = ("event", "")
            elif tail in _THREAD_TAILS:
                out[name] = ("thread", "")
            elif cm and isinstance(v, ast.Attribute) and \
                    isinstance(v.value, ast.Name) and v.value.id == "self":
                if v.attr in cm.locks:
                    out[name] = ("lock", v.attr)
                elif v.attr in cm.queue_attrs:
                    out[name] = ("queue", "")
                elif v.attr in cm.event_attrs:
                    out[name] = ("event", "")
                elif v.attr in cm.thread_attrs:
                    out[name] = ("thread", "")
        return out

    def _resolve_lock(self, expr: ast.AST) -> Optional[LockId]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self._cur_cm and \
                expr.attr in self._cur_cm.locks:
            return (self._cur_cm.qual, expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return ("", expr.id)
            alias = self._aliases.get(expr.id)
            if alias and alias[0] == "lock":
                cm = self._cur_cm
                if cm and alias[1] in cm.locks:
                    return (cm.qual, alias[1])
                return ("", alias[1])
        return None

    def _obj_kind(self, expr: ast.AST) -> str:
        """'queue' / 'event' / 'thread' / 'lock' / '' for a call
        receiver."""
        cm = self._cur_cm
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and cm:
            if expr.attr in cm.queue_attrs:
                return "queue"
            if expr.attr in cm.event_attrs:
                return "event"
            if expr.attr in cm.thread_attrs:
                return "thread"
            if expr.attr in cm.locks:
                return "lock"
        if isinstance(expr, ast.Name):
            alias = self._aliases.get(expr.id)
            if alias:
                return alias[0]
        return ""

    def _acq_rel(self, stmt: ast.AST) -> Tuple[Optional[LockId],
                                               Optional[str]]:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr in ("acquire", "release"):
            lock = self._resolve_lock(stmt.value.func.value)
            if lock is not None:
                return lock, stmt.value.func.attr
        return None, None

    def _walk_stmts(self, body: List[ast.stmt],
                    held: FrozenSet[LockId]) -> None:
        extra: FrozenSet[LockId] = frozenset()
        for stmt in body:
            cur = held | extra
            lock, op = self._acq_rel(stmt)
            self._visit(stmt, cur)
            if lock is not None and op == "acquire":
                for outer in cur:
                    if outer != lock:
                        self.order_edges.append(
                            OrderEdge(outer, lock, stmt, self._cur_fq))
                extra = extra | {lock}
            elif lock is not None and op == "release":
                extra = extra - {lock}
                if lock in held:
                    held = held - {lock}

    def _visit(self, node: ast.AST, held: FrozenSet[LockId]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # separate execution context
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new: Set[LockId] = set()
            for item in node.items:
                lock = self._resolve_lock(item.context_expr)
                self._scan_expr(item.context_expr, held)
                if lock is not None:
                    for outer in (held | new):
                        if outer != lock:
                            self.order_edges.append(
                                OrderEdge(outer, lock, item.context_expr,
                                          self._cur_fq))
                    new.add(lock)
            self._walk_stmts(node.body, held | frozenset(new))
            return
        self._event(node, held)
        for _field, value in ast.iter_fields(node):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._walk_stmts(value, held)
                else:
                    for v in value:
                        if isinstance(v, ast.AST):
                            self._visit(v, held)
            elif isinstance(value, ast.AST):
                self._visit(value, held)

    def _scan_expr(self, node: ast.AST, held: FrozenSet[LockId]) -> None:
        for n in ast.walk(node):
            self._event(n, held)

    # -- event recording ---------------------------------------------------------

    def _event(self, node: ast.AST, held: FrozenSet[LockId]) -> None:
        cm = self._cur_cm
        if self._collect and isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cm is not None:
            attr = node.attr
            if attr not in cm.methods and attr not in cm.locks:
                cm.accesses.setdefault(attr, []).append(Access(
                    attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                    node, self._cur_fq, held, self._cur_init,
                    self._cur_thread))
        if isinstance(node, ast.Call):
            if not self._collect:
                q = self.model.resolve_callable(node.func, self._cur_fq)
                if q is not None:
                    self.call_sites.setdefault(q, []).append(held)
            elif held:
                self._check_blocking(node, held)

    def _check_blocking(self, node: ast.Call,
                        held: FrozenSet[LockId]) -> None:
        dn = dotted_name(node.func)
        what = ""
        if dn in ("time.sleep", "sleep"):
            what = "time.sleep"
        elif dn in ("jax.device_get", "device_get"):
            what = "jax.device_get (device sync)"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            kind = self._obj_kind(node.func.value)
            if attr == "block_until_ready":
                what = ".block_until_ready() (device sync)"
            elif attr == "join" and kind == "thread":
                what = "Thread.join"
            elif attr in ("get", "put") and kind == "queue":
                what = f"queue.{attr}"
            elif attr in ("wait", "wait_for"):
                if kind == "event":
                    what = "Event.wait"
                elif kind == "lock":
                    # Condition.wait on the HELD condition releases it —
                    # the one blocking call that is correct under a lock
                    lock = self._resolve_lock(node.func.value)
                    if lock is not None and lock not in held:
                        what = f"wait on {node.func.attr}"
        if what:
            self.blocking.append(BlockingCall(node, self._cur_fq, held,
                                              what))

    # -- findings ------------------------------------------------------------------

    def findings(self) -> List[Finding]:
        out: List[Finding] = []
        self._guard_findings(out)
        self._block_findings(out)
        self._order_findings(out)
        self._shared_mut_findings(out)
        return out

    def _mk(self, rule: str, node: ast.AST, fq: str,
            message: str) -> Finding:
        return Finding(rule, self.model.relpath,
                       getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), fq, message)

    @staticmethod
    def _lockname(lock: LockId) -> str:
        return f"{lock[0]}.{lock[1]}" if lock[0] else lock[1]

    def _guards(self, cm: ClassModel) -> Dict[str, LockId]:
        """attr -> inferred guarding lock (written >=1x under it)."""
        guards: Dict[str, LockId] = {}
        for attr, accs in cm.accesses.items():
            if attr in cm.safe_attrs:
                continue
            writes = [a for a in accs if a.is_store and not a.is_init]
            counts: Dict[LockId, int] = {}
            for a in writes:
                for lk in a.held:
                    if lk[0] == cm.qual:  # own-class lock only
                        counts[lk] = counts.get(lk, 0) + 1
            if counts:
                guards[attr] = max(counts, key=lambda k: counts[k])
        return guards

    def _guard_findings(self, out: List[Finding]) -> None:
        for cm in self.classes.values():
            if not cm.locks:
                continue
            for attr, guard in sorted(self._guards(cm).items()):
                for a in cm.accesses[attr]:
                    if a.is_init or guard in a.held:
                        continue
                    verb = "written" if a.is_store else "read"
                    out.append(self._mk(
                        "THR-GUARD", a.node, a.func_qual,
                        f"`self.{attr}` is guarded by "
                        f"`{self._lockname(guard)}` elsewhere but "
                        f"{verb} here without it"))

    def _block_findings(self, out: List[Finding]) -> None:
        for b in self.blocking:
            locks = ", ".join(sorted(self._lockname(lk) for lk in b.held))
            out.append(self._mk(
                "THR-BLOCK", b.node, b.func_qual,
                f"blocking call {b.what} while holding `{locks}`"))

    def _order_findings(self, out: List[Finding]) -> None:
        pairs: Dict[Tuple[LockId, LockId], List[OrderEdge]] = {}
        for e in self.order_edges:
            pairs.setdefault((e.outer, e.inner), []).append(e)
        seen: Set[int] = set()
        for (a, b), edges in sorted(pairs.items(),
                                    key=lambda kv: str(kv[0])):
            if (b, a) not in pairs:
                continue
            for e in edges:
                if id(e.node) in seen:
                    continue
                seen.add(id(e.node))
                out.append(self._mk(
                    "THR-ORDER", e.node, e.func_qual,
                    f"acquires `{self._lockname(b)}` while holding "
                    f"`{self._lockname(a)}`; another path nests them in "
                    f"the opposite order (deadlock risk)"))

    def _shared_mut_findings(self, out: List[Finding]) -> None:
        for cm in self.classes.values():
            guards = self._guards(cm)
            for attr, accs in sorted(cm.accesses.items()):
                if attr in cm.safe_attrs or attr in guards:
                    continue
                thread_writes = [a for a in accs if a.is_store and
                                 a.is_thread_ctx and not a.held]
                outside = [a for a in accs if not a.is_thread_ctx and
                           not a.is_init]
                if not thread_writes or not outside:
                    continue
                where = sorted({a.func_qual for a in outside})
                for a in thread_writes:
                    out.append(self._mk(
                        "THR-SHARED-MUT", a.node, a.func_qual,
                        f"`self.{attr}` is written on a background "
                        f"thread with no lock but accessed from "
                        f"{', '.join(where[:3])}"))


def check_concurrency(model: ModuleModel) -> List[Finding]:
    ana = ConcurrencyAnalyzer(model)
    ana.run()
    return ana.findings()
