"""zoolint driver: walk files, build models, run both rule families."""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional

from analytics_zoo_tpu.analysis import concurrency_rules, jax_rules
from analytics_zoo_tpu.analysis.findings import Finding, Suppressions
from analytics_zoo_tpu.analysis.scopes import ModuleModel

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist",
              ".eggs"}


def default_root() -> str:
    """The package directory — `python -m analytics_zoo_tpu.analysis`
    with no paths lints the library itself."""
    import analytics_zoo_tpu
    return os.path.dirname(os.path.abspath(analytics_zoo_tpu.__file__))


def repo_root() -> str:
    return os.path.dirname(default_root())


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in _SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.abspath(
                            os.path.join(dirpath, fn)))
    return out


def analyze_file(path: str, rel_to: Optional[str] = None) -> List[Finding]:
    rel_to = rel_to or repo_root()
    relpath = os.path.relpath(path, rel_to).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("LINT-SYNTAX", relpath, e.lineno or 0, 0, "",
                        f"file does not parse: {e.msg}")]
    model = ModuleModel(path, relpath, source, tree)
    findings = jax_rules.check_jax(model) + \
        concurrency_rules.check_concurrency(model)
    sup = Suppressions(source)
    kept = [f for f in findings if not sup.suppressed(f)]
    kept.extend(sup.bare_disable_findings(relpath))
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def analyze(paths: Iterable[str],
            rel_to: Optional[str] = None) -> List[Finding]:
    out: List[Finding] = []
    for path in iter_py_files(paths):
        out.extend(analyze_file(path, rel_to=rel_to))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
