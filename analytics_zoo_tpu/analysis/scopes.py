"""zoolint scope resolution: which code runs under a JAX tracer?

The JG-* rules only make sense inside *jitted scopes* — function bodies
that execute at trace time rather than per call.  This repo reaches jit
four ways, and the resolver understands all of them:

- decorator form: ``@jax.jit`` / ``@partial(jax.jit, ...)``
- call form: ``self._train_step = jax.jit(step, donate_argnums=...)``
  where ``step`` is a nested def (the Estimator idiom)
- structured control flow: a def passed to ``lax.scan`` / ``fori_loop``
  / ``while_loop`` / ``cond`` / ``jax.checkpoint`` is traced
- transitive calls: a def called *by name* from a jitted scope is
  itself traced (``single(...)`` inside ``_multi_step``'s scan body)

Propagation is a fixpoint over those edges.  It deliberately does NOT
follow attribute calls on arbitrary objects (``self.model.apply``,
``optimizer.update``) — those targets live in other modules and
flagging their bodies from here would be guesswork; each module is
analyzed with its own jit roots instead.

The resolver also records per-jit-handle metadata the rules need:
``donate_argnums`` (for JG-DONATE-REUSE) and ``static_argnums`` (for
JG-STATIC-UNSTABLE, and to exclude static params from taint).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

# --------------------------------------------------------------------------
# small AST helpers (shared by the rule modules)
# --------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for a Name/Attribute chain, '' if not a pure chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def attach_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def walk_own(node: ast.AST):
    """Walk a def's body but stop at nested def/class boundaries (the
    nested scopes are visited separately with their own context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def int_values(node: Optional[ast.AST]) -> Set[int]:
    """Integer literals inside a (possibly tuple/list) static/donate
    argnums expression; empty set when the value isn't literal."""
    if node is None:
        return set()
    out: Set[int] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            out.add(n.value)
    return out


# --------------------------------------------------------------------------
# module model
# --------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap"}
# callable-position args of the structured-control-flow primitives
_TRACED_ARG_POSITIONS = {
    "scan": (0,), "fori_loop": (2,), "while_loop": (0, 1), "cond": (1, 2),
    "switch": None,  # every arg after the index is a branch
    "checkpoint": (0,), "remat": (0,),
}


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                   # FunctionDef / AsyncFunctionDef
    name: str
    qualname: str
    parent_qual: str                # '' for module level
    class_qual: str                 # nearest enclosing class ('' if none)
    param_names: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class JitInfo:
    reason: str                     # human-readable why this scope is traced
    donate: Set[int] = dataclasses.field(default_factory=set)
    static: Set[int] = dataclasses.field(default_factory=set)
    static_names: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class JitHandle:
    """A name a compiled callable was bound to (``h = jax.jit(f)`` or
    ``self._step = jax.jit(f)``) — call-site rules key off these."""
    name: str                       # local name or attribute tail
    is_attr: bool
    donate: Set[int]
    static: Set[int]
    target_qual: str                # '' when the wrapped fn wasn't resolved
    line: int


class ModuleModel:
    """Parsed file + function registry + jitted-scope fixpoint."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.parents = attach_parents(tree)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.jitted: Dict[str, JitInfo] = {}
        self.handles: List[JitHandle] = []
        self._collect_defs()
        self._mark_jitted()

    # -- registry ----------------------------------------------------------

    def _collect_defs(self) -> None:
        def visit(node: ast.AST, qual: str, class_qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    a = child.args
                    params = ([p.arg for p in a.posonlyargs] +
                              [p.arg for p in a.args] +
                              ([a.vararg.arg] if a.vararg else []) +
                              [p.arg for p in a.kwonlyargs] +
                              ([a.kwarg.arg] if a.kwarg else []))
                    self.functions[q] = FunctionInfo(
                        child, child.name, q, qual, class_qual, params)
                    visit(child, q, class_qual)
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    self.classes[q] = child
                    visit(child, q, q)
                else:
                    visit(child, qual, class_qual)

        visit(self.tree, "", "")

    def qualname_of(self, node: ast.AST) -> str:
        """Dotted qualname of the def/class chain enclosing *node*."""
        parts: List[str] = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST) -> Optional[FunctionInfo]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.functions.get(self.qualname_of(cur) and
                                          f"{self.qualname_of(cur)}.{cur.name}"
                                          or cur.name)
            cur = self.parents.get(cur)
        return None

    def resolve_callable(self, expr: ast.AST,
                         from_qual: str) -> Optional[str]:
        """Resolve a callable expression at a call/pass site to a def's
        qualname: bare names search enclosing scopes then module level;
        ``self.X`` searches the enclosing class."""
        if isinstance(expr, ast.Name):
            scope = from_qual
            while True:
                cand = f"{scope}.{expr.id}" if scope else expr.id
                if cand in self.functions:
                    return cand
                if not scope:
                    return None
                scope = scope.rpartition(".")[0]
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls"):
            info = self.functions.get(from_qual)
            cls = info.class_qual if info else ""
            if cls:
                cand = f"{cls}.{expr.attr}"
                if cand in self.functions:
                    return cand
        return None

    # -- jit fixpoint --------------------------------------------------------

    def _jit_call_kwargs(self, call: ast.Call) -> Tuple[Set[int], Set[int],
                                                        Set[str]]:
        donate: Set[int] = set()
        static: Set[int] = set()
        static_names: Set[str] = set()
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                donate |= int_values(kw.value)
            elif kw.arg == "static_argnums":
                static |= int_values(kw.value)
            elif kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and \
                            isinstance(n.value, str):
                        static_names.add(n.value)
        return donate, static, static_names

    def _is_jit_expr(self, node: ast.AST) -> Optional[ast.Call]:
        """jax.jit / partial(jax.jit, ...) as an expression; returns the
        Call carrying the jit kwargs."""
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in _JIT_NAMES:
                return node
            if dn in ("partial", "functools.partial") and node.args and \
                    dotted_name(node.args[0]) in _JIT_NAMES:
                return node
        return None

    def _mark(self, qual: str, reason: str, donate: Set[int] = frozenset(),
              static: Set[int] = frozenset(),
              static_names: Set[str] = frozenset()) -> bool:
        if qual in self.jitted:
            self.jitted[qual].donate |= set(donate)
            self.jitted[qual].static |= set(static)
            self.jitted[qual].static_names |= set(static_names)
            return False
        self.jitted[qual] = JitInfo(reason, set(donate), set(static),
                                    set(static_names))
        return True

    def _mark_jitted(self) -> None:
        # seed 1: decorators
        for qual, info in self.functions.items():
            for dec in getattr(info.node, "decorator_list", []):
                if dotted_name(dec) in _JIT_NAMES:
                    self._mark(qual, "@jit decorator")
                else:
                    call = self._is_jit_expr(dec)
                    if call is not None:
                        d, s, sn = self._jit_call_kwargs(call)
                        self._mark(qual, "@jit decorator", d, s, sn)

        # seed 2: call forms — jax.jit(f, ...) and lax.scan/fori/... bodies
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            from_qual = self.qualname_of(node)
            call = self._is_jit_expr(node)
            if call is node and node.args:
                d, s, sn = self._jit_call_kwargs(node)
                target = self.resolve_callable(node.args[0], from_qual)
                if target:
                    self._mark(target, "passed to jax.jit", d, s, sn)
                self._record_handle(node, target, d, s)
                continue
            dn = dotted_name(node.func)
            tail = dn.rpartition(".")[2]
            positions = _TRACED_ARG_POSITIONS.get(tail)
            if tail in _TRACED_ARG_POSITIONS and \
                    ("lax" in dn or "jax" in dn or dn == tail):
                idxs = (range(1, len(node.args)) if positions is None
                        else positions)
                for i in idxs:
                    if i < len(node.args):
                        t = self.resolve_callable(node.args[i], from_qual)
                        if t:
                            self._mark(t, f"traced by {tail}")

        # propagate: nesting + direct calls from jitted scopes
        changed = True
        while changed:
            changed = False
            for qual, info in self.functions.items():
                if qual in self.jitted:
                    continue
                parent = info.parent_qual
                if parent in self.jitted and \
                        parent in self.functions:  # nested def, not method
                    changed |= self._mark(qual,
                                          f"nested in jitted {parent}")
            for qual in list(self.jitted):
                info = self.functions.get(qual)
                if info is None:
                    continue
                for node in walk_own(info.node):
                    if isinstance(node, ast.Call):
                        t = self.resolve_callable(node.func, qual)
                        if t and t not in self.jitted:
                            changed |= self._mark(
                                t, f"called from jitted {qual}")

    def _record_handle(self, call: ast.Call, target_qual: Optional[str],
                       donate: Set[int], static: Set[int]) -> None:
        """``X = jax.jit(f, ...)`` / ``self.X = jax.jit(f, ...)`` — note
        the bound name so call-site rules can find dispatches."""
        parent = self.parents.get(call)
        if not isinstance(parent, ast.Assign) or len(parent.targets) != 1:
            return
        tgt = parent.targets[0]
        if isinstance(tgt, ast.Name):
            self.handles.append(JitHandle(tgt.id, False, donate, static,
                                          target_qual or "", call.lineno))
        elif isinstance(tgt, ast.Attribute):
            self.handles.append(JitHandle(tgt.attr, True, donate, static,
                                          target_qual or "", call.lineno))

    # -- taint: which params are traced --------------------------------------

    def traced_params(self, qual: str) -> Set[str]:
        """Parameter names that hold tracers when *qual* runs traced:
        everything except self/cls, static_argnums positions, and
        params whose annotation/default says "Python config, not
        array" (``n: int``, ``shuffle: bool = True`` — static at trace
        time, so branching on them is fine)."""
        info = self.functions.get(qual)
        jit = self.jitted.get(qual)
        if info is None or jit is None:
            return set()
        a = info.node.args
        static_typed: Set[str] = set()
        pos_args = list(a.posonlyargs) + list(a.args)
        for arg in pos_args + list(a.kwonlyargs):
            ann = dotted_name(arg.annotation) if arg.annotation else ""
            if ann in ("int", "bool", "str"):
                static_typed.add(arg.arg)
        for arg, default in list(zip(reversed(pos_args),
                                     reversed(a.defaults))) + \
                list(zip(a.kwonlyargs, a.kw_defaults)):
            if isinstance(default, ast.Constant) and \
                    isinstance(default.value, (bool, str)):
                static_typed.add(arg.arg)
        params = list(info.param_names)
        offset = 0
        if params and params[0] in ("self", "cls"):
            offset = 1
        traced = set()
        for i, p in enumerate(params[offset:]):
            if i in jit.static or p in jit.static_names or \
                    p in static_typed:
                continue
            traced.add(p)
        return traced
