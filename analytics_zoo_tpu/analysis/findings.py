"""zoolint data model: rules, findings, inline suppressions.

A *rule* is a static check with a stable id (``JG-*`` for JAX/tracer
rules, ``THR-*`` for concurrency rules), a one-line description and a
fix-it hint.  A *finding* is one concrete violation: rule + location +
scope + message.  Findings are plain data so every consumer (human
report, strict JSON, baseline diff, the pytest gate) works off the same
objects.

Suppressions are inline comments on the offending line::

    self.records_served += n  # zoolint: disable=THR-GUARD(sampled stat)

Multiple rules separate with commas; the parenthesized reason is
required — an unexplained suppression is itself a finding
(``LINT-BARE-DISABLE``), because "why is this OK?" is exactly what the
next reader needs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    hint: str


_RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    _RULES[rule.id] = rule
    return rule


def all_rules() -> List[Rule]:
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str) -> Optional[Rule]:
    return _RULES.get(rule_id)


# JAX / tracer rules -------------------------------------------------------
JG_IMPURE_CALL = register(Rule(
    "JG-IMPURE-CALL",
    "side-effecting host call inside a jitted/traced scope",
    "side effects run once at trace time, not per step; move the call "
    "outside the jitted function or use jax.debug.print/jax.debug.callback"))
JG_GLOBAL_MUT = register(Rule(
    "JG-GLOBAL-MUT",
    "global-state mutation inside a jitted/traced scope",
    "tracer functions must be pure; thread the value through the carry "
    "or return it instead of mutating a global"))
JG_HOST_SYNC = register(Rule(
    "JG-HOST-SYNC",
    "host materialization of a traced value inside a jitted scope",
    "float()/int()/.item()/np.asarray() on a tracer aborts tracing or "
    "forces a device sync; keep the value as a jnp array and convert "
    "after the jitted call returns"))
JG_TRACED_BRANCH = register(Rule(
    "JG-TRACED-BRANCH",
    "Python control flow on a traced value inside a jitted scope",
    "`if`/`while` on a tracer raises ConcretizationTypeError or bakes "
    "the branch at trace time; use jax.lax.cond / jnp.where / "
    "lax.while_loop, or mark the argument static"))
JG_JIT_IN_LOOP = register(Rule(
    "JG-JIT-IN-LOOP",
    "jax.jit(...) constructed inside a loop body",
    "a fresh jit wrapper per iteration recompiles every time; hoist the "
    "jax.jit call out of the loop and reuse the compiled handle"))
JG_STATIC_UNSTABLE = register(Rule(
    "JG-STATIC-UNSTABLE",
    "unhashable literal passed in a static_argnums position",
    "static args are hashed into the compilation cache key; lists/dicts/"
    "sets are unhashable (TypeError) — pass a tuple or a hashable config"))
JG_TRANSFER_HOT = register(Rule(
    "JG-TRANSFER-HOT",
    "implicit/blocking device->host transfer inside a hot per-batch loop",
    "device_get/np.asarray/float()/block_until_ready inside the per-batch "
    "loop serializes host and device; batch the sync at epoch granularity "
    "or keep the value on device"))
JG_DONATE_REUSE = register(Rule(
    "JG-DONATE-REUSE",
    "donated buffer read after being passed to a donating jitted call",
    "donate_argnums invalidates the argument's buffer at dispatch; "
    "rebind the name from the call's result (x, ... = step(x, ...)) "
    "before reading it again"))

# concurrency rules --------------------------------------------------------
THR_GUARD = register(Rule(
    "THR-GUARD",
    "field accessed without the lock that guards its other accesses",
    "every access to a lock-guarded field must hold the same lock; wrap "
    "the access in `with self.<lock>:` (or document why the race is "
    "benign with a zoolint disable + reason)"))
THR_BLOCK = register(Rule(
    "THR-BLOCK",
    "blocking call while holding a lock",
    "sleep/join/queue I/O/device sync under a lock stalls every other "
    "thread contending for it; move the blocking call outside the "
    "critical section and re-acquire afterwards"))
THR_ORDER = register(Rule(
    "THR-ORDER",
    "locks acquired in inconsistent order across the module",
    "two code paths nesting the same locks in opposite order can "
    "deadlock; pick one global order and re-nest the later site"))
THR_SHARED_MUT = register(Rule(
    "THR-SHARED-MUT",
    "plain field shared between a background thread and other methods "
    "with no lock",
    "a field written from a Thread target and read elsewhere needs a "
    "lock (or an Event/Queue) — CPython won't tear the write, but "
    "readers can see arbitrarily stale state and compound updates race"))

# meta rule ----------------------------------------------------------------
LINT_BARE_DISABLE = register(Rule(
    "LINT-BARE-DISABLE",
    "zoolint disable comment without a reason",
    "write `# zoolint: disable=RULE(why this is safe)` — the reason is "
    "the documentation the next reader needs"))


# --------------------------------------------------------------------------
# findings
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    scope: str         # dotted qualname of the enclosing def/class ('' = module)
    message: str

    @property
    def hint(self) -> str:
        r = get_rule(self.rule)
        return r.hint if r else ""

    def key(self) -> Tuple[str, str, str, str]:
        """Line-number-free identity used by the baseline, so unrelated
        edits moving code up/down don't invalidate baseline entries."""
        return (self.rule, self.path, self.scope, self.message)

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "scope": self.scope,
                "message": self.message, "hint": self.hint}

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.rule}{scope}: {self.message}"


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

_DISABLE_RE = re.compile(r"#\s*zoolint:\s*disable=([^#\n]*)")
_RULE_WITH_REASON_RE = re.compile(  # reason may nest one paren level
    r"\s*([A-Z][A-Z0-9-]*)\s*"
    r"(?:\(((?:[^()]|\([^()]*\))*)\))?\s*(?:,|$)")


class Suppressions:
    """Per-line ``# zoolint: disable=RULE(reason)`` map for one file."""

    def __init__(self, source: str):
        # line number (1-based) -> {rule_id: reason or None}
        self.by_line: Dict[int, Dict[str, Optional[str]]] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _DISABLE_RE.search(text)
            if not m:
                continue
            rules: Dict[str, Optional[str]] = {}
            for rm in _RULE_WITH_REASON_RE.finditer(m.group(1)):
                rid, reason = rm.group(1), rm.group(2)
                rules[rid] = reason.strip() if reason else None
            if rules:
                self.by_line[i] = rules

    def suppressed(self, finding: Finding) -> bool:
        rules = self.by_line.get(finding.line)
        if not rules:
            return False
        return finding.rule in rules or "ALL" in rules

    def bare_disable_findings(self, path: str) -> List[Finding]:
        """A disable without a reason is itself reported."""
        out = []
        for line, rules in sorted(self.by_line.items()):
            for rid, reason in rules.items():
                if not reason:
                    out.append(Finding(
                        LINT_BARE_DISABLE.id, path, line, 0, "",
                        f"disable={rid} has no reason; write "
                        f"disable={rid}(<why this is safe>)"))
        return out
