"""Framework context: device discovery + mesh construction.

Replaces the reference's ``NNContext.initNNContext`` (common/NNContext.scala:133-148)
which creates a SparkContext, applies engine config and calls BigDL
``Engine.init``.  On TPU there is no cluster-manager handshake: a single
controller process discovers the devices JAX exposes, builds a
``jax.sharding.Mesh`` over them, and all parallelism is expressed as
shardings over that mesh (XLA inserts the ICI/DCN collectives).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.core.config import ZooConfig

logger = logging.getLogger("analytics_zoo_tpu")

_GLOBAL_CONTEXT: Optional["ZooContext"] = None
# coordination args of the live jax.distributed cluster (None = never
# initialised through this module; _EXTERNAL_CLUSTER = initialised by a
# launcher outside this module, so no args to compare against)
_EXTERNAL_CLUSTER = ("<external>",)
_DISTRIBUTED_ARGS: Optional[tuple] = None


def explicit_prng_key(seed: int) -> "jax.Array":
    """``jax.random.PRNGKey`` with an EXPLICIT host->device transfer of
    the seed.  ``PRNGKey(int)`` converts the Python scalar implicitly,
    which trips ``jax.transfer_guard("disallow")`` — the runtime guard
    the transfer-audited test suites (and zoolint's JG-TRANSFER-HOT
    rule) use to prove hot paths move no hidden bytes.  Routing the one
    real transfer through ``device_put`` keeps it visible and keeps
    seed-derived keys bit-identical to ``PRNGKey(seed)``."""
    import jax

    return jax.random.PRNGKey(jax.device_put(np.uint32(seed)))


@dataclass
class ZooContext:
    """Holds the device mesh and global config.

    The mesh always exists (1-device meshes are fine) so every code path is
    written SPMD-first; single-chip is just the degenerate mesh.
    """

    config: ZooConfig
    mesh: "jax.sharding.Mesh"

    # ------------------------------------------------------------------
    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    @property
    def data_axis(self) -> str:
        return self.config.mesh_axis_names[0]

    def data_sharding(self, ndim: int = 1):
        """NamedSharding that shards dim 0 over the data axis, replicates rest."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.data_axis, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    @property
    def process_count(self) -> int:
        import jax

        return jax.process_count()

    @property
    def local_device_count(self) -> int:
        import jax

        return jax.local_device_count()


def init_zoo_context(
    config: Optional[ZooConfig] = None,
    *,
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
    multihost: bool = False,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **config_overrides,
) -> ZooContext:
    """Initialise (or re-initialise) the global framework context.

    Parameters mirror capabilities of ``init_nncontext`` /
    ``init_spark_on_local`` / ``init_spark_on_yarn``
    (reference pyzoo/zoo/common/nncontext.py:23-104): instead of a Spark
    master/cores/executors topology the caller describes a device mesh.

    ``multihost=True`` runs ``jax.distributed.initialize()`` so the same
    program scales to multi-host pods over DCN (replacing the reference's
    Spark-driver + block-manager transport, docs/wp-bigdl.md:140-160).
    """
    global _GLOBAL_CONTEXT
    import jax

    if config is None:
        config = ZooConfig.from_env(**config_overrides)
    elif config_overrides:
        config = config.replace(**config_overrides)

    logging.basicConfig(level=getattr(logging, config.log_level.upper(), 20))

    if multihost:
        # On TPU pods the three coordination args are discovered from the
        # environment; on CPU/GPU clusters (or tests) they are explicit.
        # NOTE: must run before anything touches the XLA backend (even
        # jax.process_count()), so initialisation state is tracked here
        # explicitly rather than by string-matching the RuntimeError
        # (whose message changes across JAX versions).
        global _DISTRIBUTED_ARGS
        args = (coordinator_address, num_processes, process_id)
        if _DISTRIBUTED_ARGS is None and _distributed_client_live():
            # initialised outside this module (e.g. directly by the
            # launcher): adopt the live cluster; the caller's args were
            # never applied, so there is nothing to compare against later
            logger.warning(
                "jax.distributed was initialised outside init_zoo_context;"
                " multihost coordination args are ignored")
            _DISTRIBUTED_ARGS = _EXTERNAL_CLUSTER
        elif _DISTRIBUTED_ARGS is None:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id)
                _DISTRIBUTED_ARGS = args
            except RuntimeError:
                # safety net for when the liveness probe's private API
                # drifts: an already-initialised cluster must stay a
                # benign adopt, never a startup crash
                if not _distributed_client_live():
                    raise
                logger.warning(
                    "jax.distributed already initialised; multihost "
                    "coordination args are ignored")
                _DISTRIBUTED_ARGS = _EXTERNAL_CLUSTER
        elif _DISTRIBUTED_ARGS is _EXTERNAL_CLUSTER:
            logger.warning(
                "jax.distributed cluster was initialised externally; "
                "multihost coordination args are ignored")
        elif args != _DISTRIBUTED_ARGS:
            # Re-init with DIFFERENT coordination args cannot be honored —
            # the live cluster keeps its topology; silently dropping the
            # new args would hide a real misconfiguration.
            raise RuntimeError(
                "jax.distributed already initialised with "
                f"{_DISTRIBUTED_ARGS}; cannot re-initialise with {args}. "
                "Restart the process to change cluster coordination.")

    if mesh_shape is not None:
        config = config.replace(mesh_shape=tuple(mesh_shape))
    if axis_names is not None:
        config = config.replace(mesh_axis_names=tuple(axis_names))

    devices = jax.devices(config.platform) if config.platform else jax.devices()
    mesh = make_mesh(devices, config.mesh_shape, config.mesh_axis_names)

    _GLOBAL_CONTEXT = ZooContext(config=config, mesh=mesh)
    logger.info(
        "init_zoo_context: %d device(s) %s, mesh %s axes %s",
        len(devices),
        devices[0].platform,
        dict(zip(mesh.axis_names, mesh.devices.shape)),
        mesh.axis_names,
    )
    return _GLOBAL_CONTEXT


def _distributed_client_live() -> bool:
    """True when a jax.distributed client already exists in this process
    (initialised by a launcher before init_zoo_context ran)."""
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:       # private API moved: assume not initialised
        return False


def make_mesh(devices, mesh_shape, axis_names) -> "jax.sharding.Mesh":
    from jax.sharding import Mesh

    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(mesh_shape)) != n:
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {np.prod(mesh_shape)} devices, "
            f"have {n}"
        )
    # ICI-topology-aware device placement: on real TPU slices
    # mesh_utils orders devices so the minor mesh axes ride physical
    # ICI rings (collectives on the model/expert axis stay on-chip
    # links instead of hopping the torus).  Falls back to a plain
    # reshape on CPU meshes / single hosts where it doesn't apply.
    dev_array = None
    if devices and getattr(devices[0], "platform", "") == "tpu":
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                tuple(mesh_shape), devices=devices)
        except Exception:
            dev_array = None
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, tuple(axis_names))


def get_zoo_context() -> ZooContext:
    """Current global context, creating a default one on first use."""
    global _GLOBAL_CONTEXT
    if _GLOBAL_CONTEXT is None:
        _GLOBAL_CONTEXT = init_zoo_context()
    return _GLOBAL_CONTEXT


def set_zoo_context(ctx: ZooContext) -> None:
    global _GLOBAL_CONTEXT
    _GLOBAL_CONTEXT = ctx
