"""Framework context: device discovery + mesh construction.

Replaces the reference's ``NNContext.initNNContext`` (common/NNContext.scala:133-148)
which creates a SparkContext, applies engine config and calls BigDL
``Engine.init``.  On TPU there is no cluster-manager handshake: a single
controller process discovers the devices JAX exposes, builds a
``jax.sharding.Mesh`` over them, and all parallelism is expressed as
shardings over that mesh (XLA inserts the ICI/DCN collectives).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.core.config import ZooConfig

logger = logging.getLogger("analytics_zoo_tpu")

_GLOBAL_CONTEXT: Optional["ZooContext"] = None
# coordination args of the live jax.distributed cluster (None = never
# initialised through this module; _EXTERNAL_CLUSTER = initialised by a
# launcher outside this module, so no args to compare against)
_EXTERNAL_CLUSTER = ("<external>",)
_DISTRIBUTED_ARGS: Optional[tuple] = None


def explicit_prng_key(seed: int) -> "jax.Array":
    """``jax.random.PRNGKey`` with an EXPLICIT host->device transfer of
    the seed.  ``PRNGKey(int)`` converts the Python scalar implicitly,
    which trips ``jax.transfer_guard("disallow")`` — the runtime guard
    the transfer-audited test suites (and zoolint's JG-TRANSFER-HOT
    rule) use to prove hot paths move no hidden bytes.  Routing the one
    real transfer through ``device_put`` keeps it visible and keeps
    seed-derived keys bit-identical to ``PRNGKey(seed)``."""
    import jax

    return jax.random.PRNGKey(jax.device_put(np.uint32(seed)))


@dataclass
class ZooContext:
    """Holds the device mesh and global config.

    The mesh always exists (1-device meshes are fine) so every code path is
    written SPMD-first; single-chip is just the degenerate mesh.
    """

    config: ZooConfig
    mesh: "jax.sharding.Mesh"

    # ------------------------------------------------------------------
    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    @property
    def data_axis(self) -> str:
        return self.config.mesh_axis_names[0]

    def data_sharding(self, ndim: int = 1):
        """NamedSharding that shards dim 0 over the data axis, replicates rest."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.data_axis, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    @property
    def process_count(self) -> int:
        import jax

        return jax.process_count()

    @property
    def local_device_count(self) -> int:
        import jax

        return jax.local_device_count()


def init_zoo_context(
    config: Optional[ZooConfig] = None,
    *,
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
    multihost: bool = False,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **config_overrides,
) -> ZooContext:
    """Initialise (or re-initialise) the global framework context.

    Parameters mirror capabilities of ``init_nncontext`` /
    ``init_spark_on_local`` / ``init_spark_on_yarn``
    (reference pyzoo/zoo/common/nncontext.py:23-104): instead of a Spark
    master/cores/executors topology the caller describes a device mesh.

    ``multihost=True`` runs ``jax.distributed.initialize()`` so the same
    program scales to multi-host pods over DCN (replacing the reference's
    Spark-driver + block-manager transport, docs/wp-bigdl.md:140-160).
    """
    global _GLOBAL_CONTEXT
    import jax

    if config is None:
        config = ZooConfig.from_env(**config_overrides)
    elif config_overrides:
        config = config.replace(**config_overrides)

    logging.basicConfig(level=getattr(logging, config.log_level.upper(), 20))

    if multihost:
        # On TPU pods the three coordination args are discovered from the
        # environment; on CPU/GPU clusters (or tests) they are explicit.
        # NOTE: must run before anything touches the XLA backend (even
        # jax.process_count()), so initialisation state is tracked here
        # explicitly rather than by string-matching the RuntimeError
        # (whose message changes across JAX versions).
        global _DISTRIBUTED_ARGS
        args = (coordinator_address, num_processes, process_id)
        if _DISTRIBUTED_ARGS is None and _distributed_client_live():
            # initialised outside this module (e.g. directly by the
            # launcher): adopt the live cluster; the caller's args were
            # never applied, so there is nothing to compare against later
            logger.warning(
                "jax.distributed was initialised outside init_zoo_context;"
                " multihost coordination args are ignored")
            _DISTRIBUTED_ARGS = _EXTERNAL_CLUSTER
        elif _DISTRIBUTED_ARGS is None:
            if _initialize_distributed(config, coordinator_address,
                                       num_processes, process_id):
                _DISTRIBUTED_ARGS = args
            else:
                _DISTRIBUTED_ARGS = _EXTERNAL_CLUSTER
        elif _DISTRIBUTED_ARGS is _EXTERNAL_CLUSTER:
            logger.warning(
                "jax.distributed cluster was initialised externally; "
                "multihost coordination args are ignored")
        elif args != _DISTRIBUTED_ARGS:
            # Re-init with DIFFERENT coordination args cannot be honored —
            # the live cluster keeps its topology; silently dropping the
            # new args would hide a real misconfiguration.
            raise RuntimeError(
                "jax.distributed already initialised with "
                f"{_DISTRIBUTED_ARGS}; cannot re-initialise with {args}. "
                "Restart the process to change cluster coordination.")

    if mesh_shape is not None:
        config = config.replace(mesh_shape=tuple(mesh_shape))
    if axis_names is not None:
        config = config.replace(mesh_axis_names=tuple(axis_names))

    devices = jax.devices(config.platform) if config.platform else jax.devices()
    mesh = make_mesh(devices, config.mesh_shape, config.mesh_axis_names)

    _GLOBAL_CONTEXT = ZooContext(config=config, mesh=mesh)
    logger.info(
        "init_zoo_context: %d device(s) %s, mesh %s axes %s",
        len(devices),
        devices[0].platform,
        dict(zip(mesh.axis_names, mesh.devices.shape)),
        mesh.axis_names,
    )
    return _GLOBAL_CONTEXT


def _initialize_distributed(config: ZooConfig, coordinator_address,
                            num_processes, process_id) -> bool:
    """Join (or start) the jax.distributed coordination service, with
    bounded retry: a slow-starting coordinator, a just-released port
    still in TIME_WAIT, or a transient DNS hiccup must not fail a worker
    on first contact — the whole point of elastic restarts is that
    workers come back at slightly different times.

    Returns True when this call initialised the cluster, False when a
    live cluster was adopted instead (initialised concurrently by a
    launcher).  Retries count in ``dist_init_retries_total``.
    """
    import jax

    from analytics_zoo_tpu.observe import metrics as obs
    from analytics_zoo_tpu.robust.retry import RetryPolicy

    # The CPU backend refuses computations that span processes unless an
    # explicit cross-process collectives layer is configured ("Multiprocess
    # computations aren't implemented on the CPU backend"), so multihost
    # on CPU — local elastic rehearsals, the multi-process test suites —
    # defaults to gloo before the backend client is created.  TPU/GPU
    # platforms never consult the flag, and a user's explicit choice
    # (e.g. "mpi") is left alone.
    try:
        from jax._src import xla_bridge as _xb
        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        logger.debug("gloo CPU collectives unavailable on this jaxlib",
                     exc_info=True)

    adopted = []

    def _attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except RuntimeError:
            # "already initialised" must stay a benign adopt (never a
            # retry loop, never a startup crash); anything else — refused
            # connection, bind failure — is transient and retryable
            if not _distributed_client_live():
                raise
            logger.warning(
                "jax.distributed already initialised; multihost "
                "coordination args are ignored")
            adopted.append(True)

    policy = RetryPolicy.from_config(
        config,
        retry_on=(RuntimeError, OSError, ConnectionError),
        name="dist_init",
        on_retry=lambda attempt, exc: obs.count(
            "dist_init_retries_total", flat="robust/dist_init_retries"))
    policy.call(_attempt)
    return not adopted


# Hooks fired (with the lost process ids) when Python-side detection —
# a dispatch-barrier deadline, a harvest timeout — declares a pod
# member dead.  The serving fabric points these at
# ``ClusterServing.notify_host_lost`` so the FIRST detection
# quarantines every model's mesh replica, not just the one whose
# dispatch tripped the deadline.
#
# Detection is deliberately Python-side only.  The coordination
# client's own heartbeat detector cannot be softened on this jaxlib:
# its ``missed_heartbeat_callback`` default is ``LOG(QFATAL)``, and a
# Python replacement is un-invocable (the error-poll thread cannot
# convert the ``absl::Status`` argument, so invoking it terminates the
# process just as fatally).  The fabric therefore keeps pod processes
# off that path entirely — barrier deadlines fire within
# ``dist_barrier_timeout_s`` (seconds), long before the ~100 s
# heartbeat detector, and members never time out a live barrier
# (a member that abandons a barrier seq poisons it for the peers that
# arrive later).
_PEER_LOSS_HOOKS: List[Any] = []


def on_peer_loss(fn) -> None:
    """Register ``fn(process_id)`` to run when a pod member is declared
    dead by Python-side detection (see :func:`report_peer_loss`).  The
    serving fabric points this at ``ClusterServing.notify_host_lost``
    so one detection quarantines every affected mesh replica."""
    _PEER_LOSS_HOOKS.append(fn)


def remove_peer_loss_hook(fn) -> None:
    try:
        _PEER_LOSS_HOOKS.remove(fn)
    except ValueError:
        pass


def report_peer_loss(process_ids: Sequence[int], reason: str = "") -> None:
    """Declare pod members dead and fan the loss out to every
    registered hook.  Called by the serving fabric's barrier-deadline
    path (``PodCoordinator.host_lost``); counts
    ``dist_peer_loss_total`` so survived peer losses are visible next
    to the stock client's would-have-been-fatal behavior."""
    from analytics_zoo_tpu.observe import metrics as obs

    lost = sorted({int(p) for p in process_ids})
    logger.warning(
        "peer loss reported for process(es) %s%s (continuing — host "
        "loss is survivable)", lost, f": {reason}" if reason else "")
    obs.count("dist_peer_loss_total", flat="robust/dist_peer_loss")
    for fn in list(_PEER_LOSS_HOOKS):
        for pid in lost:
            try:
                fn(pid)
            except Exception:
                logger.exception("peer-loss hook %r failed", fn)


def dist_barrier(name: str, timeout_s: Optional[float] = None,
                 phase: str = "other") -> float:
    """Deadline-bounded cross-process barrier over the jax.distributed
    coordination service; returns the seconds spent waiting.

    A peer that fails to reach the barrier within ``timeout_s`` (default
    ``dist_barrier_timeout_s`` from the active config) is presumed dead:
    the wait raises a typed :class:`~analytics_zoo_tpu.robust.errors.HostLostError`
    instead of hanging, and the timeout counts in
    ``dist_barrier_timeouts_total{phase=...}``.  Single-process runs
    return immediately (0.0) — every caller can be written SPMD-first.

    ``name`` must be unique per synchronisation point (the checkpoint
    protocol embeds the step number); ``phase`` is the bounded metric
    label (``write`` / ``commit`` / ``other``).
    """
    import time as _time

    import jax

    from analytics_zoo_tpu.observe import metrics as obs
    from analytics_zoo_tpu.robust import faults
    from analytics_zoo_tpu.robust.errors import HostLostError

    if timeout_s is None:
        cfg = (_GLOBAL_CONTEXT.config if _GLOBAL_CONTEXT is not None
               else ZooConfig())
        timeout_s = cfg.dist_barrier_timeout_s
    plan = faults.fire("dist.barrier_timeout")
    if plan is not None:
        obs.count("dist_barrier_timeouts_total", phase=phase,
                  flat="robust/dist_barrier_timeouts")
        raise (plan.exc if plan.exc is not None else HostLostError(
            f"barrier {name!r}: injected peer loss "
            f"(deadline {timeout_s}s)", barrier=name, timeout_s=timeout_s))
    if jax.process_count() <= 1:
        return 0.0
    from jax._src.distributed import global_state
    client = global_state.client
    t0 = _time.perf_counter()
    try:
        if client is not None and hasattr(client, "wait_at_barrier"):
            client.wait_at_barrier(name, timeout_in_ms=max(
                1, int(timeout_s * 1000)))
        else:
            # coordination client unavailable (private API moved):
            # fall back to the device-level sync — correct, but a dead
            # peer hangs until the collective layer's own timeout
            from jax.experimental import multihost_utils
            logger.warning("dist_barrier %r: no coordination client; "
                           "falling back to sync_global_devices "
                           "(no deadline)", name)
            multihost_utils.sync_global_devices(name)
    except Exception as e:
        obs.count("dist_barrier_timeouts_total", phase=phase,
                  flat="robust/dist_barrier_timeouts")
        raise HostLostError(
            f"barrier {name!r}: peer missed the {timeout_s}s deadline "
            f"and is presumed dead ({type(e).__name__}: {e})",
            barrier=name, timeout_s=timeout_s) from e
    return _time.perf_counter() - t0


class HostRoster:
    """Epoch-tagged membership view of a serving pod's processes.

    The serving fabric's source of truth for which member hosts of a
    mesh replica are believed alive.  Every membership change bumps the
    ``epoch``; the quarantine broadcast and the supervisor's heal/shed
    decisions key off epochs, so concurrent observers of the same host
    death collapse into one atomic reaction (docs/SERVING.md
    "Pod-scale serving").

    All state transitions happen under one lock (marking a host lost
    and bumping the epoch must be indivisible — an unlocked roster
    write is exactly the THR-SHARED-MUT hazard the lint fixture pins).
    The clock is injectable so fast tests fabricate loss ages instead
    of sleeping; there is no ``jax`` dependency — OS-process pods feed
    it from barrier timeouts, fast tests feed it by hand.
    """

    def __init__(self, process_ids: Sequence[int], *, clock=None):
        import threading
        import time as _time

        self._lock = threading.Lock()
        self._clock = clock or _time.monotonic
        self._expected = tuple(int(p) for p in process_ids)
        self._alive = set(self._expected)
        self._epoch = 0
        self._lost_t: Optional[float] = None

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def expected(self) -> Tuple[int, ...]:
        return self._expected

    def alive(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._alive))

    def mark_lost(self, process_id: int) -> int:
        """Record a presumed-dead member; returns the NEW epoch.  A
        repeat loss of an already-lost host does not bump the epoch
        (the same death observed twice is one event)."""
        process_id = int(process_id)
        with self._lock:
            if process_id in self._alive:
                self._alive.discard(process_id)
                self._epoch += 1
                self._lost_t = self._clock()
            return self._epoch

    def mark_alive(self, process_id: int) -> int:
        """Record a (re)joined member; returns the new epoch."""
        process_id = int(process_id)
        with self._lock:
            if process_id in self._expected and \
                    process_id not in self._alive:
                self._alive.add(process_id)
                self._epoch += 1
                if self._alive == set(self._expected):
                    self._lost_t = None
            return self._epoch

    def healed(self) -> bool:
        """True when every expected member is believed alive."""
        with self._lock:
            return self._alive == set(self._expected)

    def lost(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(set(self._expected) - self._alive))

    def lost_age_s(self) -> float:
        """Seconds the roster has been degraded (0.0 while whole)."""
        with self._lock:
            if self._lost_t is None:
                return 0.0
            return max(0.0, self._clock() - self._lost_t)

    def snapshot(self) -> dict:
        with self._lock:
            return {"epoch": self._epoch,
                    "expected": list(self._expected),
                    "alive": sorted(self._alive),
                    "lost": sorted(set(self._expected) - self._alive),
                    "healed": self._alive == set(self._expected)}


def _distributed_client_live() -> bool:
    """True when a jax.distributed client already exists in this process
    (initialised by a launcher before init_zoo_context ran)."""
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:       # private API moved: assume not initialised
        return False


def make_mesh(devices, mesh_shape, axis_names) -> "jax.sharding.Mesh":
    from jax.sharding import Mesh

    n = len(devices)
    if mesh_shape is None:
        mesh_shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(mesh_shape)) != n:
        raise ValueError(
            f"mesh_shape {mesh_shape} needs {np.prod(mesh_shape)} devices, "
            f"have {n}"
        )
    # ICI-topology-aware device placement: on real TPU slices
    # mesh_utils orders devices so the minor mesh axes ride physical
    # ICI rings (collectives on the model/expert axis stay on-chip
    # links instead of hopping the torus).  Falls back to a plain
    # reshape on CPU meshes / single hosts where it doesn't apply.
    dev_array = None
    if devices and getattr(devices[0], "platform", "") == "tpu":
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                tuple(mesh_shape), devices=devices)
        except Exception:
            dev_array = None
    if dev_array is None:
        dev_array = np.asarray(devices).reshape(mesh_shape)
    return Mesh(dev_array, tuple(axis_names))


def get_zoo_context() -> ZooContext:
    """Current global context, creating a default one on first use."""
    global _GLOBAL_CONTEXT
    if _GLOBAL_CONTEXT is None:
        _GLOBAL_CONTEXT = init_zoo_context()
    return _GLOBAL_CONTEXT


def set_zoo_context(ctx: ZooContext) -> None:
    global _GLOBAL_CONTEXT
    _GLOBAL_CONTEXT = ctx
