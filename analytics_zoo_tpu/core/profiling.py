"""Tracing / profiling (SURVEY §5.1).

Reference capability: ``Utils.timeIt(name){...}`` debug-log timers around
hot calls (pipeline/api/net/TFNet.scala:179, tfpark/GraphRunner.scala:132)
and per-iteration BigDL ``Metrics`` aggregation (Topology.scala:1192).

TPU-native design: two complementary mechanisms —
- ``timeit`` / ``scoped_timer``: host-side wall-clock scopes aggregated in
  a process-wide registry (mean/total/count per name), for spotting
  host-bound stages (data prep, device_put, checkpoint writes).
- ``trace``: a context manager around ``jax.profiler`` that captures an
  xprof/TensorBoard-viewable device trace; annotations via
  ``jax.profiler.TraceAnnotation`` inside.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

logger = logging.getLogger("analytics_zoo_tpu.profiling")


# per-stat reservoir of recent durations for percentile rollups; 512
# samples bound memory while keeping p99 meaningful over the last ~minutes
# of a serving stage (the serving pipeline reads p50/p99 per stage)
_MAX_SAMPLES = 512


@dataclass
class _Stat:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    samples: list = field(default_factory=list)  # ring of recent durations
    cursor: int = 0  # next ring slot to overwrite once the ring is full

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)
        if len(self.samples) < _MAX_SAMPLES:
            self.samples.append(dt)
        else:
            # explicit cursor: deriving the slot from the already-
            # incremented count skipped slot 0 a full lap
            self.samples[self.cursor] = dt
            self.cursor = (self.cursor + 1) % _MAX_SAMPLES

    def percentile(self, q: float) -> float:
        """q-th percentile (0-100) over the recent-sample ring."""
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
        return s[i]


class Timers:
    """Process-wide named wall-clock scopes + event counters (thread-safe).

    Counters record *how often* something happened (per-batch
    ``device_put`` dispatches, which data path an Estimator.fit took)
    where a duration would be meaningless; tests assert on them to prove
    hot-path properties ("zero host→device transfers per epoch") instead
    of eyeballing traces."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, _Stat] = {}
        self._counts: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}

    @contextlib.contextmanager
    def scope(self, name: str, log: bool = False) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.observe(name, dt)
            if log:
                logger.info("[timeit] %s: %.3fms", name, dt * 1e3)

    def observe(self, name: str, seconds: float) -> None:
        """Record one duration measured externally (a cross-thread span —
        e.g. request enqueue → response written — that no single
        ``scope`` block can bracket)."""
        with self._lock:
            self._stats.setdefault(name, _Stat()).add(seconds)

    def percentile(self, name: str, q: float) -> float:
        """q-th percentile (0-100) of the named timer's recent samples."""
        with self._lock:
            s = self._stats.get(name)
            return s.percentile(q) if s else 0.0

    def incr(self, name: str, n: int = 1) -> None:
        """Bump the named event counter by ``n``."""
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def count(self, name: str) -> int:
        """Current value of the named counter (0 if never bumped)."""
        with self._lock:
            return self._counts.get(name, 0)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def set_gauge(self, name: str, value: float) -> None:
        """Record a point-in-time level (replicas healthy, heartbeat
        age, queue depth) — unlike counters these overwrite, so the
        reader always sees the current state, not an accumulation."""
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"count": v.count, "total_s": v.total_s,
                        "mean_s": v.mean_s, "max_s": v.max_s,
                        "p50_s": v.percentile(50), "p99_s": v.percentile(99)}
                    for k, v in self._stats.items()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._counts.clear()
            self._gauges.clear()

    def report(self) -> str:
        lines = ["name count total_s mean_ms p50_ms p99_ms max_ms"]
        for k, v in sorted(self.stats().items(),
                           key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{k} {v['count']} {v['total_s']:.3f} "
                         f"{v['mean_s'] * 1e3:.2f} {v['p50_s'] * 1e3:.2f} "
                         f"{v['p99_s'] * 1e3:.2f} {v['max_s'] * 1e3:.2f}")
        counts = self.counts()
        if counts:
            lines.append("-- counters --")
            for k, n in sorted(counts.items()):
                lines.append(f"{k} {n}")
        gauges = self.gauges()
        if gauges:
            lines.append("-- gauges --")
            for k, v in sorted(gauges.items()):
                lines.append(f"{k} {v:g}")
        return "\n".join(lines)


TIMERS = Timers()


def timeit(name: str, log: bool = False):
    """``with timeit("shard_batch"): ...`` — scoped wall-clock timer."""
    return TIMERS.scope(name, log=log)


def count_event(name: str, n: int = 1) -> None:
    """Bump a process-wide event counter (``TIMERS.counts()`` reads it)."""
    TIMERS.incr(name, n)


# jax.profiler supports exactly one active trace per process; track it
# so a nested trace() fails loudly instead of corrupting the session
_trace_lock = threading.Lock()
_active_trace_dir: Optional[str] = None


@contextlib.contextmanager
def trace(log_dir: str, annotation: Optional[str] = None) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace into ``log_dir``
    (view with TensorBoard's profile plugin / xprof).

    Nested ``trace()`` calls raise ``RuntimeError`` (the profiler is a
    process-wide singleton), and a failed ``start_trace`` propagates
    without attempting ``stop_trace`` on a never-started profiler."""
    import jax

    global _active_trace_dir
    with _trace_lock:
        if _active_trace_dir is not None:
            raise RuntimeError(
                f"profiling.trace({log_dir!r}) called while a trace into "
                f"{_active_trace_dir!r} is active; jax.profiler supports "
                "one trace per process — end the outer trace first")
        _active_trace_dir = log_dir
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
        if annotation:
            with jax.profiler.TraceAnnotation(annotation):
                yield
        else:
            yield
    finally:
        with _trace_lock:
            _active_trace_dir = None
        if started:
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up on the device timeline inside a trace."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
