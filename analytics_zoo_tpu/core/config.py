"""Unified typed configuration.

The reference spreads configuration over four mechanisms (Spark conf files,
env vars, JVM system properties, per-app CLI/YAML — see
reference common/NNContext.scala:188-237 and
serving/utils/ClusterServingHelper.scala:104-170).  Here a single dataclass
is the source of truth; env vars with the ``ZOO_`` prefix override fields,
and YAML/dict loading covers the serving use-case.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

_ENV_PREFIX = "ZOO_"


@dataclass
class ZooConfig:
    """Global framework configuration.

    Fields mirror the *capabilities* of the reference's config surface:
    engine/thread tuning becomes XLA/mesh settings, failure-retry knobs keep
    their semantics (reference api/keras/models/Topology.scala:1180-1181).
    """

    # --- device / mesh ---------------------------------------------------
    platform: Optional[str] = None          # None = let JAX pick (tpu>cpu)
    mesh_shape: Optional[Tuple[int, ...]] = None   # None = all devices on "data"
    mesh_axis_names: Tuple[str, ...] = ("data",)
    # Preferred compute dtype for matmul-heavy paths (MXU wants bf16).
    compute_dtype: str = "float32"

    # --- training --------------------------------------------------------
    # Steps fused into one XLA dispatch (lax.scan over a device-resident
    # superbatch).  >1 amortizes per-step host->device latency — essential
    # on remote-tunnel links, and still removes dispatch overhead on-host.
    steps_per_execution: int = 1
    # Failure-retry semantics of InternalDistriOptimizer.train
    # (reference Topology.scala:1179-1261).
    failure_retry_times: int = 5
    failure_retry_interval_s: float = 120.0
    checkpoint_dir: Optional[str] = None
    # Async checkpointing (orbax) on by default.
    async_checkpoint: bool = True

    # --- data ------------------------------------------------------------
    # Memory tier for FeatureSet caches: DRAM | DISK_AND_DRAM | DIRECT
    # (reference feature/pmem/NativeArray.scala:21-37; PMEM itself has no
    # TPU-host equivalent — DISK_AND_DRAM covers the capacity use-case).
    default_memory_type: str = "DRAM"
    data_prefetch: int = 2                  # batches prefetched to device
    shuffle_buffer: int = 10000
    # Cache level for FeatureSets that don't pin one themselves: HOST
    # keeps the reference behaviour (host batches + prefetch/device_put);
    # DEVICE materializes the dataset into HBM once and runs the
    # Estimator's device-resident epoch body (on-device shuffle +
    # in-step minibatch gather, zero host→device bytes per epoch) — the
    # TPU analog of the reference's PMEM/DRAM cached partitions
    # (feature/FeatureSet.scala:690-722).
    data_cache_level: str = "HOST"
    # HBM budget for DEVICE caching; datasets above it stream
    # budget-sized shards through HBM (CacheLevel.STREAM — the tier
    # auto-router is replicated < budget < stream < host) with the host
    # prefetch path as the final fallback (4 GiB default leaves room
    # for params/activations on every shipping TPU generation).
    data_device_budget_bytes: int = 4 << 30
    # STREAM tier: HBM shard slots alive at once.  2 = double
    # buffering — shard N+1 uploads on the background uploader thread
    # while the jitted shard program trains on shard N.
    data_stream_slots: int = 2
    # Compressed device cache for STREAM shards: None keeps shards at
    # their native dtype; "uint8" (affine) / "int8" (symmetric) encode
    # FLOAT feature arrays host-side and decode them in-kernel after
    # the minibatch gather (ops/quantization.py), stretching the
    # effective device budget ~4x for image/embedding features.
    # Labels and integer arrays always pass through unquantized.
    data_cache_dtype: Optional[str] = None
    # Fused embedding-bag kernel routing (ops/embedding_bag.py) for the
    # recommenders' multi-hot lookups: "auto" lets ops.dispatch pick
    # (Pallas on TPU above its win threshold), "on" insists on the
    # kernel wherever shapes allow, "off" pins the XLA gather path.
    fused_embedding: str = "auto"
    # Within-batch duplicate-id dedup for embedding lookups
    # (ops/embedding_bag.py embedding_bag_dedup): "auto" dedups the
    # sharded-table lookup path only (where duplicate rows pay full HBM
    # + exchange price), "on" dedups every bag lookup, "off" pins the
    # naive per-slot gather.  Exact-parity custom_vjp either way.
    dedup_ids: str = "auto"
    # Hot-row replication cache for SERVING lookups against row-sharded
    # tables (parallel/hot_cache.py): "auto"/"on" lets deploy serving
    # build a per-table top-K replica cache so hot ids resolve from a
    # chip-local copy and skip the psum exchange; "off" disables cache
    # construction entirely.  Training never reads the cache (optimizer
    # writes stay authoritative).
    table_hot_cache: str = "auto"
    # Rows held per hot cache (top-K by observed lookup frequency).
    table_hot_cache_capacity: int = 1024
    # Seconds between cache refreshes from the authoritative shards; a
    # refresh re-ranks the top-K from the live frequency counts and
    # re-reads the row values, bounding staleness to one period.
    table_hot_cache_refresh_s: float = 30.0
    # Ring-attention routing (ops/ring_attention.py) for sequence-
    # parallel long context: "auto" rings only on a mesh with a >1-way
    # seq axis above RING_MIN_LEN tokens, "on" insists wherever a mesh
    # allows, "off" pins the single-device blockwise path.
    ring_attention: str = "auto"
    # Sequence shards for the attention layers when no explicit
    # sequence-parallel regime is active: >1 makes MultiHeadAttention
    # build a seq mesh over that many devices and route self-attention
    # through the ring (docs/PARALLELISM.md "Sequence parallelism").
    # 0 = off (a compile(sharding="sp") regime still takes precedence).
    seq_shards: int = 0

    # --- serving ---------------------------------------------------------
    # Pipelined serving engine (docs/SERVING.md).  The DynamicBatcher
    # dispatches a shape bucket on whichever comes first: batch-full
    # (serving_batch_size rows) or the serving_max_batch_delay_ms
    # deadline — the continuous-batching tradeoff between latency under
    # trickle load and MXU utilization under saturation.
    serving_batch_size: int = 32
    serving_max_batch_delay_ms: float = 5.0
    # Decode-pool threads: base64/JSON decode + host preprocess run off
    # the device hot path, concurrently with device compute.
    serving_decode_workers: int = 4
    # Model replicas round-robined by the device executor (one full copy
    # per mesh device along the data axis; 1 = single-chip serving).
    serving_replicas: int = 1
    # Batches in flight per executor (2 = double buffering: batch N+1 is
    # enqueued while N computes; also the backpressure bound).
    serving_max_inflight: int = 2
    # Self-healing serving (docs/SERVING.md "Failure semantics"): each
    # replica's circuit breaker quarantines it after this many
    # CONSECUTIVE dispatch/harvest failures...
    serving_breaker_threshold: int = 3
    # ...and lets one half-open probe through after this cooldown; a
    # quarantined replica still open past the cooldown is rebuilt by
    # the supervisor and hot-swapped in.
    serving_breaker_cooldown_s: float = 2.0
    # How often the supervisor thread runs its repair checks (replica
    # rebuild, harvest watchdog, stage restarts, health gauges).
    serving_supervisor_interval_s: float = 0.25
    # A pipeline stage whose heartbeat is older than this while the
    # worker runs is treated as wedged and restarted.
    serving_stage_stall_s: float = 10.0
    # A device harvest readback blocking longer than this is a hung
    # dispatch: the replica is quarantined, its in-flight records are
    # requeued, and the harvest stage restarts.
    serving_harvest_deadline_s: float = 30.0
    # Default client TTL applied to records that don't carry their own
    # ``ttl_ms`` (None = records without a TTL never expire).  Expired
    # work is shed with a structured "expired" error before paying
    # decode/dispatch cost.
    serving_default_ttl_ms: Optional[float] = None
    # Serving SLO for the flight recorder (docs/OBSERVABILITY.md): a
    # p99 bound on serving_stage_seconds{stage=e2e}, evaluated over
    # serving_slo_window_s windows by a supervisor check.  0 disables
    # the watcher entirely.
    serving_slo_p99_ms: float = 0.0
    serving_slo_window_s: float = 5.0
    # Queue transport (docs/SERVING.md "Wire format & queue backends"):
    # "memory" (in-process, legacy json wire), "file" (spool dir, binary
    # framed records), "redis" (reference-compatible distributed), or
    # "shm" — the zero-copy shared-memory ring buffer for single-host
    # serving (deploy.make_queue_from_zoo lowers this).
    serving_queue_backend: str = "memory"
    # ShmQueue arena geometry: ring capacity in records and the byte cap
    # per record slot / per result slot.  slots x slot_bytes is the
    # segment's request-arena footprint in /dev/shm; a record that packs
    # larger than slot_bytes is rejected client-side as malformed.
    serving_shm_slots: int = 256
    serving_shm_slot_bytes: int = 1 << 20
    serving_shm_result_slot_bytes: int = 1 << 20
    # Replica weight storage (deploy/inference.py): "float32" keeps full
    # precision; "int8" / "int4" store weights quantized per output
    # channel (1/4, resp. 1/8 of the f32 HBM footprint) and dequantize
    # inside the serving forward — on TPU through the fused
    # dequantize-matmul kernel (ops/dequant_matmul.py).
    serving_weight_dtype: str = "float32"
    # Persistent AOT compile cache (docs/SERVING.md "Warm start &
    # multi-model"): directory where serialized XLA executables are
    # stored per (model fingerprint, bucket signature, mesh); a
    # restarted worker reaches full bucket coverage from disk instead
    # of re-compiling.  Empty string = off.
    serving_compile_cache_dir: str = ""
    # Shared HBM budget for multi-model replica planning (0 = no cap):
    # a replica-grow request that would push the summed weight bytes of
    # every hosted model's replicas past this is refused.
    serving_hbm_budget_bytes: int = 0
    # Metrics-driven autoscaler (deploy/autoscale.py): grows/shrinks
    # decode workers, per-model replicas and the batch deadline from
    # the stage gauges, with hysteresis + cooldown.
    serving_autoscale: bool = False
    serving_autoscale_cooldown_s: float = 5.0
    serving_autoscale_interval_s: float = 1.0

    # --- observability ---------------------------------------------------
    # Bounded ring of completed spans kept by observe.TRACER; any
    # request's timeline is reconstructable while it's inside the ring.
    observe_span_ring: int = 4096
    # Structured JSONL event log (spans as they complete + metric
    # dumps); empty string = off.
    observe_jsonl_path: str = ""
    # Where flight-recorder snapshots (span ring + metrics delta at the
    # moment of an SLO breach / breaker trip) are written; empty = keep
    # the last few in memory only.
    observe_flight_dir: str = ""
    # Arm a short jax.profiler device trace when the flight recorder
    # trips (written under observe_flight_dir/profile).
    observe_profile_on_breach: bool = False

    # --- robustness ------------------------------------------------------
    # What a non-finite training loss does (docs/ROBUSTNESS.md):
    #   "skip"     — the jitted step discards the bad update on device
    #                (params/opt-state keep their pre-step values) and the
    #                epoch-boundary check counts it; training continues.
    #   "rollback" — like skip, plus: >= max_bad_steps CONSECUTIVE bad
    #                steps restores the last checkpoint and scales the
    #                learning rate by nan_backoff_factor.
    #   "raise"    — any bad step raises FloatingPointError at the next
    #                epoch-boundary check (the update was still skipped,
    #                so the surviving params are finite for post-mortem).
    # Checks are epoch-granular: the bad-step counters ride the device
    # carry, so the happy path costs zero extra host syncs.
    nan_policy: str = "skip"
    max_bad_steps: int = 5
    nan_backoff_factor: float = 0.5
    # Verify per-leaf CRC32 manifests on checkpoint restore; torn/corrupt
    # snapshots quarantine and restore falls back to the newest intact one.
    ckpt_verify: bool = True
    # Multi-controller checkpointing (docs/ROBUSTNESS.md "Distributed
    # checkpoints & elastic resume"): each process writes only the
    # shards it owns plus a global manifest, with a two-phase commit so
    # a host dying mid-save leaves a quarantined partial step, never a
    # torn "latest".  Off → every process would race on one archive, so
    # leave this on for any multi-process run.
    ckpt_distributed: bool = True
    # Deadline for every cross-process coordination barrier (checkpoint
    # write/commit phases): a peer missing the barrier for this long is
    # presumed dead and surfaces as a typed HostLostError instead of a
    # hang.  Generous default — pod-scale saves can be slow; tests dial
    # it down to seconds.
    dist_barrier_timeout_s: float = 120.0
    # RetryPolicy defaults (robust/retry.py) — exponential backoff with
    # jitter, bounded by attempts and an optional wall-clock deadline.
    retry_max_attempts: int = 5
    retry_base_delay_s: float = 0.1
    retry_max_delay_s: float = 30.0
    retry_multiplier: float = 2.0
    retry_jitter: float = 0.1
    retry_deadline_s: Optional[float] = None

    # --- logging / summaries --------------------------------------------
    log_level: str = "INFO"
    tensorboard_dir: Optional[str] = None

    # --- misc ------------------------------------------------------------
    seed: int = 42
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_env(cls, **overrides: Any) -> "ZooConfig":
        """Build a config from defaults <- ZOO_* env vars <- overrides."""
        kwargs: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            env_key = _ENV_PREFIX + f.name.upper()
            if env_key in os.environ:
                kwargs[f.name] = _coerce(os.environ[env_key], f.type)
        kwargs.update(overrides)
        return cls(**kwargs)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ZooConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        known = {k: v for k, v in d.items() if k in names}
        extra = {k: v for k, v in d.items() if k not in names}
        cfg = cls(**known)
        cfg.extra.update(extra)
        return cfg

    @classmethod
    def from_yaml(cls, path: str) -> "ZooConfig":
        try:
            import yaml  # type: ignore

            with open(path) as f:
                d = yaml.safe_load(f) or {}
        except ImportError:
            with open(path) as f:
                d = json.load(f)
        return cls.from_dict(_flatten(d))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def replace(self, **kw: Any) -> "ZooConfig":
        return dataclasses.replace(self, **kw)


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}_{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _coerce(raw: str, typ: Any) -> Any:
    t = str(typ)
    if "int" in t and "Tuple" not in t:
        return int(raw)
    if "float" in t:
        return float(raw)
    if "bool" in t:
        return raw.lower() in ("1", "true", "yes", "on")
    if "Tuple" in t or "Sequence" in t:
        return tuple(
            int(x) if x.strip().isdigit() else x.strip() for x in raw.split(",")
        )
    return raw
