from analytics_zoo_tpu.core.config import ZooConfig  # noqa: F401
from analytics_zoo_tpu.core.context import (  # noqa: F401
    HostRoster,
    ZooContext,
    get_zoo_context,
    init_zoo_context,
    make_mesh,
    set_zoo_context,
)
from analytics_zoo_tpu.core.triggers import (  # noqa: F401
    And,
    EveryEpoch,
    MaxEpoch,
    MaxIteration,
    MaxScore,
    MinLoss,
    Or,
    SeveralIteration,
    Trigger,
    TriggerState,
)
