"""Trigger algebra for ending training / checkpointing / validation.

Capability-parity with the reference's ``ZooTrigger`` family
(common/ZooTrigger.scala:33-163): EveryEpoch, SeveralIteration, MaxEpoch,
MaxIteration, MaxScore, MinLoss, and the And/Or combinators.  Triggers are
pure predicates over a ``TrainState``-like record holding counters, so they
live entirely on the host side of the training loop (never traced by XLA).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TriggerState:
    """Host-side snapshot of training progress fed to triggers."""

    epoch: int = 0                 # completed epochs
    iteration: int = 0             # completed global steps
    epoch_finished: bool = False   # True exactly at an epoch boundary
    loss: Optional[float] = None   # most recent training loss
    score: Optional[float] = None  # most recent validation score
    records: int = 0               # samples consumed


class Trigger:
    def __call__(self, state: TriggerState) -> bool:  # pragma: no cover
        raise NotImplementedError

    def __and__(self, other: "Trigger") -> "Trigger":
        return And(self, other)

    def __or__(self, other: "Trigger") -> "Trigger":
        return Or(self, other)


class EveryEpoch(Trigger):
    """Fires at every epoch boundary."""

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch_finished


class SeveralIteration(Trigger):
    """Fires every ``interval`` iterations."""

    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = interval

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration > 0 and state.iteration % self.interval == 0


class MaxEpoch(Trigger):
    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration >= self.max_iteration


class MaxScore(Trigger):
    """Fires once validation score reaches ``max_score``."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state: TriggerState) -> bool:
        return state.score is not None and state.score >= self.max_score


class MinLoss(Trigger):
    """Fires once training loss drops to ``min_loss``."""

    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state: TriggerState) -> bool:
        return state.loss is not None and state.loss <= self.min_loss


class And(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TriggerState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers = triggers

    def __call__(self, state: TriggerState) -> bool:
        return any(t(state) for t in self.triggers)
