"""TensorBoard event-file writer + scalar reader — no TF dependency.

Reference capability: the reference ships its own event writer
(tensorboard/EventWriter.scala:32, FileWriter.scala:32, RecordWriter.scala,
Summary.scala) and a scalar reader (FileReader.scala:80) so it can emit
TB summaries without a TensorFlow dependency.  Same approach here: we
hand-encode the two tiny protobuf messages involved (Event{wall_time, step,
summary{value{tag, simple_value}}}) and the TFRecord framing with masked
CRC-32C.  TensorBoard reads these files directly.
"""

from __future__ import annotations

import glob
import os
import socket
import struct
import time
from typing import Dict, List, Tuple

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven — required by the TFRecord framing.
# ---------------------------------------------------------------------------

_CRC_TABLE = []


def _build_table() -> None:
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# native.masked_crc32c resolves native-vs-python internally (the python
# crc32c table above remains its fallback and golden reference)
from analytics_zoo_tpu.native import masked_crc32c as _masked_crc  # noqa: E402


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format encoding (just what Event/Summary need).
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: float) -> bytes:
    # Summary.Value{ tag=1: string, simple_value=2: float }
    val = _pb_bytes(1, tag.encode()) + _pb_float(2, float(value))
    # Summary{ value=1: repeated Value }
    summary = _pb_bytes(1, val)
    # Event{ wall_time=1: double, step=2: int64, summary=5: Summary }
    return (_pb_double(1, wall_time) + _pb_int64(2, step)
            + _pb_bytes(5, summary))


def encode_file_version_event(wall_time: float) -> bytes:
    # Event{ wall_time=1, file_version=3: string }
    return _pb_double(1, wall_time) + _pb_bytes(3, b"brain.Event:2")


def write_record(f, data: bytes) -> None:
    """TFRecord framing: len(8) + masked_crc(len)(4) + data + masked_crc(data)."""
    header = struct.pack("<Q", len(data))
    f.write(header)
    f.write(struct.pack("<I", _masked_crc(header)))
    f.write(data)
    f.write(struct.pack("<I", _masked_crc(data)))


class SummaryWriter:
    """Append-only scalar summary writer (reference FileWriter.scala:32)."""

    def __init__(self, log_dir: str, flush_secs: float = 10.0):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._last_flush = time.time()
        self.flush_secs = flush_secs
        write_record(self._f, encode_file_version_event(time.time()))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        write_record(self._f,
                     encode_scalar_event(tag, value, step, time.time()))
        if time.time() - self._last_flush > self.flush_secs:
            self.flush()

    def flush(self) -> None:
        self._f.flush()
        self._last_flush = time.time()

    def close(self) -> None:
        self.flush()
        self._f.close()


# ---------------------------------------------------------------------------
# Reader (reference FileReader.scala:80 readScalar)
# ---------------------------------------------------------------------------

def _decode_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _iter_fields(buf: bytes):
    i = 0
    while i < len(buf):
        key, i = _decode_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _decode_varint(buf, i)
        elif wire == 1:
            val = buf[i:i + 8]
            i += 8
        elif wire == 2:
            ln, i = _decode_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def read_scalars(log_dir: str, tag: str) -> List[Tuple[int, float]]:
    """Read (step, value) pairs for ``tag`` from all event files in a dir."""
    out: List[Tuple[int, float]] = []
    for path in sorted(glob.glob(os.path.join(log_dir, "events.out.tfevents.*"))):
        with open(path, "rb") as f:
            data = f.read()
        i = 0
        while i + 12 <= len(data):
            (length,) = struct.unpack("<Q", data[i:i + 8])
            if i + 12 + length + 4 > len(data):
                break  # truncated tail record (torn write); keep the rest
            i += 12  # len + len_crc
            rec = data[i:i + length]
            i += length + 4  # data + data_crc
            step = 0
            summary = None
            for field, wire, val in _iter_fields(rec):
                if field == 2 and wire == 0:
                    step = val
                elif field == 5 and wire == 2:
                    summary = val
            if summary is None:
                continue
            for field, wire, val in _iter_fields(summary):
                if field == 1 and wire == 2:  # Summary.Value
                    vtag, simple = None, None
                    for f2, w2, v2 in _iter_fields(val):
                        if f2 == 1 and w2 == 2:
                            vtag = v2.decode()
                        elif f2 == 2 and w2 == 5:
                            (simple,) = struct.unpack("<f", v2)
                    if vtag == tag and simple is not None:
                        out.append((step, simple))
    return out
