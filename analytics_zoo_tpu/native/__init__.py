"""Native host-side primitives (C++ via ctypes).

The library is compiled lazily on first use (g++ is part of the
toolchain; there is no wheel-building step) into a per-user cache dir,
and every entry point has a pure-python/numpy fallback — importing this
package never fails because a compiler is missing.

Exports:
- ``crc32c(data) -> int``        (castagnoli; slice-by-8 native)
- ``masked_crc32c(data) -> int`` (TFRecord/TB event framing mask)
- ``gather_rows(src, idx) -> np.ndarray``  (parallel batch assembly)
- ``available() -> bool``
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu.native")

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "zoo_native.cpp")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get("ZOO_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), f"zoo_native_{os.getuid()}")
    os.makedirs(cache, exist_ok=True)
    return os.path.join(cache, f"zoo_native_{digest}.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    try:
        so = _cache_path()
        if not os.path.exists(so):
            tmp = so + f".build{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
                 _SRC, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
        lib.zoo_crc32c.restype = ctypes.c_uint32
        lib.zoo_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.zoo_gather_rows.restype = None
        lib.zoo_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
        _LIB = lib
        logger.debug("zoo_native loaded from %s", so)
    except Exception as e:          # no compiler / sandbox / etc.
        logger.info("zoo_native unavailable (%s); using python fallbacks",
                    e)
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# crc32c
# ---------------------------------------------------------------------------

def _py_crc32c(data: bytes) -> int:
    from analytics_zoo_tpu.core.summary import crc32c as py

    return py(data)


def crc32c(data: bytes) -> int:
    lib = _load()
    if lib is None:
        return _py_crc32c(data)
    return int(lib.zoo_crc32c(data, len(data)))


def masked_crc32c(data: bytes) -> int:
    """TFRecord / TB-event masked checksum."""
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# gather_rows
# ---------------------------------------------------------------------------

def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 0) -> np.ndarray:
    """``src[idx]`` for row-major arrays; parallel native memcpy when the
    library is available, numpy fancy indexing otherwise."""
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    lib = _load()
    if lib is None or src.ndim == 0:
        return src[idx]
    # numpy semantics: negatives wrap, out-of-range raises — the C++
    # memcpy path must never read outside the buffer
    n = src.shape[0]
    if idx.size:
        idx = np.where(idx < 0, idx + n, idx)
        lo, hi = int(idx.min()), int(idx.max())
        if lo < 0 or hi >= n:
            raise IndexError(
                f"index out of range for axis 0 with size {n}")
    row_bytes = int(src.dtype.itemsize * np.prod(src.shape[1:], dtype=int))
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    if n_threads <= 0:
        n_threads = min(8, os.cpu_count() or 1)
    lib.zoo_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        len(idx), row_bytes, n_threads)
    return out
