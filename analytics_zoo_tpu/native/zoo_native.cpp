// zoo_native — host-side data-plane primitives.
//
// The reference reaches native code through JNI for exactly these jobs:
// record-format checksums and copies (feature/pmem
// PersistentMemoryAllocator.java:37-43 native copy) and multi-threaded
// minibatch assembly (feature/common/MTSampleToMiniBatch.scala).  Here
// the same roles are a small C++ library loaded via ctypes:
//   - crc32c (castagnoli, slice-by-8): TFRecord / TensorBoard event
//     framing checksums at memory bandwidth instead of a Python loop
//   - gather_rows: parallel row gather (batch assembly) that releases
//     the GIL — called by FeatureSet for large batches.
//
// Built by native/__init__.py with: g++ -O3 -shared -fPIC -pthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

static uint32_t TBL[8][256];

static void build_tables() {
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int k = 0; k < 8; k++)
      crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
    TBL[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = TBL[0][i];
    for (int s = 1; s < 8; s++) {
      crc = TBL[0][crc & 0xFF] ^ (crc >> 8);
      TBL[s][i] = crc;
    }
  }
}

// built once at library load — no lazy-init data race across caller
// threads (prefetch, async checkpoint, TB writer)
struct TableInit { TableInit() { build_tables(); } };
static TableInit table_init;

extern "C" {

uint32_t zoo_crc32c(const uint8_t* data, uint64_t n) {
  uint32_t crc = 0xFFFFFFFFu;
  // slice-by-8 over the aligned middle
  while (n >= 8) {
    uint64_t chunk;
    std::memcpy(&chunk, data, 8);
    chunk ^= crc;                      // little-endian hosts
    crc = TBL[7][chunk & 0xFF] ^ TBL[6][(chunk >> 8) & 0xFF] ^
          TBL[5][(chunk >> 16) & 0xFF] ^ TBL[4][(chunk >> 24) & 0xFF] ^
          TBL[3][(chunk >> 32) & 0xFF] ^ TBL[2][(chunk >> 40) & 0xFF] ^
          TBL[1][(chunk >> 48) & 0xFF] ^ TBL[0][(chunk >> 56) & 0xFF];
    data += 8;
    n -= 8;
  }
  while (n--) crc = TBL[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// Gather rows: dst[i] = src[idx[i]] for fixed-stride rows.
// Parallel memcpy across a thread pool for large batches.
void zoo_gather_rows(const char* src, const int64_t* idx, char* dst,
                     int64_t n_idx, int64_t row_bytes, int32_t n_threads) {
  if (n_threads <= 1 || n_idx < 4 * n_threads) {
    for (int64_t i = 0; i < n_idx; i++)
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                  row_bytes);
    return;
  }
  std::vector<std::thread> workers;
  int64_t per = (n_idx + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int64_t lo = t * per;
    int64_t hi = lo + per < n_idx ? lo + per : n_idx;
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; i++)
        std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes,
                    row_bytes);
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
