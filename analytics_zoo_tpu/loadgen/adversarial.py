"""Adversarial legs: clients that misbehave on purpose.

Production traffic is not all well-formed and prompt.  Three legs
(docs/LOADGEN.md "Adversarial legs") pin that the serving pipeline
degrades *typed*, never silent, and that one bad client cannot starve
its neighbours:

- :class:`SlowClient` — sends a burst, then sits on the answers for
  ``hold_s`` before collecting.  On the shm backend the un-collected
  results pin result slots (the lease protocol); the assertion is that
  a concurrent well-behaved client keeps its own latency while the
  slow one holds.
- :func:`malformed_flood` — pushes raw records straight onto the queue
  *bypassing* ``InputQueue``'s client-side validation (no tensor
  fields, unknown model, garbage TTL).  Every one must come back as a
  typed ``malformed``/``decode_error`` payload.
- :func:`expired_ttl_flood` — enqueues with a TTL that expires before
  any plausible service: the poller sheds them as typed ``expired``
  (or ``overloaded`` via the time-to-answer estimate) without paying
  decode or device time for them.
- :func:`host_kill` — SIGKILLs one *serving process* of a pod at a
  scheduled offset into the storm (a real OS kill, not an injected
  exception).  The survivors must quarantine the whole mesh replica
  within the barrier timeout and every accepted request must still
  terminate as a result or a typed error — the chaos leg behind the
  ``kill`` pod rows in docs/LOADGEN.md.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["SlowClient", "malformed_flood", "expired_ttl_flood",
           "host_kill"]


class SlowClient:
    """Send ``n`` requests, hold the results unclaimed for ``hold_s``,
    then collect.  Returns per-uri terminal values from :meth:`collect`
    so tests can assert the slow traffic itself still terminates."""

    def __init__(self, input_queue, output_queue, model: str,
                 shape=(4,), n: int = 8, hold_s: float = 1.0,
                 uri_prefix: str = "slow", seed: int = 0):
        self.inp = input_queue
        self.outp = output_queue
        self.model = model
        self.shape = tuple(shape)
        self.n = int(n)
        self.hold_s = float(hold_s)
        self.uri_prefix = uri_prefix
        self._rng = np.random.Generator(np.random.PCG64(int(seed)))
        self.uris: List[str] = []

    def send(self) -> List[str]:
        for i in range(self.n):
            uri = f"{self.uri_prefix}-{i:04d}"
            x = self._rng.uniform(0, 1, self.shape).astype(np.float32)
            self.inp.enqueue(uri=uri, model=self.model, x=x)
            self.uris.append(uri)
        return list(self.uris)

    def collect(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Sleep out the hold, then claim every held answer."""
        time.sleep(self.hold_s)
        out: Dict[str, Any] = {}
        for uri in self.uris:
            out[uri] = self.outp.query(uri, timeout=timeout_s)
        return out


def malformed_flood(queue, n: int = 16,
                    uri_prefix: str = "mal") -> List[str]:
    """Push ``n`` invalid records DIRECTLY onto the queue backend —
    past ``InputQueue.enqueue``'s client-side rejection — cycling the
    malformations a hostile or buggy producer would emit.  Returns the
    rids to assert typed answers against."""
    rids: List[str] = []
    for i in range(n):
        uri = f"{uri_prefix}-{i:04d}-{uuid.uuid4().hex[:6]}"
        kind = i % 3
        rec: Dict[str, Any] = {"uri": uri, "ts": time.time(),
                               "fmt": "tensor"}
        if kind == 0:
            pass                             # no tensor fields at all
        elif kind == 1:
            rec["model"] = "no_such_model"   # unroutable
            rec["x"] = np.zeros((2,), np.float32)
        else:
            rec["x"] = {"b64": "!!not-base64!!", "dtype": "float32",
                        "shape": [2]}        # rotten payload
        rids.append(queue.push(rec))
    return rids


def expired_ttl_flood(input_queue, model: Optional[str] = None,
                      n: int = 16, shape=(4,), ttl_ms: float = 0.01,
                      uri_prefix: str = "ttl", seed: int = 0) -> List[str]:
    """Enqueue ``n`` well-formed records whose TTL is already hopeless
    (default 0.01ms): the worker must shed each with a typed
    ``expired``/``overloaded`` error before decode, never serve a
    stale answer."""
    rng = np.random.Generator(np.random.PCG64(int(seed)))
    uris: List[str] = []
    for i in range(n):
        uri = f"{uri_prefix}-{i:04d}"
        x = rng.uniform(0, 1, tuple(shape)).astype(np.float32)
        input_queue.enqueue(uri=uri, model=model, ttl_ms=float(ttl_ms),
                            x=x)
        uris.append(uri)
    return uris


def host_kill(proc, at_s: float = 0.0) -> threading.Thread:
    """SIGKILL a serving process ``at_s`` seconds from now.

    ``proc`` is a ``subprocess.Popen`` / ``multiprocessing.Process``
    (anything with a ``pid``) or a raw pid.  The kill is delivered on a
    daemon timer thread so the caller can start the storm first and let
    the host die mid-flight; join the returned thread to sequence
    assertions after the kill.  SIGKILL is deliberate — no atexit, no
    finally blocks, no graceful drain — because the recovery contract
    being tested is the *survivors'* barrier timeout, not the victim's
    shutdown path.  Already-dead victims are ignored (idempotent under
    races with natural exit).
    """
    pid = int(getattr(proc, "pid", proc))

    def _kill() -> None:
        if at_s > 0:
            time.sleep(at_s)
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    t = threading.Thread(target=_kill, name=f"host_kill_{pid}",
                         daemon=True)
    t.start()
    return t
