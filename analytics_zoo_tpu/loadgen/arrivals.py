"""Open-loop arrival schedules: Poisson processes under composable
rate shapes (docs/LOADGEN.md).

Closed-loop generators (send, wait, send again) suffer coordinated
omission: when the server stalls, the client stops *offering* load, so
queueing delay during the stall is never measured.  Everything here is
open-loop — the arrival schedule is drawn up front from a seeded
generator, and the client fires on that wall-clock schedule regardless
of how the server is doing.  Offered rate is a property of the
schedule, never of service time.

A *shape* is a pure ``rate(t)`` function (requests/s at offset ``t``
seconds into the run) plus its ``peak_rate()`` bound.  Schedules are
drawn by Lewis-Shedler thinning of a homogeneous Poisson process at
the peak rate, so any bounded shape — steady, diurnal ramp, flash
crowd — yields honest Poisson arrivals with the right local intensity.
Everything is deterministic from ``(shape, duration, seed)``: the same
call returns the identical schedule, byte for byte.
"""

from __future__ import annotations

import math
from typing import List, Sequence

import numpy as np

__all__ = ["Steady", "DiurnalRamp", "FlashCrowd", "ShapeSum",
           "arrival_times", "interarrivals"]


class Steady:
    """Constant offered rate: the sustained-QPS legs."""

    def __init__(self, qps: float):
        if qps <= 0:
            raise ValueError(f"qps must be positive, got {qps}")
        self.qps = float(qps)

    def rate(self, t: float) -> float:
        return self.qps if t >= 0 else 0.0

    def peak_rate(self) -> float:
        return self.qps

    def __repr__(self) -> str:
        return f"Steady(qps={self.qps})"


class DiurnalRamp:
    """A smooth low→high→low swing: one raised-cosine period over
    ``period_s``, floored at ``low_qps`` and peaking at ``high_qps`` —
    the compressed day/night cycle the autoscaler must track without
    flapping."""

    def __init__(self, low_qps: float, high_qps: float, period_s: float):
        if not (0 < low_qps <= high_qps):
            raise ValueError(
                f"need 0 < low_qps <= high_qps, got {low_qps}/{high_qps}")
        if period_s <= 0:
            raise ValueError(f"period_s must be positive, got {period_s}")
        self.low_qps = float(low_qps)
        self.high_qps = float(high_qps)
        self.period_s = float(period_s)

    def rate(self, t: float) -> float:
        if t < 0:
            return 0.0
        phase = 2.0 * math.pi * (t % self.period_s) / self.period_s
        frac = 0.5 * (1.0 - math.cos(phase))   # 0 at t=0, 1 at mid-period
        return self.low_qps + (self.high_qps - self.low_qps) * frac

    def peak_rate(self) -> float:
        return self.high_qps

    def __repr__(self) -> str:
        return (f"DiurnalRamp(low={self.low_qps}, high={self.high_qps}, "
                f"period_s={self.period_s})")


class FlashCrowd:
    """Steady base load with one rectangular burst: rate jumps to
    ``burst_qps`` during ``[at_s, at_s + dur_s)`` — the recovery-time
    legs measure how long after the burst ends p99 returns under SLO."""

    def __init__(self, base_qps: float, burst_qps: float,
                 at_s: float, dur_s: float):
        if base_qps <= 0 or burst_qps < base_qps:
            raise ValueError(
                f"need 0 < base_qps <= burst_qps, got {base_qps}/{burst_qps}")
        if at_s < 0 or dur_s <= 0:
            raise ValueError(f"bad burst window at={at_s} dur={dur_s}")
        self.base_qps = float(base_qps)
        self.burst_qps = float(burst_qps)
        self.at_s = float(at_s)
        self.dur_s = float(dur_s)

    def rate(self, t: float) -> float:
        if t < 0:
            return 0.0
        if self.at_s <= t < self.at_s + self.dur_s:
            return self.burst_qps
        return self.base_qps

    def peak_rate(self) -> float:
        return self.burst_qps

    def __repr__(self) -> str:
        return (f"FlashCrowd(base={self.base_qps}, burst={self.burst_qps}, "
                f"at_s={self.at_s}, dur_s={self.dur_s})")


class ShapeSum:
    """Superposition of shapes (Poisson processes are closed under
    superposition): e.g. a steady floor plus a flash crowd."""

    def __init__(self, shapes: Sequence):
        if not shapes:
            raise ValueError("ShapeSum needs at least one shape")
        self.shapes = list(shapes)

    def rate(self, t: float) -> float:
        return sum(s.rate(t) for s in self.shapes)

    def peak_rate(self) -> float:
        return sum(s.peak_rate() for s in self.shapes)

    def __repr__(self) -> str:
        return f"ShapeSum({self.shapes!r})"


def arrival_times(shape, duration_s: float, seed: int) -> np.ndarray:
    """Arrival offsets (seconds, ascending) for one run.

    Lewis-Shedler thinning: draw a homogeneous Poisson process at
    ``shape.peak_rate()`` and keep each candidate ``t`` with probability
    ``rate(t) / peak``.  Exact for any bounded intensity, and fully
    deterministic from ``seed`` (a fresh PCG64 stream per call — the
    schedule is reproducible across processes and sessions).
    """
    if duration_s <= 0:
        raise ValueError(f"duration_s must be positive, got {duration_s}")
    peak = float(shape.peak_rate())
    if peak <= 0:
        raise ValueError(f"shape peak rate must be positive, got {peak}")
    rng = np.random.Generator(np.random.PCG64(int(seed)))
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / peak)
        if t >= duration_s:
            break
        # thinning: one uniform per candidate, drawn unconditionally so
        # the stream position (and thus the schedule) is deterministic
        u = rng.random()
        if u * peak <= shape.rate(t):
            out.append(t)
    return np.asarray(out, dtype=np.float64)


def interarrivals(times: np.ndarray) -> np.ndarray:
    """Gaps between consecutive arrivals (the Poisson property tests
    check mean ~= 1/qps and coefficient of variation ~= 1)."""
    times = np.asarray(times, dtype=np.float64)
    if times.size < 2:
        return np.empty(0, dtype=np.float64)
    return np.diff(times)
