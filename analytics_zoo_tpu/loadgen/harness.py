"""Scenario orchestration: wire shapes + mixes + clients to a live
``ClusterServing`` and fold the result into SLO report sections.

Each ``run_*_leg`` function is self-contained — it builds its queue,
server, schedule and client(s), runs to completion, and returns the
JSON-ready section the artifact writer
(``python -m analytics_zoo_tpu.loadgen``) assembles into
``SLO_r18.json``.  The slow soak tests drive the same functions and
assert over the sections, so the pinned artifact and the CI proof are
the same code path.

The kill leg is the only one that crosses a process boundary: the
server runs as a real OS process (``loadgen/server_main.py``) over a
``FileQueue`` spool with a persistent compile cache, gets SIGKILLed
mid-storm, and is relaunched against the same cache — the client's
schedule never blinks (open loop), and the restarted server's status
file must show ZERO live compiles (warm start through the cache).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.loadgen import slo as slo_mod
from analytics_zoo_tpu.loadgen.adversarial import (SlowClient,
                                                   expired_ttl_flood,
                                                   malformed_flood)
from analytics_zoo_tpu.loadgen.arrivals import (FlashCrowd, Steady,
                                                arrival_times)
from analytics_zoo_tpu.loadgen.client import OpenLoopClient
from analytics_zoo_tpu.loadgen.payloads import PayloadClass, PayloadMix

__all__ = ["two_model_pair", "make_queue", "run_steady_leg",
           "run_burst_leg", "run_mix_shift_leg", "run_adversarial_leg",
           "run_open_loop_check", "run_kill_leg", "run_pod_kill_leg",
           "SERVER_IN_DIM", "SERVER_QUEUE_NAME", "POD_IN_DIM",
           "POD_VOCAB"]

# the deterministic cross-process server contract (server_main.py /
# client_main.py / the kill leg all agree on these)
SERVER_IN_DIM = 12
SERVER_QUEUE_NAME = "loadgen_stream"
# the pod-mode bag model's contract (server_main.build_bag_model)
POD_IN_DIM = 4
POD_VOCAB = 64


def two_model_pair(laggy_sleep_s: float = 0.03, dim: int = 4):
    """The soak's model cast: ``echo`` (can always meet a loose SLO)
    and ``laggy`` (a forward that can never meet a tight one) — same
    cast as ``tests/test_serving_chaos.py``'s autoscale soak."""
    from analytics_zoo_tpu.deploy import InferenceModel

    def fast_fwd(xs):
        return xs[0] * 2.0

    def laggy_fwd(xs):
        time.sleep(laggy_sleep_s)
        return xs[0] * 2.0

    echo = InferenceModel(fast_fwd, batch_buckets=(1, 8))
    laggy = InferenceModel(laggy_fwd, batch_buckets=(1, 8))
    return {"echo": echo, "laggy": laggy}


def make_queue(backend: str = "memory", **kw):
    """Queue for an in-process leg: ``memory``, or ``shm`` when POSIX
    shared memory is usable (silently falls back to memory otherwise —
    the leg's section records which wire actually ran)."""
    if backend == "shm":
        from analytics_zoo_tpu.deploy.shmqueue import ShmQueue, shm_available
        if shm_available():
            kw.setdefault("slots", 256)
            kw.setdefault("slot_bytes", 1 << 16)
            kw.setdefault("push_timeout_s", 5.0)
            return ShmQueue(name=kw.pop("name", "loadgen"), **kw), "shm"
    from analytics_zoo_tpu.deploy.serving import MemoryQueue
    return MemoryQueue(), "memory"


def _lat_stats(records) -> Dict[str, Optional[float]]:
    oks = [r.latency_s * 1e3 for r in records
           if r.outcome == "ok" and r.latency_s is not None]
    lags = [r.lag_s * 1e3 for r in records if r.lag_s is not None]
    return {"latency_p50_ms": slo_mod.percentile(oks, 50),
            "latency_p99_ms": slo_mod.percentile(oks, 99),
            "send_lag_p99_ms": slo_mod.percentile(lags, 99)}


def _section(records, windows, slo_ms_by_model) -> Dict[str, Any]:
    outcomes = slo_mod.outcome_counts(records)
    sec: Dict[str, Any] = {
        "offered": len(records),
        "answered_ok": outcomes.get("ok", 0),
        "outcomes": outcomes,
        "shed_fraction": slo_mod.shed_fraction_by_model(records),
        "sustained_qps_at_slo": slo_mod.sustained_qps_at_slo(
            windows, slo_ms_by_model),
    }
    sec.update(_lat_stats(records))
    return sec


def run_steady_leg(qps: float = 80.0, duration_s: float = 8.0,
                   seed: int = 11, slo_ms: float = 250.0,
                   backend: str = "shm",
                   window_s: float = 1.0) -> Dict[str, Any]:
    """Sustained Poisson load on one model through the full pipeline:
    the sustained-QPS-at-SLO headline row."""
    from analytics_zoo_tpu.deploy import (ClusterServing, InputQueue,
                                          OutputQueue, ServingConfig)

    models = two_model_pair(laggy_sleep_s=0.0)
    model = {"echo": models["echo"]}
    q, wire = make_queue(backend, name="loadgen_steady")
    cfg = ServingConfig(batch_size=8, poll_timeout_s=0.02,
                        max_batch_delay_ms=3, decode_workers=2,
                        slo_p99_ms={"echo": slo_ms})
    srv = ClusterServing(model, q, cfg).start()
    try:
        schedule = arrival_times(Steady(qps), duration_s, seed)
        mix = PayloadMix([PayloadClass("echo", shape=(4,),
                                      dtype="float32")])
        client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule,
                                mix, leg="steady", seed=seed)
        records = client.run(drain_timeout_s=30.0)
    finally:
        srv.stop()
        if hasattr(q, "stop"):
            q.stop()
    windows = slo_mod.fold_windows(records, window_s, duration_s)
    sec = _section(records, windows, {"echo": slo_ms})
    sec.update({"qps_target": qps, "duration_s": duration_s,
                "slo_p99_ms": slo_ms, "wire": wire,
                "open_loop_drops": client.open_loop_drops})
    return sec


def run_burst_leg(base_qps: float = 40.0, burst_qps: float = 400.0,
                  at_s: float = 3.0, dur_s: float = 2.0,
                  duration_s: float = 14.0, seed: int = 13,
                  slo_ms: float = 400.0, backend: str = "shm",
                  window_s: float = 1.0) -> Dict[str, Any]:
    """Flash crowd: a 10x rectangular burst over a steady floor.  The
    pinned row is recovery-time-to-SLO measured from the burst END."""
    from analytics_zoo_tpu.deploy import (ClusterServing, InputQueue,
                                          OutputQueue, ServingConfig)

    models = {"echo": two_model_pair(laggy_sleep_s=0.002)["laggy"]}
    models["echo"].name = "echo"
    q, wire = make_queue(backend, name="loadgen_burst")
    cfg = ServingConfig(batch_size=8, poll_timeout_s=0.02,
                        max_batch_delay_ms=3, decode_workers=2,
                        slo_p99_ms={"echo": slo_ms})
    srv = ClusterServing(models, q, cfg).start()
    try:
        shape = FlashCrowd(base_qps, burst_qps, at_s, dur_s)
        schedule = arrival_times(shape, duration_s, seed)
        mix = PayloadMix([PayloadClass("echo", shape=(4,),
                                      dtype="float32")])
        client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule,
                                mix, leg="burst", seed=seed)
        records = client.run(drain_timeout_s=60.0)
    finally:
        srv.stop()
        if hasattr(q, "stop"):
            q.stop()
    windows = slo_mod.fold_windows(records, window_s, duration_s)
    burst_end = at_s + dur_s
    sec = _section(records, windows, {"echo": slo_ms})
    sec.update({
        "base_qps": base_qps, "burst_qps": burst_qps,
        "burst_at_s": at_s, "burst_dur_s": dur_s, "wire": wire,
        "slo_p99_ms": slo_ms,
        "recovery_after_burst_s": slo_mod.recovery_time_to_slo(
            windows, burst_end, {"echo": slo_ms}),
    })
    return sec


def run_mix_shift_leg(duration_s: float = 16.0, qps: float = 60.0,
                      shift_at_s: float = 6.0, seed: int = 17,
                      laggy_sleep_s: float = 0.03,
                      backend: str = "shm",
                      window_s: float = 1.0) -> Dict[str, Any]:
    """The two-model shifting mix under the live autoscaler: balanced
    load, then 85% of traffic shifts onto the model that cannot meet
    its SLO.  Pins selective shed (only the over-SLO model loses
    traffic) and autoscale convergence (actions, zero flaps)."""
    from analytics_zoo_tpu.deploy import (AutoscalePolicy, ClusterServing,
                                          InputQueue, OutputQueue,
                                          ServingConfig)

    models = two_model_pair(laggy_sleep_s=laggy_sleep_s)
    q, wire = make_queue(backend, name="loadgen_mix")
    cfg = ServingConfig(
        batch_size=8, poll_timeout_s=0.02, max_batch_delay_ms=3,
        decode_workers=2, replicas=2, supervisor_interval_s=0.05,
        slo_p99_ms={"echo": 10_000.0, "laggy": 15.0},
        hbm_budget_bytes=1 << 30,
        autoscale=True, autoscale_interval_s=0.05,
        autoscale_cooldown_s=0.25,
        autoscale_policy=AutoscalePolicy(
            hysteresis=2, cooldown_s=0.25, queue_high=8,
            max_decode_workers=4, max_replicas=4,
            min_batch_delay_ms=1.0, max_batch_delay_ms=20.0))
    srv = ClusterServing(models, q, cfg).start()
    try:
        schedule = arrival_times(Steady(qps), duration_s, seed)
        mix = PayloadMix(
            [PayloadClass("echo", shape=(4,), dtype="float32",
                          weight=0.5),
             PayloadClass("laggy", shape=(4,), dtype="float32",
                          weight=0.5)],
            shift_at_s=shift_at_s, shift_weights=[0.15, 0.85])
        client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule,
                                mix, leg="mix_shift", seed=seed)
        records = client.run(drain_timeout_s=90.0)
        audit = srv.autoscale_audit() or {}
        actions = srv.autoscale_actions()
        health = srv.health()
    finally:
        srv.stop()
        if hasattr(q, "stop"):
            q.stop()
    windows = slo_mod.fold_windows(records, window_s, duration_s)
    shed = slo_mod.shed_fraction_by_model(records)
    outcomes = slo_mod.outcome_counts(records)
    lost = outcomes.get("lost", 0) + outcomes.get("dropped", 0)
    sec = _section(records, windows, {"echo": 10_000.0})
    sec.update({
        "wire": wire, "qps_target": qps, "shift_at_s": shift_at_s,
        "lost": lost,
        "shed_fraction_echo": shed.get("echo", 0.0),
        "shed_fraction_laggy": shed.get("laggy", 0.0),
        # 1.0 iff every shed record belonged to the over-SLO model
        "only_over_slo_shed": float(shed.get("echo", 0.0) == 0.0
                                    and shed.get("laggy", 0.0) > 0.0),
        "autoscale_actions": len(actions),
        "autoscale_flaps": audit.get("flaps"),
        "autoscale_by_label": audit.get("by_label"),
        "observed_p99_laggy_ms":
            health["models"]["laggy"]["observed_p99_ms"],
    })
    return sec


def run_adversarial_leg(backend: str = "shm") -> Dict[str, Any]:
    """Malformed flood + expired-TTL flood + a slow client holding its
    results while a well-behaved neighbour keeps its latency."""
    from analytics_zoo_tpu.deploy import (ClusterServing, InputQueue,
                                          OutputQueue, ServingConfig)

    models = {"echo": two_model_pair(laggy_sleep_s=0.0)["echo"]}
    q, wire = make_queue(backend, name="loadgen_adv")
    cfg = ServingConfig(batch_size=8, poll_timeout_s=0.02,
                        max_batch_delay_ms=3, decode_workers=2)
    srv = ClusterServing(models, q, cfg).start()
    try:
        inp, outp = InputQueue(q), OutputQueue(q)
        # 1. malformed records pushed past client-side validation
        mal_rids = malformed_flood(q, n=12)
        mal_answers = {r: outp.query(r, timeout=30.0) for r in mal_rids}
        mal_typed = sum(
            1 for v in mal_answers.values()
            if isinstance(v, dict) and "error" in v
            and v.get("code") in ("malformed", "decode_error"))
        # 2. expired-TTL flood: shed typed, never served stale
        ttl_uris = expired_ttl_flood(inp, model="echo", n=12,
                                     ttl_ms=0.01)
        ttl_answers = {u: outp.query(u, timeout=30.0) for u in ttl_uris}
        ttl_shed = sum(
            1 for v in ttl_answers.values()
            if isinstance(v, dict)
            and v.get("code") in ("expired", "overloaded"))
        # 3. slow client holds result leases while a neighbour runs
        slow = SlowClient(inp, outp, model="echo", n=8, hold_s=1.0,
                          uri_prefix="slow")
        slow.send()
        lats = []
        rng = np.random.Generator(np.random.PCG64(3))
        for i in range(16):
            x = rng.uniform(0, 1, (4,)).astype(np.float32)
            t0 = time.monotonic()
            inp.enqueue(uri=f"fast-{i:04d}", model="echo", x=x)
            outp.query(f"fast-{i:04d}", timeout=30.0)
            lats.append((time.monotonic() - t0) * 1e3)
        held = slow.collect(timeout_s=30.0)
        slow_ok = sum(1 for v in held.values()
                      if not (isinstance(v, dict) and "error" in v))
    finally:
        srv.stop()
        if hasattr(q, "stop"):
            q.stop()
    return {
        "wire": wire,
        "malformed_offered": len(mal_rids),
        "malformed_typed": mal_typed,
        "malformed_all_typed": float(mal_typed == len(mal_rids)),
        "expired_offered": len(ttl_uris),
        "expired_shed": ttl_shed,
        "expired_all_shed": float(ttl_shed == len(ttl_uris)),
        "slow_client_held": len(held),
        "slow_client_ok": slow_ok,
        "neighbour_p99_ms_while_held": slo_mod.percentile(lats, 99),
    }


def run_open_loop_check(qps: float = 50.0, duration_s: float = 2.0,
                        stall_s: float = 0.5,
                        seed: int = 23) -> Dict[str, Any]:
    """The open-loop property, pinned: a deliberately-stalled executor
    (every forward sleeps ``stall_s`` >> the mean inter-arrival gap)
    must not slow the offered schedule.  Every scheduled send fires,
    and send lag stays bounded by client-side cost alone."""
    from analytics_zoo_tpu.deploy import (ClusterServing, InferenceModel,
                                          InputQueue, MemoryQueue,
                                          OutputQueue, ServingConfig)

    def stalled_fwd(xs):
        time.sleep(stall_s)
        return xs[0] * 2.0

    m = InferenceModel(stalled_fwd, batch_buckets=(1, 8))
    q = MemoryQueue()
    srv = ClusterServing({"stall": m}, q, ServingConfig(
        batch_size=8, poll_timeout_s=0.02, max_batch_delay_ms=3,
        decode_workers=2)).start()
    try:
        schedule = arrival_times(Steady(qps), duration_s, seed)
        mix = PayloadMix([PayloadClass("stall", shape=(4,),
                                      dtype="float32")])
        client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule,
                                mix, leg="open_loop", seed=seed)
        records = client.finish(drain_timeout_s=90.0) \
            if client.start() else []
    finally:
        srv.stop()
    lags = [r.lag_s for r in records if r.lag_s is not None]
    sent = sum(1 for r in records if r.t_sent is not None)
    lag_p99 = slo_mod.percentile([v * 1e3 for v in lags], 99)
    mean_gap_ms = 1e3 / qps
    # offered rate independent of service time: every slot fired, and
    # p99 send lag stayed under the mean inter-arrival gap even though
    # service time was stall_s >> the gap
    independent = float(sent == len(schedule)
                        and lag_p99 is not None
                        and lag_p99 < mean_gap_ms)
    return {
        "scheduled": len(schedule), "sent": sent,
        "stall_s": stall_s, "qps_target": qps,
        "send_lag_p99_ms": lag_p99,
        "mean_interarrival_ms": mean_gap_ms,
        "service_p99_ms": _lat_stats(records)["latency_p99_ms"],
        "offered_rate_independent": independent,
    }


# -- the kill leg: a real server process dies mid-storm ---------------------

def _loadgen_env() -> Dict[str, str]:
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def start_server_process(spool: str, cache_dir: str, status_file: str,
                         log_path: str, slo_ms: float = 1000.0,
                         extra_args: Optional[List[str]] = None
                         ) -> subprocess.Popen:
    """One ``server_main`` OS process over the FileQueue spool.  The
    caller owns the Popen (the kill leg SIGKILLs it mid-storm)."""
    argv = [sys.executable, "-m",
            "analytics_zoo_tpu.loadgen.server_main",
            "--queue-root", spool, "--cache-dir", cache_dir,
            "--status-file", status_file,
            "--slo-p99-ms", str(slo_ms)]
    argv += list(extra_args or [])
    logf = open(log_path, "w")
    try:
        return subprocess.Popen(argv, env=_loadgen_env(), stdout=logf,
                                stderr=subprocess.STDOUT)
    finally:
        logf.close()     # the child holds its own fd


def wait_for_status(status_file: str, timeout_s: float = 120.0,
                    require: Optional[str] = None) -> Dict[str, Any]:
    """Block until the server's status JSON exists (and, optionally,
    carries ``require`` as a truthy key) — the 'server is up' barrier."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(status_file):
            try:
                with open(status_file) as f:
                    st = json.load(f)
            except (OSError, ValueError):
                st = None
            if st is not None and (require is None or st.get(require)):
                return st
        time.sleep(0.1)
    raise TimeoutError(f"server status never appeared at {status_file}")


def run_kill_leg(workdir: str, qps: float = 50.0, duration_s: float = 22.0,
                 kill_at_s: float = 7.0, restart_delay_s: float = 0.5,
                 slo_ms: float = 2000.0, seed: int = 29,
                 window_s: float = 1.0) -> Dict[str, Any]:
    """SIGKILL the serving process mid-storm; relaunch it against the
    same compile-cache dir; prove the client returns to SLO and the
    restarted server did ZERO live compiles (pure warm start).

    The FileQueue spool survives the kill, so queued-but-unclaimed
    records are served by the successor; records in flight inside the
    killed process are bounded by the pipeline depth and terminate as
    ``lost`` at the client (counted, not hidden).
    """
    from analytics_zoo_tpu.deploy.serving import (FileQueue, InputQueue,
                                                  OutputQueue)

    os.makedirs(workdir, exist_ok=True)
    spool = os.path.join(workdir, "spool")
    cache = os.path.join(workdir, "cache")
    os.makedirs(spool, exist_ok=True)
    os.makedirs(cache, exist_ok=True)
    status1 = os.path.join(workdir, "server1.status.json")
    status2 = os.path.join(workdir, "server2.status.json")

    proc = start_server_process(
        spool, cache, status1, os.path.join(workdir, "server1.log"),
        slo_ms=slo_ms)
    st1 = wait_for_status(status1, require="ready")
    q = FileQueue(spool, name=SERVER_QUEUE_NAME)
    schedule = arrival_times(Steady(qps), duration_s, seed)
    mix = PayloadMix([PayloadClass("default", shape=(SERVER_IN_DIM,),
                                   dtype="float32")])
    client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule, mix,
                            leg="kill", seed=seed,
                            query_timeout_s=5.0).start()
    t0 = time.monotonic()
    time.sleep(kill_at_s)
    proc.kill()                                   # SIGKILL, mid-storm
    proc.wait(timeout=30)
    kill_t = time.monotonic() - t0
    time.sleep(restart_delay_s)
    proc2 = start_server_process(
        spool, cache, status2, os.path.join(workdir, "server2.log"),
        slo_ms=slo_ms)
    try:
        records = client.finish(drain_timeout_s=60.0)
        st2 = wait_for_status(status2, require="ready")
    finally:
        proc2.send_signal(signal.SIGTERM)
        try:
            rc2 = proc2.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc2.kill()
            rc2 = proc2.wait(timeout=10)
    # final status the server dumped on SIGTERM (post-traffic truth)
    try:
        with open(status2) as f:
            st2 = json.load(f)
    except (OSError, ValueError):
        pass
    windows = slo_mod.fold_windows(records, window_s, duration_s)
    outcomes = slo_mod.outcome_counts(records)
    sec: Dict[str, Any] = {
        "qps_target": qps, "duration_s": duration_s,
        "kill_at_s": round(kill_t, 3), "slo_p99_ms": slo_ms,
        "offered": len(records),
        "answered_ok": outcomes.get("ok", 0),
        "lost": outcomes.get("lost", 0) + outcomes.get("dropped", 0),
        "outcomes": outcomes,
        "recovery_after_kill_s": slo_mod.recovery_time_to_slo(
            windows, kill_t, {"default": slo_ms}),
        "cold_compile_count": st1.get("compile_count"),
        "warm_compile_count": st2.get("compile_count"),
        "warm_cache_hits": ((st2.get("cache") or {}).get("events")
                            or {}).get("hit"),
        "warm_count": st2.get("warm_count"),
        "server2_exit_rc": rc2,
    }
    sec.update(_lat_stats(records))
    return sec


# -- the pod kill leg: a pod MEMBER HOST dies mid-storm ---------------------

def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _read_status(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def start_pod(workdir: str, spool: str, cache: str, tag: str,
              port: int, slo_ms: float, barrier_timeout_s: float,
              pod_name: str) -> Tuple[subprocess.Popen, subprocess.Popen,
                                      str, str]:
    """Launch one 2-process pod (lead + member host) of ``server_main``
    over the shared FileQueue spool.  Returns (lead, follower,
    lead_status_path, follower_status_path)."""
    procs, statuses = [], []
    for pid in (0, 1):
        status = os.path.join(workdir, f"{tag}_{pid}.status.json")
        procs.append(start_server_process(
            spool, cache, status,
            os.path.join(workdir, f"{tag}_{pid}.log"), slo_ms=slo_ms,
            extra_args=["--model", "bag", "--pod-processes", "2",
                        "--pod-id", str(pid), "--pod-port", str(port),
                        "--pod-name", pod_name, "--local-devices", "2",
                        "--barrier-timeout", str(barrier_timeout_s),
                        "--mesh-replicas", "1"]))
        statuses.append(status)
    return procs[0], procs[1], statuses[0], statuses[1]


def run_pod_kill_leg(workdir: str, qps: float = 40.0,
                     duration_s: float = 16.0, kill_at_s: float = 6.0,
                     tail_duration_s: float = 8.0,
                     barrier_timeout_s: float = 2.0,
                     slo_ms: float = 4000.0, seed: int = 31,
                     window_s: float = 1.0) -> Dict[str, Any]:
    """SIGKILL a pod MEMBER HOST mid-storm (``adversarial.host_kill``);
    prove the surviving lead quarantines the whole mesh replica within
    the barrier deadline and keeps serving degraded with ZERO lost
    requests, then that a successor pod against the same compile cache
    reaches SLO on a tail storm with ZERO live compiles.

    Two storms, overlapping pods: storm 1 runs on pod A; its member is
    SIGKILLed at ``kill_at_s`` and the lead serves the rest on its
    single-chip slot.  Pod B launches as soon as the quarantine is
    observed — warm-starting while A still serves, so the spool never
    loses its claimer — and storm 2 (the tail) runs after A retires
    idle (SIGTERM, exit 0 — its final status is the quarantine proof).
    The FileQueue hands each record to exactly one claimer, so the
    overlap is race-free.
    """
    from analytics_zoo_tpu.deploy.serving import (FileQueue, InputQueue,
                                                  OutputQueue)
    from analytics_zoo_tpu.loadgen.adversarial import host_kill

    os.makedirs(workdir, exist_ok=True)
    spool = os.path.join(workdir, "spool")
    cache = os.path.join(workdir, "cache")
    os.makedirs(spool, exist_ok=True)
    os.makedirs(cache, exist_ok=True)

    lead_a, fol_a, st_a0, _ = start_pod(
        workdir, spool, cache, "podA", _free_port(), slo_ms,
        barrier_timeout_s, "podA")
    sta = wait_for_status(st_a0, require="ready")
    q = FileQueue(spool, name=SERVER_QUEUE_NAME)
    mix = PayloadMix([PayloadClass("default", shape=(POD_IN_DIM,),
                                   dtype="int32", field="ids",
                                   low=0, high=POD_VOCAB)])
    schedule = arrival_times(Steady(qps), duration_s, seed)
    client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule, mix,
                            leg="pod_kill", seed=seed,
                            query_timeout_s=5.0).start()
    t0 = time.monotonic()
    killer = host_kill(fol_a, at_s=kill_at_s)
    killer.join(timeout=kill_at_s + 30)
    t_kill = time.monotonic() - t0

    # the lead's next mesh dispatch must time its deadline barrier out
    # and quarantine the whole mesh replica — watch the status file
    detect_deadline = time.monotonic() + barrier_timeout_s + 8.0
    quarantine_detect_s = None
    while time.monotonic() < detect_deadline:
        mesh_h = _read_status(st_a0).get("mesh") or {}
        if (mesh_h.get("quarantine_epoch") or 0) >= 1:
            quarantine_detect_s = time.monotonic() - t0 - t_kill
            break
        time.sleep(0.1)

    # successor pod on a FRESH coordination port, same spool + cache:
    # it must warm-start the full executable set (mesh flavor included)
    # while pod A still serves the storm
    lead_b, fol_b, st_b0, _ = start_pod(
        workdir, spool, cache, "podB", _free_port(), slo_ms,
        barrier_timeout_s, "podB")
    rc_a = rc_b = None
    records2: List[Any] = []
    try:
        records = client.finish(drain_timeout_s=90.0)
        wait_for_status(st_b0, require="ready")
        # pod A retires idle; B owns the spool from here — no gap
        lead_a.send_signal(signal.SIGTERM)
        rc_a = lead_a.wait(timeout=30)
        schedule2 = arrival_times(Steady(qps), tail_duration_s, seed + 1)
        client2 = OpenLoopClient(InputQueue(q), OutputQueue(q),
                                 schedule2, mix, leg="pod_kill_tail",
                                 seed=seed + 1, query_timeout_s=5.0)
        client2.start()
        records2 = client2.finish(drain_timeout_s=60.0)
    finally:
        if rc_a is None:
            lead_a.kill()
            rc_a = lead_a.wait(timeout=10)
        lead_b.send_signal(signal.SIGTERM)
        try:
            rc_b = lead_b.wait(timeout=30)
        except subprocess.TimeoutExpired:
            lead_b.kill()
            rc_b = lead_b.wait(timeout=10)
    fol_a.wait(timeout=10)
    try:
        # exits on its own once lead B's coordination service is gone
        rc_fol_b = fol_b.wait(timeout=30)
    except subprocess.TimeoutExpired:
        fol_b.kill()
        rc_fol_b = fol_b.wait(timeout=10)

    fin_a = _read_status(st_a0)          # post-SIGTERM quarantine proof
    fin_b = _read_status(st_b0)
    mesh_a = fin_a.get("mesh") or {}
    windows = slo_mod.fold_windows(records, window_s, duration_s)
    windows2 = slo_mod.fold_windows(records2, window_s, tail_duration_s)
    outcomes = slo_mod.outcome_counts(records)
    outcomes2 = slo_mod.outcome_counts(records2)
    lost = (outcomes.get("lost", 0) + outcomes.get("dropped", 0)
            + outcomes2.get("lost", 0) + outcomes2.get("dropped", 0))
    within = (quarantine_detect_s is not None
              and quarantine_detect_s <= barrier_timeout_s + 8.0)
    sec: Dict[str, Any] = {
        "qps_target": qps, "duration_s": duration_s,
        "tail_duration_s": tail_duration_s,
        "kill_at_s": round(t_kill, 3),
        "barrier_timeout_s": barrier_timeout_s, "slo_p99_ms": slo_ms,
        "offered": len(records) + len(records2),
        "answered_ok": (outcomes.get("ok", 0) + outcomes2.get("ok", 0)),
        "lost": lost,
        "outcomes": outcomes,
        "tail_outcomes": outcomes2,
        "quarantine_detect_s": (None if quarantine_detect_s is None
                                else round(quarantine_detect_s, 3)),
        "quarantine_within_deadline": float(within),
        "quarantine_epoch": mesh_a.get("quarantine_epoch"),
        "roster_lost": (mesh_a.get("roster") or {}).get("lost"),
        "recovery_after_kill_s": slo_mod.recovery_time_to_slo(
            windows, t_kill, {"default": slo_ms}),
        "tail_sustained_qps_at_slo": slo_mod.sustained_qps_at_slo(
            windows2, {"default": slo_ms}),
        "cold_compile_count": sta.get("compile_count"),
        "warm_compile_count": fin_b.get("compile_count"),
        "warm_cache_hits": ((fin_b.get("cache") or {}).get("events")
                            or {}).get("hit"),
        "leadA_exit_rc": rc_a,
        "leadB_exit_rc": rc_b,
        "follower_exit_rc": fol_a.returncode,     # -9: SIGKILLed
        "followerB_exit_rc": rc_fol_b,
    }
    sec.update(_lat_stats(list(records) + list(records2)))
    return sec


def default_report(workdir: str, quick: bool = False) -> Dict[str, Any]:
    """The full artifact: every leg, assembled.  ``quick`` shrinks
    durations for smoke runs (NOT for the pinned artifact)."""
    import platform

    scale = 0.5 if quick else 1.0
    report: Dict[str, Any] = {
        "schema": "slo-artifact-v1",
        "run_metadata": {
            "python": platform.python_version(),
            "platform": platform.platform(),
            "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
            "quick": bool(quick),
        },
    }
    report["steady"] = run_steady_leg(duration_s=8.0 * scale)
    report["burst"] = run_burst_leg(duration_s=14.0 * scale,
                                    at_s=3.0 * scale, dur_s=2.0 * scale)
    report["mix_shift"] = run_mix_shift_leg(duration_s=16.0 * scale,
                                            shift_at_s=6.0 * scale)
    report["adversarial"] = run_adversarial_leg()
    report["open_loop"] = run_open_loop_check()
    report["kill"] = run_kill_leg(os.path.join(workdir, "kill"),
                                  duration_s=22.0 * scale,
                                  kill_at_s=7.0 * scale)
    report["pod_kill"] = run_pod_kill_leg(
        os.path.join(workdir, "pod_kill"), duration_s=16.0 * scale,
        kill_at_s=6.0 * scale, tail_duration_s=8.0 * scale)
    return report
