"""Fleet-scale load harness: production-shaped open-loop traffic with a
pinned SLO proof (docs/LOADGEN.md).

- ``arrivals``    — Poisson schedules over composable traffic shapes
  (steady / diurnal ramp / flash crowd), thinning-sampled so the
  offered schedule is deterministic in ``(shape, duration, seed)``.
- ``payloads``    — payload distributions over model/shape/dtype and
  the two-model shifting mix.
- ``client``      — the open-loop client: sends on the schedule no
  matter how slow the server is (no coordinated omission), latency is
  measured schedule-to-answer.
- ``adversarial`` — slow clients holding result leases, malformed
  floods, expired-TTL floods.
- ``slo``         — fold per-request records into windows; sustained
  QPS at SLO, shed fraction by model, recovery-time-to-SLO; the
  ``SLO_*.json`` artifact writer.
- ``harness``     — scenario legs wiring all of the above to a live
  ``ClusterServing`` (including the SIGKILL-mid-storm warm-restart
  leg over real OS processes).
"""

from analytics_zoo_tpu.loadgen.arrivals import (  # noqa: F401
    DiurnalRamp, FlashCrowd, ShapeSum, Steady, arrival_times,
    interarrivals)
from analytics_zoo_tpu.loadgen.client import (  # noqa: F401
    OpenLoopClient, RequestRecord)
from analytics_zoo_tpu.loadgen.payloads import (  # noqa: F401
    PayloadClass, PayloadMix, saturated_images)
from analytics_zoo_tpu.loadgen.slo import (  # noqa: F401
    fold_windows, percentile, recovery_time_to_slo,
    shed_fraction_by_model, sustained_qps_at_slo, write_artifact)
