"""Standalone open-loop client process.

One OS process = one open-loop client: it builds its schedule from
``(shape, seed)``, attaches to the shared ``FileQueue`` spool a
``server_main`` process is serving, fires the schedule, drains, and
writes a JSON summary to ``--outfile`` for the harness to fold.  The
soak test launches several of these concurrently through
``tests/mp_harness.run_processes`` so the offered load really crosses
process boundaries — no shared GIL, no shared clock, no shared rng.

Usage::

    python -m analytics_zoo_tpu.loadgen.client_main \
        --queue-root /tmp/spool --outfile /tmp/c0.json \
        --shape steady --qps 40 --duration-s 8 --seed 3
"""

from __future__ import annotations

import argparse
import json
import sys


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--queue-root", required=True)
    p.add_argument("--queue-name", default="loadgen_stream")
    p.add_argument("--outfile", required=True)
    p.add_argument("--leg", default="steady")
    p.add_argument("--shape", default="steady",
                   choices=("steady", "ramp", "burst"))
    p.add_argument("--qps", type=float, default=20.0)
    p.add_argument("--high-qps", type=float, default=None,
                   help="ramp peak / burst rate (defaults to 5x --qps)")
    p.add_argument("--burst-at-s", type=float, default=3.0)
    p.add_argument("--burst-dur-s", type=float, default=2.0)
    p.add_argument("--duration-s", type=float, default=8.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model", default="default")
    p.add_argument("--in-dim", type=int, default=12)
    p.add_argument("--ttl-ms", type=float, default=None)
    p.add_argument("--uri-prefix", default=None)
    p.add_argument("--drain-timeout-s", type=float, default=60.0)
    p.add_argument("--window-s", type=float, default=1.0)
    return p.parse_args(argv)


def build_shape(args):
    from analytics_zoo_tpu.loadgen.arrivals import (DiurnalRamp,
                                                    FlashCrowd, Steady)
    high = args.high_qps if args.high_qps is not None else 5.0 * args.qps
    if args.shape == "ramp":
        return DiurnalRamp(args.qps, high, period_s=args.duration_s)
    if args.shape == "burst":
        return FlashCrowd(args.qps, high, args.burst_at_s,
                          args.burst_dur_s)
    return Steady(args.qps)


def main(argv=None) -> int:
    args = parse_args(argv)
    from analytics_zoo_tpu.deploy.serving import (FileQueue, InputQueue,
                                                  OutputQueue)
    from analytics_zoo_tpu.loadgen import slo as slo_mod
    from analytics_zoo_tpu.loadgen.arrivals import arrival_times
    from analytics_zoo_tpu.loadgen.client import OpenLoopClient
    from analytics_zoo_tpu.loadgen.payloads import PayloadClass, PayloadMix

    q = FileQueue(args.queue_root, name=args.queue_name)
    schedule = arrival_times(build_shape(args), args.duration_s,
                             args.seed)
    mix = PayloadMix([PayloadClass(args.model, shape=(args.in_dim,),
                                   dtype="float32", ttl_ms=args.ttl_ms)])
    client = OpenLoopClient(InputQueue(q), OutputQueue(q), schedule, mix,
                            leg=args.leg, seed=args.seed,
                            uri_prefix=args.uri_prefix,
                            query_timeout_s=5.0)
    records = client.run(drain_timeout_s=args.drain_timeout_s)

    outcomes = slo_mod.outcome_counts(records)
    oks = [r.latency_s * 1e3 for r in records
           if r.outcome == "ok" and r.latency_s is not None]
    lags = [r.lag_s * 1e3 for r in records if r.lag_s is not None]
    windows = slo_mod.fold_windows(records, args.window_s,
                                   args.duration_s)
    summary = {
        "leg": args.leg, "shape": args.shape, "seed": args.seed,
        "qps_target": args.qps, "duration_s": args.duration_s,
        "scheduled": len(schedule),
        "offered": len(records),
        "sent": sum(1 for r in records if r.t_sent is not None),
        "answered_ok": outcomes.get("ok", 0),
        "outcomes": outcomes,
        "open_loop_drops": client.open_loop_drops,
        "latency_p50_ms": slo_mod.percentile(oks, 50),
        "latency_p99_ms": slo_mod.percentile(oks, 99),
        "send_lag_p99_ms": slo_mod.percentile(lags, 99),
        "windows": windows,
    }
    with open(args.outfile, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
