"""The open-loop client: fire on the schedule, collect on the side.

Two threads per client:

- **sender** walks the pre-drawn arrival schedule on a monotonic
  clock.  It never waits for a result — if the server stalls, sends
  keep their schedule (the open-loop property the stalled-executor
  test pins) and only ``schedule lag`` (how far behind its slot each
  send actually fired) grows with *client-side* cost, not service
  time.  A transport that refuses a push (full shm ring, closed queue)
  counts an ``open_loop_drop`` and the schedule moves on — offered
  load is never modulated by the server.
- **collector** polls the result store for this client's uri prefix
  and timestamps each terminal answer as it lands (result or typed
  error payload), so per-request latency is measured at arrival of the
  answer, not at whenever a sequential reader got around to it.

Every request terminates in exactly one of: a result (``ok``), a typed
error code (``overloaded`` / ``expired`` / ``malformed`` / ...), or
``lost`` if the drain deadline passes with no answer (e.g. in-flight
work killed with a server process).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.observe import metrics as obs

from analytics_zoo_tpu.loadgen.payloads import PayloadMix

__all__ = ["RequestRecord", "OpenLoopClient"]


class RequestRecord:
    """One request's timeline, in run-relative seconds."""

    __slots__ = ("uri", "model", "t_sched", "t_sent", "t_done", "outcome")

    def __init__(self, uri: str, model: str, t_sched: float,
                 t_sent: Optional[float] = None,
                 t_done: Optional[float] = None,
                 outcome: str = "pending"):
        self.uri = uri
        self.model = model
        self.t_sched = t_sched
        self.t_sent = t_sent
        self.t_done = t_done
        self.outcome = outcome

    @property
    def latency_s(self) -> Optional[float]:
        """Schedule-to-answer: includes any lag the client itself added
        (coordinated-omission-free, per Gil Tene's correction)."""
        if self.t_done is None:
            return None
        return self.t_done - self.t_sched

    @property
    def lag_s(self) -> Optional[float]:
        if self.t_sent is None:
            return None
        return self.t_sent - self.t_sched

    def as_dict(self) -> Dict[str, Any]:
        return {"uri": self.uri, "model": self.model,
                "t_sched": self.t_sched, "t_sent": self.t_sent,
                "t_done": self.t_done, "outcome": self.outcome}


def _outcome_of(val: Any) -> str:
    if isinstance(val, dict) and "error" in val:
        return str(val.get("code") or "internal")
    return "ok"


class OpenLoopClient:
    """Drive one ``InputQueue``/``OutputQueue`` pair on a schedule.

    ``schedule`` is the arrival-offset array from
    :func:`~analytics_zoo_tpu.loadgen.arrivals.arrival_times`;
    ``mix`` supplies each arrival's (model, payload, ttl).  ``uri_prefix``
    namespaces this client's records so N clients can share one result
    store without stealing each other's answers.
    """

    def __init__(self, input_queue, output_queue, schedule, mix: PayloadMix,
                 *, leg: str = "steady", seed: int = 0,
                 uri_prefix: Optional[str] = None,
                 query_timeout_s: float = 2.0):
        self.inp = input_queue
        self.outp = output_queue
        self.schedule = np.asarray(schedule, dtype=np.float64)
        self.mix = mix
        self.leg = str(leg)
        self.uri_prefix = uri_prefix if uri_prefix is not None else leg
        self._rng = np.random.Generator(np.random.PCG64(int(seed)))
        self._query_timeout_s = float(query_timeout_s)
        self._lock = threading.Lock()
        self._records: Dict[str, RequestRecord] = {}
        self._drops = 0
        self._stop = threading.Event()
        self._sender: Optional[threading.Thread] = None
        self._collector: Optional[threading.Thread] = None
        self._t0: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "OpenLoopClient":
        if self._sender is not None:
            raise RuntimeError("OpenLoopClient already started")
        self._t0 = time.monotonic()
        self._sender = threading.Thread(target=self._send_loop, daemon=True,
                                        name=f"loadgen-send-{self.leg}")
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name=f"loadgen-collect-{self.leg}")
        self._sender.start()
        self._collector.start()
        return self

    def run(self, drain_timeout_s: float = 30.0) -> List[RequestRecord]:
        """Start, wait for the schedule to finish, drain, return records."""
        self.start()
        return self.finish(drain_timeout_s=drain_timeout_s)

    def finish(self, drain_timeout_s: float = 30.0) -> List[RequestRecord]:
        """Join the sender, give the collector ``drain_timeout_s`` past
        the last send to pull remaining answers, then mark stragglers
        ``lost`` and return every record in schedule order."""
        assert self._sender is not None, "finish() before start()"
        self._sender.join()
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            if not self._pending_uris():
                break
            time.sleep(0.02)
        self._stop.set()
        self._collector.join(timeout=5.0)
        with self._lock:
            records = list(self._records.values())
        for r in records:
            if r.outcome == "pending":
                r.outcome = "lost"
                obs.count("loadgen_outcomes_total", model=r.model,
                          outcome="lost", flat="loadgen/lost")
        records.sort(key=lambda r: r.t_sched)
        return records

    # -- introspection -----------------------------------------------------

    def _pending_uris(self) -> List[str]:
        with self._lock:
            return [u for u, r in self._records.items()
                    if r.outcome == "pending"]

    @property
    def open_loop_drops(self) -> int:
        with self._lock:
            return self._drops

    def sent_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._records.values()
                       if r.t_sent is not None)

    # -- threads -----------------------------------------------------------

    def _send_loop(self) -> None:
        t0 = self._t0
        for i, off in enumerate(self.schedule):
            if self._stop.is_set():
                return
            # sleep UNTIL the slot, never because of the server
            while True:
                ahead = (t0 + off) - time.monotonic()
                if ahead <= 0:
                    break
                time.sleep(min(ahead, 0.05))
            cls, payload = self.mix.draw(self._rng, t=float(off))
            uri = f"{self.uri_prefix}-{i:06d}"
            rec = RequestRecord(uri, cls.model, t_sched=float(off))
            with self._lock:
                self._records[uri] = rec
            obs.count("loadgen_requests_total", leg=self.leg,
                      model=cls.model, flat="loadgen/requests")
            try:
                self.inp.enqueue(uri=uri, model=cls.model,
                                 ttl_ms=cls.ttl_ms,
                                 **{cls.field: payload})
            except Exception:
                # transport refused (ring full, queue closed, malformed):
                # the schedule does NOT block or retry — count and move on
                with self._lock:
                    self._drops += 1
                    rec.outcome = "dropped"
                obs.count("loadgen_open_loop_drops_total", leg=self.leg,
                          flat="loadgen/open_loop_drops")
                continue
            sent = time.monotonic() - t0
            with self._lock:
                rec.t_sent = sent
            obs.observe("loadgen_schedule_lag_seconds",
                        max(0.0, sent - float(off)), leg=self.leg,
                        flat="loadgen/schedule_lag")

    def _collect_loop(self) -> None:
        prefix = f"{self.uri_prefix}-"
        while not self._stop.is_set():
            try:
                pend = [u for u in self.outp.queue.pending_results()
                        if u.startswith(prefix)]
            except Exception:
                pend = []
            if not pend:
                time.sleep(0.005)
                continue
            for uri in pend:
                try:
                    val = self.outp.query(uri,
                                          timeout=self._query_timeout_s)
                except Exception:
                    continue        # raced another reader / not ours yet
                done = time.monotonic() - self._t0
                outcome = _outcome_of(val)
                with self._lock:
                    rec = self._records.get(uri)
                    if rec is not None:
                        rec.t_done = done
                        rec.outcome = outcome
                        model = rec.model
                    else:
                        model = "unknown"
                obs.count("loadgen_outcomes_total", model=model,
                          outcome=outcome, flat="loadgen/outcomes")
