"""Payload distributions: what each scheduled arrival actually sends.

A :class:`PayloadClass` is one (model, tensor shape, dtype, TTL)
flavor; a :class:`PayloadMix` weights several classes and draws one
per arrival.  Weights may shift over the run (``shift_at_s`` /
``shift_weights``) — the two-model shifting mix the autoscale soak
drives is "balanced, then 80/20 onto the laggy model", expressed as
one mix.

``bench_serving``'s saturated legs draw their request arrays from
:func:`saturated_images` so the bench and the load harness share one
source of truth for request shapes (ISSUE 16 satellite).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PayloadClass", "PayloadMix", "ZipfianIdPayload",
           "saturated_images"]


class PayloadClass:
    """One request flavor: tensor spec + routing + deadline."""

    def __init__(self, model: str, shape: Tuple[int, ...],
                 dtype: str = "float32", weight: float = 1.0,
                 field: str = "x", ttl_ms: Optional[float] = None,
                 low: float = 0.0, high: float = 1.0):
        if weight < 0:
            raise ValueError(f"weight must be >= 0, got {weight}")
        self.model = str(model)
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        self.weight = float(weight)
        self.field = str(field)
        self.ttl_ms = None if ttl_ms is None else float(ttl_ms)
        self.low = float(low)
        self.high = float(high)

    def draw(self, rng: np.random.Generator) -> np.ndarray:
        """One fresh array (distinct per request — identical payloads
        can be memoized downstream and would measure a cache)."""
        if self.dtype.kind in "ui":
            return rng.integers(int(self.low), max(int(self.high), 2),
                                size=self.shape).astype(self.dtype)
        a = rng.uniform(self.low, self.high, size=self.shape)
        return a.astype(self.dtype)

    def __repr__(self) -> str:
        return (f"PayloadClass(model={self.model!r}, shape={self.shape}, "
                f"dtype={self.dtype.name}, weight={self.weight})")


class ZipfianIdPayload(PayloadClass):
    """Skewed recommender id traffic: each request's id block draws
    zipf(s) over ``vocab`` through :func:`analytics_zoo_tpu.data.zipf.
    zipfian_ids` — the SAME generator the ``bench.py`` sharded-table
    legs use, so the load harness's skew is byte-identical to the
    bench's for the same generator state (ISSUE 19 satellite).  The
    hot-row cache hit rates a bench pins therefore describe exactly the
    traffic this class offers."""

    def __init__(self, model: str, shape: Tuple[int, ...], vocab: int,
                 s: float = 1.0, dtype: str = "int32",
                 weight: float = 1.0, field: str = "ids",
                 ttl_ms: Optional[float] = None):
        super().__init__(model, shape, dtype=dtype, weight=weight,
                         field=field, ttl_ms=ttl_ms, low=0.0,
                         high=float(vocab))
        if vocab <= 0:
            raise ValueError(f"vocab must be positive, got {vocab}")
        self.vocab = int(vocab)
        self.s = float(s)

    def draw(self, rng: np.random.Generator) -> np.ndarray:
        from analytics_zoo_tpu.data.zipf import zipfian_ids

        n = int(np.prod(self.shape)) if self.shape else 1
        ids = zipfian_ids(self.vocab, n, self.s, rng=rng)
        return ids.reshape(self.shape).astype(self.dtype)

    def __repr__(self) -> str:
        return (f"ZipfianIdPayload(model={self.model!r}, "
                f"shape={self.shape}, vocab={self.vocab}, s={self.s})")


class PayloadMix:
    """Weighted mixture over payload classes, optionally time-varying.

    ``shift_at_s``/``shift_weights`` swap the per-class weights once at
    a run offset — the "two-model shifting mix" leg.  ``weights(t)``
    is pure; ``draw(rng, t)`` consumes exactly two draws from ``rng``
    per call (class pick + payload), so a mix driven by a seeded
    generator is deterministic from ``(seed, arrival index)``.
    """

    def __init__(self, classes: Sequence[PayloadClass],
                 shift_at_s: Optional[float] = None,
                 shift_weights: Optional[Sequence[float]] = None):
        if not classes:
            raise ValueError("PayloadMix needs at least one PayloadClass")
        self.classes = list(classes)
        if (shift_at_s is None) != (shift_weights is None):
            raise ValueError(
                "shift_at_s and shift_weights come together or not at all")
        if shift_weights is not None \
                and len(shift_weights) != len(self.classes):
            raise ValueError(
                f"shift_weights has {len(shift_weights)} entries for "
                f"{len(self.classes)} classes")
        self.shift_at_s = None if shift_at_s is None else float(shift_at_s)
        self.shift_weights = (None if shift_weights is None
                              else [float(w) for w in shift_weights])

    def models(self) -> List[str]:
        seen: List[str] = []
        for c in self.classes:
            if c.model not in seen:
                seen.append(c.model)
        return seen

    def weights(self, t: float = 0.0) -> np.ndarray:
        """Normalized class weights at run offset ``t``."""
        if self.shift_at_s is not None and t >= self.shift_at_s:
            w = np.asarray(self.shift_weights, dtype=np.float64)
        else:
            w = np.asarray([c.weight for c in self.classes],
                           dtype=np.float64)
        tot = w.sum()
        if tot <= 0:
            raise ValueError(f"mix weights sum to {tot} at t={t}")
        return w / tot

    def draw(self, rng: np.random.Generator,
             t: float = 0.0) -> Tuple[PayloadClass, np.ndarray]:
        """One (class, payload) pair for an arrival at offset ``t``."""
        idx = int(rng.choice(len(self.classes), p=self.weights(t)))
        cls = self.classes[idx]
        return cls, cls.draw(rng)

    def model_weights(self, t: float = 0.0) -> Dict[str, float]:
        """Per-model offered fraction at ``t`` (classes summed)."""
        w = self.weights(t)
        out: Dict[str, float] = {}
        for cls, wi in zip(self.classes, w):
            out[cls.model] = out.get(cls.model, 0.0) + float(wi)
        return out


def saturated_images(n: int, rs=None, seed: int = 0,
                     shape: Tuple[int, ...] = (224, 224, 3)) -> List[np.ndarray]:
    """``n`` distinct uint8 images for a saturated offered-load leg.

    The one source of truth for the request mix ``bench_serving`` and
    the load harness both saturate with.  Accepts an existing
    ``np.random.RandomState`` (``rs``) so callers that interleave other
    draws on the same stream keep their historical sequences; without
    one, a fresh ``RandomState(seed)`` makes the leg self-contained.
    """
    if rs is None:
        rs = np.random.RandomState(seed)
    return [rs.randint(0, 256, shape).astype(np.uint8) for _ in range(n)]
