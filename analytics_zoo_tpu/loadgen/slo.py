"""Fold per-request records into the pinned SLO artifact.

The reporter turns a flat list of
:class:`~analytics_zoo_tpu.loadgen.client.RequestRecord` timelines
into the numbers the SLO artifact pins (docs/LOADGEN.md "SLO artifact
schema"):

- **windows** — fixed-width time buckets over the run, each with
  offered/answered counts, per-model p99, and shed/lost tallies.  All
  downstream folds read windows, so a stall shows up as *windows over
  SLO*, not as a diluted whole-run percentile.
- **sustained QPS at SLO** — the highest offered rate averaged over
  ``min_consec`` CONSECUTIVE windows that all meet p99 < deadline.  A
  single lucky window is not "sustained".
- **shed fraction by model** — typed ``overloaded`` answers / offered,
  per model; the selective-shed assertion reads this.
- **recovery time to SLO** — after an event (burst end, process kill),
  seconds until the first of ``min_consec`` consecutive compliant
  windows.  ``None`` = never recovered inside the run.

Artifacts are plain JSON; ``SLO_r18.json`` at the repo root is the
doc-of-record copy ``tests/test_doc_drift.py`` machine-checks against
``docs/LOADGEN.md``'s pinned SLO_TABLE rows.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["percentile", "fold_windows", "sustained_qps_at_slo",
           "shed_fraction_by_model", "recovery_time_to_slo",
           "outcome_counts", "write_artifact"]

_SHED_CODES = ("overloaded", "expired")


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile; None for an empty sample."""
    vs = sorted(float(v) for v in values)
    if not vs:
        return None
    idx = min(len(vs) - 1, max(0, int(math.ceil(q / 100.0 * len(vs))) - 1))
    return vs[idx]


def outcome_counts(records) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for r in records:
        out[r.outcome] = out.get(r.outcome, 0) + 1
    return out


def fold_windows(records, window_s: float = 1.0,
                 duration_s: Optional[float] = None) -> List[Dict[str, Any]]:
    """Bucket records by schedule time into ``window_s`` windows.

    Latency is schedule-to-answer (``RequestRecord.latency_s``), so a
    request delayed by a stalled server lands its full queueing delay
    in the window it was OFFERED in — the coordinated-omission-honest
    accounting.
    """
    if window_s <= 0:
        raise ValueError(f"window_s must be positive, got {window_s}")
    records = list(records)
    if duration_s is None:
        duration_s = max((r.t_sched for r in records), default=0.0) + 1e-9
    n_win = max(1, int(math.ceil(duration_s / window_s)))
    wins: List[Dict[str, Any]] = [
        {"t_start": i * window_s, "t_end": (i + 1) * window_s,
         "offered": 0, "answered": 0, "shed": 0, "lost": 0,
         "latencies_ms": {}}
        for i in range(n_win)]
    for r in records:
        i = min(n_win - 1, int(r.t_sched / window_s))
        w = wins[i]
        w["offered"] += 1
        if r.outcome == "ok":
            w["answered"] += 1
            lat = r.latency_s
            if lat is not None:
                w["latencies_ms"].setdefault(r.model, []).append(lat * 1e3)
        elif r.outcome in _SHED_CODES:
            w["shed"] += 1
        elif r.outcome in ("lost", "dropped"):
            w["lost"] += 1
        else:
            w["answered"] += 1      # typed error: terminated, not lost
    for w in wins:
        w["offered_qps"] = w["offered"] / window_s
        w["p99_ms"] = {m: percentile(ls, 99)
                       for m, ls in w["latencies_ms"].items()}
        del w["latencies_ms"]
    return wins


def _window_meets(w: Dict[str, Any], slo_ms_by_model: Dict[str, float],
                  require_answers: bool) -> bool:
    if w["lost"]:
        return False
    if require_answers and not w["answered"]:
        return False
    for model, slo in slo_ms_by_model.items():
        if slo <= 0:
            continue
        p99 = w["p99_ms"].get(model)
        if p99 is not None and p99 > slo:
            return False
    return True


def sustained_qps_at_slo(windows: Sequence[Dict[str, Any]],
                         slo_ms_by_model: Dict[str, float],
                         min_consec: int = 3) -> Optional[float]:
    """Best offered QPS averaged over any ``min_consec`` consecutive
    windows that ALL meet every model's p99 SLO (and lost nothing)."""
    best: Optional[float] = None
    run: List[float] = []
    for w in windows:
        if _window_meets(w, slo_ms_by_model, require_answers=True):
            run.append(w["offered_qps"])
            if len(run) >= min_consec:
                qps = sum(run[-min_consec:]) / min_consec
                if best is None or qps > best:
                    best = qps
        else:
            run = []
    return best


def shed_fraction_by_model(records) -> Dict[str, float]:
    """Typed sheds (overloaded/expired) over offered, per model."""
    offered: Dict[str, int] = {}
    shed: Dict[str, int] = {}
    for r in records:
        offered[r.model] = offered.get(r.model, 0) + 1
        if r.outcome in _SHED_CODES:
            shed[r.model] = shed.get(r.model, 0) + 1
    return {m: shed.get(m, 0) / n for m, n in offered.items() if n}


def recovery_time_to_slo(windows: Sequence[Dict[str, Any]],
                         event_t: float,
                         slo_ms_by_model: Dict[str, float],
                         min_consec: int = 2) -> Optional[float]:
    """Seconds from ``event_t`` to the start of the first
    ``min_consec``-window compliant streak at or after it.  0.0 means
    the event never dented the SLO; None means no recovery in-run."""
    idxs = [i for i, w in enumerate(windows) if w["t_end"] > event_t]
    streak = 0
    for i in idxs:
        if _window_meets(windows[i], slo_ms_by_model,
                         require_answers=False):
            streak += 1
            if streak >= min_consec:
                start = windows[i - min_consec + 1]["t_start"]
                return max(0.0, start - event_t)
        else:
            streak = 0
    return None


def write_artifact(path: str, report: Dict[str, Any]) -> str:
    """Atomic JSON write (tmp + replace) — a reader never sees a torn
    artifact, and strict JSON (no NaN/Infinity) is enforced."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True,
                      allow_nan=False)
            f.write("\n")
        os.replace(tmp, path)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path
