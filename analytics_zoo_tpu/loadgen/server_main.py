"""Standalone serving process for cross-process load legs.

Runs a full ``ClusterServing`` pipeline over a ``FileQueue`` spool so
real OS-process clients (``loadgen/client_main.py`` or the in-process
kill-leg client) can reach it from outside.  The model is built
DETERMINISTICALLY — seeded weights, seeded data, reset name scope — so
every process that runs this module produces the identical fingerprint
and a successor process warm-starts from the predecessor's persistent
compile cache with zero live compiles.

The process periodically dumps a status JSON (atomic replace) carrying
the warm-start proof (``compile_count``, ``warm_count``, cache event
counts) plus serving health; the kill leg reads it instead of scraping
logs.  SIGTERM stops cleanly (final status dump, exit 0); SIGKILL is
the point — the kill leg sends it mid-storm.

Usage::

    python -m analytics_zoo_tpu.loadgen.server_main \
        --queue-root /tmp/spool --cache-dir /tmp/cache \
        --status-file /tmp/server.status.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--queue-root", required=True)
    p.add_argument("--queue-name", default="loadgen_stream")
    p.add_argument("--cache-dir", required=True)
    p.add_argument("--status-file", required=True)
    p.add_argument("--slo-p99-ms", type=float, default=1000.0)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--decode-workers", type=int, default=2)
    p.add_argument("--max-batch-delay-ms", type=float, default=3.0)
    p.add_argument("--status-interval", type=int, default=2,
                   help="dump status every N supervisor ticks")
    p.add_argument("--autoscale", action="store_true")
    return p.parse_args(argv)


def build_model():
    """The deterministic two-layer Dense model shared by every loadgen
    server process (same idiom as tests/multiprocess_worker.py's
    ``serving_warm`` scenario: seeded context + seeded data => identical
    fingerprint in every process)."""
    import numpy as np

    from analytics_zoo_tpu.deploy import InferenceModel
    from analytics_zoo_tpu.nn import Sequential, reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Activation, Dense
    from analytics_zoo_tpu.train.optimizers import Adam

    buckets = (1, 4, 8)
    in_dim, out_dim = 12, 4
    rs = np.random.RandomState(0)
    reset_name_scope()
    net = Sequential([Dense(16, input_shape=(in_dim,)),
                      Activation("relu"), Dense(out_dim)])
    net.compile(optimizer=Adam(1e-2), loss="mse")
    x = rs.randn(32, in_dim).astype(np.float32)
    net.fit(x, rs.randn(32, out_dim).astype(np.float32), batch_size=16,
            nb_epoch=1, verbose=False)
    return InferenceModel.from_keras_net(net, net.estimator.params,
                                         net.estimator.state,
                                         batch_buckets=buckets)


def _dump_status(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def main(argv=None) -> int:
    args = parse_args(argv)
    from analytics_zoo_tpu.deploy import ClusterServing, ServingConfig
    from analytics_zoo_tpu.deploy.serving import FileQueue

    model = build_model()
    q = FileQueue(args.queue_root, name=args.queue_name)
    cfg = ServingConfig(
        batch_size=args.batch_size, poll_timeout_s=0.05,
        max_batch_delay_ms=args.max_batch_delay_ms,
        decode_workers=args.decode_workers,
        supervisor_interval_s=0.1,
        compile_cache_dir=args.cache_dir,
        slo_p99_ms={"default": args.slo_p99_ms},
        autoscale=args.autoscale, autoscale_interval_s=0.2,
        autoscale_cooldown_s=0.5)
    srv = ClusterServing({"default": model}, q, cfg).start()

    # Full bucket coverage through the REPLICA dispatch path before
    # declaring ready: replica programs carry their target device in
    # the cache signature, so predict()-side coverage would persist a
    # different flavor than the one the pipeline executes.  The cold
    # process stores every (bucket, device) executable; a successor
    # warm-starts the whole set and serves the storm with zero live
    # compiles.
    import numpy as np
    xcov = np.random.RandomState(1).randn(8, 12).astype(np.float32)
    rep = model.replica_forwards(n=1)[0]
    for b in model.batch_buckets:
        rep.harvest(rep.dispatch([xcov[:b]]))

    def status_payload() -> Dict[str, Any]:
        h = srv.health()
        audit = srv.autoscale_audit()
        return {
            "ready": True,
            "pid": os.getpid(),
            "t": time.time(),
            "fingerprint": model.fingerprint(),
            "compile_count": int(model.compile_count),
            "warm_count": int(model.warm_count),
            "cache": h.get("compile_cache"),
            "records_served": h.get("records_served"),
            "queue": h.get("queue"),
            "models": h.get("models"),
            "autoscale_flaps": (audit or {}).get("flaps"),
        }

    def dump() -> None:
        try:
            _dump_status(args.status_file, status_payload())
        except Exception:           # status is best-effort telemetry
            pass

    dump()                          # the readiness barrier for callers
    srv.add_scenario_check("loadgen_status_dump", dump,
                           every=args.status_interval)

    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    signal.signal(signal.SIGINT, lambda *_: stop_evt.set())
    while not stop_evt.is_set():
        stop_evt.wait(0.2)
    srv.stop()
    dump()                          # final post-traffic truth
    return 0


if __name__ == "__main__":
    sys.exit(main())
