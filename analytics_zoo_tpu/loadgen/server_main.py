"""Standalone serving process for cross-process load legs.

Runs a full ``ClusterServing`` pipeline over a ``FileQueue`` spool so
real OS-process clients (``loadgen/client_main.py`` or the in-process
kill-leg client) can reach it from outside.  The model is built
DETERMINISTICALLY — seeded weights, seeded data, reset name scope — so
every process that runs this module produces the identical fingerprint
and a successor process warm-starts from the predecessor's persistent
compile cache with zero live compiles.

The process periodically dumps a status JSON (atomic replace) carrying
the warm-start proof (``compile_count``, ``warm_count``, cache event
counts) plus serving health; the kill leg reads it instead of scraping
logs.  SIGTERM stops cleanly (final status dump, exit 0); SIGKILL is
the point — the kill leg sends it mid-storm.

Pod mode (``--pod-processes N``, docs/SERVING.md "Pod-scale serving"):
the processes of one pod join a ``jax.distributed`` coordination
service.  Process 0 (the lead) runs the serving pipeline with a mesh
replica (``--mesh-replicas``) over a sharded-table model
(``--model bag``), every mesh dispatch gated by the pod's deadline
barrier; processes > 0 are member hosts that run the matching barrier
loop.  SIGKILLing a member mid-storm times the lead's next dispatch
barrier out within ``--barrier-timeout`` seconds, quarantining the
whole mesh replica atomically while the lead keeps serving on its
single-chip replica — the pod kill leg
(``loadgen/harness.py::run_pod_kill_leg``) drives exactly that.

Usage::

    python -m analytics_zoo_tpu.loadgen.server_main \
        --queue-root /tmp/spool --cache-dir /tmp/cache \
        --status-file /tmp/server.status.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--queue-root", required=True)
    p.add_argument("--queue-name", default="loadgen_stream")
    p.add_argument("--cache-dir", required=True)
    p.add_argument("--status-file", required=True)
    p.add_argument("--slo-p99-ms", type=float, default=1000.0)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--decode-workers", type=int, default=2)
    p.add_argument("--max-batch-delay-ms", type=float, default=3.0)
    p.add_argument("--status-interval", type=int, default=2,
                   help="dump status every N supervisor ticks")
    p.add_argument("--autoscale", action="store_true")
    # pod mode (docs/SERVING.md "Pod-scale serving")
    p.add_argument("--model", default="dense", choices=["dense", "bag"],
                   help="'bag' = the sharded-embedding-table model the "
                        "mesh replica shards over the model axis")
    p.add_argument("--pod-processes", type=int, default=0,
                   help="> 1 joins a jax.distributed pod of this size")
    p.add_argument("--pod-id", type=int, default=0,
                   help="this process's id in the pod (0 = lead)")
    p.add_argument("--pod-port", type=int, default=0,
                   help="coordination-service port (lead hosts it)")
    p.add_argument("--pod-name", default="pod",
                   help="pod name (prefixes the dispatch barriers)")
    p.add_argument("--local-devices", type=int, default=0,
                   help="force N virtual CPU devices (mesh replicas "
                        "need >= 2)")
    p.add_argument("--barrier-timeout", type=float, default=2.0,
                   help="dist_barrier_timeout_s: a member missing a "
                        "dispatch barrier this long is presumed dead")
    p.add_argument("--follower-idle-timeout", type=float, default=600.0,
                   help="member hosts give up after this long with no "
                        "dispatch barrier from the lead (normally they "
                        "exit when the lead's coordination service "
                        "goes away — a member must NOT time a live "
                        "barrier out, or the lead's next arrival at it "
                        "fails spuriously)")
    p.add_argument("--mesh-replicas", type=int, default=0,
                   help="mesh-replica slots to plan (needs --model bag)")
    return p.parse_args(argv)


def build_model():
    """The deterministic two-layer Dense model shared by every loadgen
    server process (same idiom as tests/multiprocess_worker.py's
    ``serving_warm`` scenario: seeded context + seeded data => identical
    fingerprint in every process)."""
    import numpy as np

    from analytics_zoo_tpu.deploy import InferenceModel
    from analytics_zoo_tpu.nn import Sequential, reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Activation, Dense
    from analytics_zoo_tpu.train.optimizers import Adam

    buckets = (1, 4, 8)
    in_dim, out_dim = 12, 4
    rs = np.random.RandomState(0)
    reset_name_scope()
    net = Sequential([Dense(16, input_shape=(in_dim,)),
                      Activation("relu"), Dense(out_dim)])
    net.compile(optimizer=Adam(1e-2), loss="mse")
    x = rs.randn(32, in_dim).astype(np.float32)
    net.fit(x, rs.randn(32, out_dim).astype(np.float32), batch_size=16,
            nb_epoch=1, verbose=False)
    return InferenceModel.from_keras_net(net, net.estimator.params,
                                         net.estimator.state,
                                         batch_buckets=buckets)


def build_bag_model():
    """The deterministic sharded-table model for pod mode: a single
    int32-ids input through a ``ShardedEmbeddingTable`` mean-bag into a
    Dense head.  Weights are the SEEDED INITIALIZERS, not a fit — in
    pod mode this process has already joined a multi-process
    ``jax.distributed`` runtime, and a training fit there would issue
    global-mesh collectives the member hosts never join.  Seeded init
    is just as deterministic, so every pod generation produces the
    identical fingerprint and warm-starts its predecessor's compile
    cache — including the mesh-sharded forward flavor (cache keys fold
    the mesh).  Contract constants (ids dim 4, vocab 64) match
    ``harness.POD_IN_DIM`` / ``harness.POD_VOCAB``."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.deploy import InferenceModel
    from analytics_zoo_tpu.nn import Input, Model, reset_name_scope
    from analytics_zoo_tpu.nn.layers.core import Dense
    from analytics_zoo_tpu.nn.layers.sharded_embedding import \
        ShardedEmbeddingTable

    reset_name_scope()
    ids = Input(shape=(4,), dtype=jnp.int32, name="ids")
    bag = ShardedEmbeddingTable(64, 8, combiner="mean",
                                name="embed")(ids)
    net = Model([ids], Dense(4, name="head")(bag), name="default")
    net._sharded_tables = ("embed",)
    net.compile(optimizer="adam", loss="mse")
    # NOT est._ensure_built: that device_puts the params onto the
    # CONTEXT mesh, which under a multihost pod spans every process —
    # a cross-process collective the member hosts never join.  A plain
    # local jit runs the same seeded initializers entirely in-process.
    import jax
    est = net.estimator
    params, state = jax.jit(
        lambda r: est.model.init(r, (2, 4)))(jax.random.PRNGKey(0))
    return InferenceModel.from_keras_net(net, params, state,
                                         batch_buckets=(1, 4, 8))


def follower_main(args) -> int:
    """A pod member host: arrive at every ``zoo_pod_dispatch_*``
    deadline barrier the lead's mesh dispatches enter.  Exits 0 when
    the barriers stop coming (lead finished or died — surfaced as a
    ``HostLostError`` timeout after ``--follower-idle-timeout``).  The
    pod kill leg SIGKILLs this process mid-storm; dying between
    barriers IS the scenario."""
    from analytics_zoo_tpu.core.context import dist_barrier
    from analytics_zoo_tpu.robust.errors import HostLostError

    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    signal.signal(signal.SIGINT, lambda *_: stop_evt.set())
    _dump_status(args.status_file,
                 {"ready": True, "pod_follower": True,
                  "pod_id": args.pod_id, "pid": os.getpid()})
    seq = 0
    while not stop_evt.is_set():
        seq += 1
        try:
            dist_barrier(f"zoo_pod_dispatch_{args.pod_name}_{seq}",
                         timeout_s=args.follower_idle_timeout,
                         phase="dispatch")
        except HostLostError:
            break
        except Exception:
            break       # coordination service gone (lead exited)
    _dump_status(args.status_file,
                 {"ready": True, "pod_follower": True,
                  "pod_id": args.pod_id, "pid": os.getpid(),
                  "barriers": seq - 1, "t": time.time()})
    # skip the distributed shutdown handshake: the lead (which hosts
    # the coordination service) may already be gone
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


def _dump_status(path: str, payload: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.local_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{args.local_devices}").strip()
    if args.pod_processes > 1:
        from analytics_zoo_tpu import init_zoo_context
        init_zoo_context(
            multihost=True,
            coordinator_address=f"127.0.0.1:{args.pod_port}",
            num_processes=args.pod_processes,
            process_id=args.pod_id,
            dist_barrier_timeout_s=args.barrier_timeout)
        if args.pod_id != 0:
            return follower_main(args)

    from analytics_zoo_tpu.deploy import ClusterServing, ServingConfig
    from analytics_zoo_tpu.deploy.serving import FileQueue, PodCoordinator

    model = build_bag_model() if args.model == "bag" else build_model()
    mesh = roster = pod = None
    if args.mesh_replicas > 0:
        import jax
        import numpy as np

        from analytics_zoo_tpu.core.context import HostRoster

        devs = jax.local_devices()
        ways = 2 if len(devs) >= 2 else 1
        # the mesh replica shards over the lead's LOCAL devices; the
        # pod barrier is what crosses the process boundary
        mesh = jax.sharding.Mesh(
            np.asarray(devs[:ways]).reshape(1, ways), ("data", "model"))
        roster = HostRoster(list(range(max(1, args.pod_processes))))
        if args.pod_processes > 1:
            pod = PodCoordinator(roster, args.pod_id,
                                 name=args.pod_name,
                                 barrier_timeout_s=args.barrier_timeout)
    q = FileQueue(args.queue_root, name=args.queue_name)
    cfg = ServingConfig(
        batch_size=args.batch_size, poll_timeout_s=0.05,
        max_batch_delay_ms=args.max_batch_delay_ms,
        decode_workers=args.decode_workers,
        supervisor_interval_s=0.1,
        compile_cache_dir=args.cache_dir,
        slo_p99_ms={"default": args.slo_p99_ms},
        mesh_replicas=args.mesh_replicas,
        autoscale=args.autoscale, autoscale_interval_s=0.2,
        autoscale_cooldown_s=0.5)
    srv = ClusterServing({"default": model}, q, cfg, mesh=mesh,
                         roster=roster, pod=pod).start()

    # Full bucket coverage through the REPLICA dispatch path before
    # declaring ready: replica programs carry their target device in
    # the cache signature, so predict()-side coverage would persist a
    # different flavor than the one the pipeline executes.  The cold
    # process stores every (bucket, device) executable; a successor
    # warm-starts the whole set and serves the storm with zero live
    # compiles.
    import numpy as np
    if args.model == "bag":
        xcov = np.random.RandomState(1).randint(
            0, 64, (8, 4)).astype(np.int32)
    else:
        xcov = np.random.RandomState(1).randn(8, 12).astype(np.float32)
    rep = model.replica_forwards(n=1)[0]
    for b in model.batch_buckets:
        rep.harvest(rep.dispatch([xcov[:b]]))
    if mesh is not None and args.mesh_replicas > 0:
        # cover the mesh-sharded flavor too (its cache signature folds
        # the shard mesh), bypassing the pod barrier: a successor pod
        # must warm-start the WHOLE executable set, not just the
        # single-chip one.  Storm-time mesh dispatches then never
        # compile live — the pod kill leg's warm_compile_count==0 pin.
        srep = model.shard_replica(mesh)
        for b in model.batch_buckets:
            srep.harvest(srep.dispatch([xcov[:b]]))

    def status_payload() -> Dict[str, Any]:
        h = srv.health()
        audit = srv.autoscale_audit()
        return {
            "ready": True,
            "pid": os.getpid(),
            "t": time.time(),
            "fingerprint": model.fingerprint(),
            "compile_count": int(model.compile_count),
            "warm_count": int(model.warm_count),
            "cache": h.get("compile_cache"),
            "records_served": h.get("records_served"),
            "queue": h.get("queue"),
            "models": h.get("models"),
            "mesh": h.get("mesh"),
            "pod_id": args.pod_id if args.pod_processes > 1 else None,
            "autoscale_flaps": (audit or {}).get("flaps"),
        }

    def dump() -> None:
        try:
            _dump_status(args.status_file, status_payload())
        except Exception:           # status is best-effort telemetry
            pass

    dump()                          # the readiness barrier for callers
    srv.add_scenario_check("loadgen_status_dump", dump,
                           every=args.status_interval)

    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    signal.signal(signal.SIGINT, lambda *_: stop_evt.set())
    while not stop_evt.is_set():
        stop_evt.wait(0.2)
    srv.stop()
    dump()                          # final post-traffic truth
    if args.pod_processes > 1:
        # skip the distributed shutdown handshake: a pod member this
        # lead outlived (the kill leg's SIGKILLed follower) can never
        # arrive at it
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
