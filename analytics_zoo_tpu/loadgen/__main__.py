"""Artifact generator: run every load leg and pin ``SLO_r18.json``.

::

    JAX_PLATFORMS=cpu python -m analytics_zoo_tpu.loadgen \
        --out SLO_r18.json [--workdir /tmp/loadgen] [--quick]

The artifact's schema and the doc-pinned rows are described in
docs/LOADGEN.md; ``tests/test_doc_drift.py`` machine-checks the pinned
``SLO_TABLE`` blocks against the newest ``SLO_*.json`` in the repo
root.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="SLO_r18.json")
    p.add_argument("--workdir", default=None,
                   help="scratch dir for the kill leg's spool/cache "
                        "(a fresh tempdir when omitted)")
    p.add_argument("--quick", action="store_true",
                   help="halved durations for smoke runs (never for "
                        "the pinned artifact)")
    args = p.parse_args(argv)

    from analytics_zoo_tpu.loadgen.harness import default_report
    from analytics_zoo_tpu.loadgen.slo import write_artifact

    workdir = args.workdir or tempfile.mkdtemp(prefix="loadgen-")
    t0 = time.monotonic()
    report = default_report(workdir, quick=args.quick)
    report["run_metadata"]["wall_s"] = round(time.monotonic() - t0, 2)
    write_artifact(args.out, report)
    print(f"wrote {os.path.abspath(args.out)} "
          f"({report['run_metadata']['wall_s']}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
