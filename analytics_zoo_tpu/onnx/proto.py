"""Minimal pure-python ONNX protobuf codec (wire format).

The reference's ONNX importer (pyzoo/zoo/pipeline/api/onnx/onnx_loader.py)
depends on the ``onnx`` package; this environment ships without it, so the
loader decodes the protobuf wire format directly for the message subset an
importer needs: ModelProto / GraphProto / NodeProto / AttributeProto /
TensorProto / ValueInfoProto.  Field numbers follow the public onnx.proto
spec (stable across IR versions).  An encoder for the same subset exists
so tests (and ``export_onnx``) can produce real ``.onnx`` bytes without
the package either.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# -- wire-format primitives --------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:                       # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:                     # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:                     # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:                     # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _key(fnum: int, wtype: int) -> bytes:
    return _write_varint((fnum << 3) | wtype)


def _ld(fnum: int, payload: bytes) -> bytes:
    return _key(fnum, 2) + _write_varint(len(payload)) + payload


def _signed(v: int) -> int:
    """Two's-complement interpretation of a 64-bit varint."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _varints(wtype: int, val) -> List[int]:
    """Repeated-varint field values: proto3 serializers PACK repeated
    ints (wire type 2, the default for onnx files produced by protoc /
    the onnx package), while proto2-era writers emit one varint per
    element — accept both."""
    if wtype == 2:
        out, pos = [], 0
        while pos < len(val):
            v, pos = _read_varint(val, pos)
            out.append(_signed(v))
        return out
    return [_signed(val)]


# -- message dataclasses -----------------------------------------------------

_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16,
           6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
           11: np.float64, 12: np.uint32, 13: np.uint64}
_DTYPE_IDS = {np.dtype(v): k for k, v in _DTYPES.items()}


@dataclass
class Tensor:
    name: str = ""
    dims: Tuple[int, ...] = ()
    data_type: int = 1
    array: Optional[np.ndarray] = None


@dataclass
class Attribute:
    name: str = ""
    type: int = 0      # 1 f, 2 i, 3 s, 4 t, 6 floats, 7 ints, 8 strings
    value: Any = None


@dataclass
class Node:
    op_type: str = ""
    name: str = ""
    inputs: List[str] = field(default_factory=list)
    outputs: List[str] = field(default_factory=list)
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ValueInfo:
    name: str = ""
    elem_type: int = 1
    shape: Tuple[Optional[int], ...] = ()


@dataclass
class Graph:
    name: str = ""
    nodes: List[Node] = field(default_factory=list)
    initializers: List[Tensor] = field(default_factory=list)
    inputs: List[ValueInfo] = field(default_factory=list)
    outputs: List[ValueInfo] = field(default_factory=list)


@dataclass
class Model:
    ir_version: int = 8
    producer: str = ""
    opset: int = 13
    graph: Graph = field(default_factory=Graph)


# -- decoding ----------------------------------------------------------------

def _decode_tensor(buf: bytes) -> Tensor:
    t = Tensor()
    dims: List[int] = []
    floats: List[float] = []
    ints: List[int] = []
    raw = b""
    for fnum, wtype, val in _fields(buf):
        if fnum == 1:            # dims (packed by proto3 serializers)
            dims.extend(_varints(wtype, val))
        elif fnum == 2:
            t.data_type = val
        elif fnum == 4:          # packed float_data
            floats.extend(struct.unpack(f"<{len(val) // 4}f", val)) \
                if wtype == 2 else floats.append(
                    struct.unpack("<f", val)[0])
        elif fnum in (5, 7):     # int32_data / int64_data (packed varints)
            ints.extend(_varints(wtype, val))
        elif fnum == 8:
            t.name = val.decode()
        elif fnum == 9:
            raw = val
        elif fnum == 10:         # packed double_data
            floats.extend(struct.unpack(f"<{len(val) // 8}d", val)) \
                if wtype == 2 else floats.append(
                    struct.unpack("<d", val)[0])
    t.dims = tuple(dims)
    dtype = _DTYPES.get(t.data_type, np.float32)
    if raw:
        t.array = np.frombuffer(raw, dtype=dtype).reshape(t.dims).copy()
    elif floats:
        t.array = np.asarray(floats, dtype=dtype).reshape(t.dims)
    elif ints:
        t.array = np.asarray(ints, dtype=dtype).reshape(t.dims)
    else:
        t.array = np.zeros(t.dims, dtype=dtype)
    return t


def _decode_attr(buf: bytes) -> Attribute:
    a = Attribute()
    floats: List[float] = []
    ints: List[int] = []
    strings: List[bytes] = []
    for fnum, wtype, val in _fields(buf):
        if fnum == 1:
            a.name = val.decode()
        elif fnum == 2:
            a.value = struct.unpack("<f", val)[0]
            a.type = a.type or 1
        elif fnum == 3:
            a.value = _signed(val)
            a.type = a.type or 2
        elif fnum == 4:
            a.value = val
            a.type = a.type or 3
        elif fnum == 5:
            a.value = _decode_tensor(val)
            a.type = a.type or 4
        elif fnum == 7:
            if wtype == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif fnum == 8:
            ints.extend(_varints(wtype, val))
        elif fnum == 9:
            strings.append(val)
        elif fnum == 20:
            a.type = val
    if floats:
        a.value, a.type = floats, 6
    elif ints:
        a.value, a.type = ints, 7
    elif strings:
        a.value, a.type = strings, 8
    return a


def _decode_node(buf: bytes) -> Node:
    n = Node()
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            n.inputs.append(val.decode())
        elif fnum == 2:
            n.outputs.append(val.decode())
        elif fnum == 3:
            n.name = val.decode()
        elif fnum == 4:
            n.op_type = val.decode()
        elif fnum == 5:
            a = _decode_attr(val)
            n.attrs[a.name] = a.value
    return n


def _decode_value_info(buf: bytes) -> ValueInfo:
    vi = ValueInfo()
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            vi.name = val.decode()
        elif fnum == 2:          # TypeProto
            for f2, _, v2 in _fields(val):
                if f2 == 1:      # tensor_type
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            vi.elem_type = v3
                        elif f3 == 2:    # TensorShapeProto
                            dims: List[Optional[int]] = []
                            for f4, _, v4 in _fields(v3):
                                if f4 == 1:      # dim
                                    dim_val: Optional[int] = None
                                    for f5, _, v5 in _fields(v4):
                                        if f5 == 1:
                                            dim_val = _signed(v5)
                                    dims.append(dim_val)
                            vi.shape = tuple(dims)
    return vi


def _decode_graph(buf: bytes) -> Graph:
    g = Graph()
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            g.nodes.append(_decode_node(val))
        elif fnum == 2:
            g.name = val.decode()
        elif fnum == 5:
            g.initializers.append(_decode_tensor(val))
        elif fnum == 11:
            g.inputs.append(_decode_value_info(val))
        elif fnum == 12:
            g.outputs.append(_decode_value_info(val))
    return g


def decode_model(buf: bytes) -> Model:
    m = Model()
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            m.ir_version = val
        elif fnum == 2:
            m.producer = val.decode()
        elif fnum == 7:
            m.graph = _decode_graph(val)
        elif fnum == 8:          # opset_import
            for f2, _, v2 in _fields(val):
                if f2 == 2:
                    m.opset = _signed(v2)
    return m


# -- encoding (tests / export) ----------------------------------------------

def _encode_tensor(t: Tensor) -> bytes:
    out = b""
    for d in t.dims:
        out += _key(1, 0) + _write_varint(d)
    out += _key(2, 0) + _write_varint(t.data_type)
    if t.array is not None:
        out += _ld(9, np.ascontiguousarray(t.array).tobytes())
    if t.name:
        out += _ld(8, t.name.encode())
    return out


def _encode_attr(name: str, value: Any) -> bytes:
    out = _ld(1, name.encode())
    if isinstance(value, (float, np.floating)):
        out += _key(2, 5) + struct.pack("<f", float(value))
        out += _key(20, 0) + _write_varint(1)
    elif isinstance(value, (bool, int, np.integer)):
        out += _key(3, 0) + _write_varint(int(value))
        out += _key(20, 0) + _write_varint(2)
    elif isinstance(value, (bytes, str)):
        out += _ld(4, value.encode() if isinstance(value, str) else value)
        out += _key(20, 0) + _write_varint(3)
    elif isinstance(value, Tensor):
        out += _ld(5, _encode_tensor(value))
        out += _key(20, 0) + _write_varint(4)
    elif isinstance(value, (list, tuple, np.ndarray)) and len(value) \
            and any(isinstance(v, (float, np.floating)) for v in value) \
            and all(isinstance(v, (int, float, np.integer, np.floating))
                    for v in value):
        # any float promotes the whole list to FLOATS (lossless); pure
        # ints stay INTS below
        for v in value:
            out += _key(7, 5) + struct.pack("<f", float(v))
        out += _key(20, 0) + _write_varint(6)
    elif isinstance(value, (list, tuple, np.ndarray)):
        for v in value:
            out += _key(8, 0) + _write_varint(int(v))
        out += _key(20, 0) + _write_varint(7)
    else:
        raise ValueError(f"unsupported attribute value {value!r}")
    return out


def _encode_node(n: Node) -> bytes:
    out = b""
    for i in n.inputs:
        out += _ld(1, i.encode())
    for o in n.outputs:
        out += _ld(2, o.encode())
    if n.name:
        out += _ld(3, n.name.encode())
    out += _ld(4, n.op_type.encode())
    for k, v in n.attrs.items():
        out += _ld(5, _encode_attr(k, v))
    return out


def _encode_value_info(vi: ValueInfo) -> bytes:
    dims = b""
    for d in vi.shape:
        dim = b"" if d is None else _key(1, 0) + _write_varint(d)
        dims += _ld(1, dim)
    tensor_type = (_key(1, 0) + _write_varint(vi.elem_type)
                   + _ld(2, dims))
    return _ld(1, vi.name.encode()) + _ld(2, _ld(1, tensor_type))


def _encode_graph(g: Graph) -> bytes:
    out = b""
    for n in g.nodes:
        out += _ld(1, _encode_node(n))
    if g.name:
        out += _ld(2, g.name.encode())
    for t in g.initializers:
        out += _ld(5, _encode_tensor(t))
    for vi in g.inputs:
        out += _ld(11, _encode_value_info(vi))
    for vi in g.outputs:
        out += _ld(12, _encode_value_info(vi))
    return out


def encode_model(m: Model) -> bytes:
    out = _key(1, 0) + _write_varint(m.ir_version)
    if m.producer:
        out += _ld(2, m.producer.encode())
    out += _ld(7, _encode_graph(m.graph))
    opset = _ld(1, b"") + _key(2, 0) + _write_varint(m.opset)
    out += _ld(8, opset)
    return out


def tensor_from_array(name: str, arr: np.ndarray) -> Tensor:
    arr = np.asarray(arr)
    return Tensor(name=name, dims=arr.shape,
                  data_type=_DTYPE_IDS[arr.dtype], array=arr)
