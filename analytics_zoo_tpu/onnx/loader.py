"""ONNX graph -> native JAX program.

Reference capability: pyzoo/zoo/pipeline/api/onnx/onnx_loader.py +
mapper/*.py (~40 op mappers into the Keras-layer graph).  TPU-native
redesign: ops lower directly to jax/lax primitives in a topologically
ordered tensor-environment program (no intermediate layer objects), with
initializer tensors as the trainable param pytree — so an imported ONNX
model both predicts AND trains under the SPMD Estimator.

ONNX convs/pools are NCHW; they are kept NCHW verbatim (like
tfpark.TorchModel) — XLA lays NCHW onto the MXU itself, and Flatten->Gemm
weight ordering stays correct.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.onnx import proto

__all__ = ["load_onnx", "load_onnx_bytes", "OnnxProgram",
           "UnsupportedOnnxOp"]


class UnsupportedOnnxOp(ValueError):
    pass


def _pads_to_lax(pads: Sequence[int], spatial: int):
    """ONNX pads [b1..bn, e1..en] -> lax [(b1, e1), ...]."""
    if not pads:
        return [(0, 0)] * spatial
    return [(int(pads[i]), int(pads[i + spatial])) for i in range(spatial)]


def _conv_dn(spatial: int):
    if spatial == 1:
        return ("NCW", "OIW", "NCW")
    if spatial == 2:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


def _auto_pad_or_pads(attrs, spatial: int):
    """Resolve ONNX auto_pad/pads to a lax padding spec.  SAME_LOWER
    (extra pad at the START) has no lax string equivalent — fail loudly
    rather than shift every activation by one."""
    auto_pad = attrs.get("auto_pad", b"NOTSET")
    if isinstance(auto_pad, bytes):
        auto_pad = auto_pad.decode()
    if auto_pad == "SAME_UPPER":
        return "SAME"
    if auto_pad == "SAME_LOWER":
        raise UnsupportedOnnxOp(
            "auto_pad=SAME_LOWER (lax SAME pads at the end; re-export "
            "with explicit pads)")
    return _pads_to_lax(attrs.get("pads", []), spatial)


# each mapper: (node) -> fn(xs, training, rng) -> array
# xs are the resolved input arrays in node-input order.

def _mk_conv(node):
    attrs = node.attrs

    def fn(xs, training, rng):
        x, w = xs[0], xs[1]
        spatial = x.ndim - 2
        strides = tuple(int(v) for v in attrs.get("strides",
                                                  [1] * spatial))
        dil = tuple(int(v) for v in attrs.get("dilations",
                                              [1] * spatial))
        groups = int(attrs.get("group", 1))
        padding = _auto_pad_or_pads(attrs, spatial)
        dn = jax.lax.conv_dimension_numbers(x.shape, w.shape,
                                            _conv_dn(spatial))
        y = jax.lax.conv_general_dilated(
            x, w, strides, padding, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups)
        if len(xs) > 2 and xs[2] is not None:
            y = y + xs[2].reshape((1, -1) + (1,) * spatial)
        return y

    return fn


def _mk_pool(node, mode):
    attrs = node.attrs
    if int(attrs.get("ceil_mode", 0)):
        raise UnsupportedOnnxOp("pooling with ceil_mode=1")

    def fn(xs, training, rng):
        x = xs[0]
        spatial = x.ndim - 2
        if mode in ("gmax", "gavg"):
            axes = tuple(range(2, x.ndim))
            red = jnp.max if mode == "gmax" else jnp.mean
            return red(x, axis=axes, keepdims=True)
        ks = tuple(int(v) for v in attrs["kernel_shape"])
        strides = tuple(int(v) for v in attrs.get("strides",
                                                  [1] * spatial))
        resolved = _auto_pad_or_pads(attrs, spatial)
        if resolved == "SAME":
            # lax string padding applies to ALL dims; compute explicit
            # SAME_UPPER pads for the spatial dims only
            pads = []
            for i in range(spatial):
                out = -(-x.shape[2 + i] // strides[i])
                total = max(0, (out - 1) * strides[i] + ks[i]
                            - x.shape[2 + i])
                pads.append((total // 2, total - total // 2))
        else:
            pads = resolved
        window = (1, 1) + ks
        strd = (1, 1) + strides
        padding = [(0, 0), (0, 0)] + pads
        if mode == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                         strd, padding)
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strd,
                                       padding)
        if int(node.attrs.get("count_include_pad", 0)):
            return summed / float(np.prod(ks))
        counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                       window, strd, padding)
        return summed / counts

    return fn


def _mk_gemm(node):
    attrs = node.attrs

    def fn(xs, training, rng):
        a, b = xs[0], xs[1]
        if int(attrs.get("transA", 0)):
            a = a.T
        if int(attrs.get("transB", 0)):
            b = b.T
        y = float(attrs.get("alpha", 1.0)) * (a @ b)
        if len(xs) > 2:
            y = y + float(attrs.get("beta", 1.0)) * xs[2]
        return y

    return fn


def _mk_batchnorm(node):
    eps = float(node.attrs.get("epsilon", 1e-5))

    def fn(xs, training, rng):
        x, gamma, beta, mean, var = xs[:5]
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mean.reshape(shape))
                / jnp.sqrt(var.reshape(shape) + eps)
                * gamma.reshape(shape) + beta.reshape(shape))

    return fn


def _axis_attr(node, default=1):
    return int(node.attrs.get("axis", default))


def _mk_elementwise(f):
    return lambda node: (lambda xs, training, rng: f(*xs))


def _mk_reduce(red):
    def make(node):
        axes = node.attrs.get("axes")
        keep = bool(int(node.attrs.get("keepdims", 1)))

        def fn(xs, training, rng):
            ax = tuple(axes) if axes else None
            if ax is None and len(xs) > 1 and xs[1] is not None:
                # opset>=13 passes axes as a (constant) second input
                ax = tuple(int(a) for a in np.asarray(xs[1]))
            return red(xs[0], axis=ax, keepdims=keep)

        return fn

    return make


def _mk_dropout(node):
    ratio = float(node.attrs.get("ratio", 0.5))

    def fn(xs, training, rng):
        x = xs[0]
        if not training or rng is None or ratio <= 0:
            return x
        keep = jax.random.bernoulli(rng, 1.0 - ratio, x.shape)
        return jnp.where(keep, x / (1.0 - ratio), 0.0)

    return fn


_MAPPERS: Dict[str, Callable] = {
    "Conv": _mk_conv,
    "MaxPool": lambda n: _mk_pool(n, "max"),
    "AveragePool": lambda n: _mk_pool(n, "avg"),
    "GlobalMaxPool": lambda n: _mk_pool(n, "gmax"),
    "GlobalAveragePool": lambda n: _mk_pool(n, "gavg"),
    "Gemm": _mk_gemm,
    "BatchNormalization": _mk_batchnorm,
    "Dropout": _mk_dropout,
    "MatMul": _mk_elementwise(jnp.matmul),
    "Add": _mk_elementwise(jnp.add),
    "Sub": _mk_elementwise(jnp.subtract),
    "Mul": _mk_elementwise(jnp.multiply),
    "Div": _mk_elementwise(jnp.divide),
    "Pow": _mk_elementwise(jnp.power),
    "Neg": _mk_elementwise(jnp.negative),
    "Abs": _mk_elementwise(jnp.abs),
    "Exp": _mk_elementwise(jnp.exp),
    "Log": _mk_elementwise(jnp.log),
    "Sqrt": _mk_elementwise(jnp.sqrt),
    "Relu": _mk_elementwise(jax.nn.relu),
    "Sigmoid": _mk_elementwise(jax.nn.sigmoid),
    "Tanh": _mk_elementwise(jnp.tanh),
    "Softplus": _mk_elementwise(jax.nn.softplus),
    "Identity": _mk_elementwise(lambda x: x),
    "Sum": _mk_elementwise(lambda *xs: sum(xs[1:], xs[0])),
    "Max": _mk_elementwise(
        lambda *xs: jnp.stack(jnp.broadcast_arrays(*xs)).max(0)),
    "Min": _mk_elementwise(
        lambda *xs: jnp.stack(jnp.broadcast_arrays(*xs)).min(0)),
    "Erf": _mk_elementwise(jax.scipy.special.erf),
    "Reciprocal": _mk_elementwise(lambda x: 1.0 / x),
    "Floor": _mk_elementwise(jnp.floor),
    "Ceil": _mk_elementwise(jnp.ceil),
}


def _register_structured():
    def softmax(node):
        ax = _axis_attr(node, -1)
        return lambda xs, t, r: jax.nn.softmax(xs[0], axis=ax)

    def logsoftmax(node):
        ax = _axis_attr(node, -1)
        return lambda xs, t, r: jax.nn.log_softmax(xs[0], axis=ax)

    def leaky(node):
        alpha = float(node.attrs.get("alpha", 0.01))
        return lambda xs, t, r: jax.nn.leaky_relu(xs[0], alpha)

    def elu(node):
        alpha = float(node.attrs.get("alpha", 1.0))
        return lambda xs, t, r: jax.nn.elu(xs[0], alpha)

    def hard_sigmoid(node):
        alpha = float(node.attrs.get("alpha", 0.2))
        beta = float(node.attrs.get("beta", 0.5))
        return lambda xs, t, r: jnp.clip(alpha * xs[0] + beta, 0.0, 1.0)

    def prelu(node):
        return lambda xs, t, r: jnp.where(xs[0] >= 0, xs[0],
                                          xs[1] * xs[0])

    def clip(node):
        lo = node.attrs.get("min")
        hi = node.attrs.get("max")

        def fn(xs, t, r):
            # omitted optional inputs arrive as None placeholders, so
            # min/max keep their positions
            low = xs[1] if len(xs) > 1 and xs[1] is not None else lo
            high = xs[2] if len(xs) > 2 and xs[2] is not None else hi
            return jnp.clip(xs[0], low, high)

        return fn

    def flatten(node):
        ax = _axis_attr(node, 1)
        return lambda xs, t, r: xs[0].reshape(
            (int(np.prod(xs[0].shape[:ax])) if ax else 1, -1))

    def reshape(node):
        def fn(xs, t, r):
            shape = tuple(int(s) for s in np.asarray(xs[1]))
            shape = tuple(xs[0].shape[i] if s == 0 else s
                          for i, s in enumerate(shape))
            return xs[0].reshape(shape)

        return fn

    def transpose(node):
        perm = node.attrs.get("perm")
        return lambda xs, t, r: jnp.transpose(
            xs[0], tuple(perm) if perm else None)

    def concat(node):
        ax = _axis_attr(node)
        return lambda xs, t, r: jnp.concatenate(xs, axis=ax)

    def squeeze(node):
        axes = node.attrs.get("axes")

        def fn(xs, t, r):
            ax = axes if axes is not None else (
                tuple(int(a) for a in np.asarray(xs[1]))
                if len(xs) > 1 and xs[1] is not None else None)
            return jnp.squeeze(xs[0], axis=tuple(ax) if ax else None)

        return fn

    def unsqueeze(node):
        axes = node.attrs.get("axes")

        def fn(xs, t, r):
            ax = axes if axes is not None else \
                [int(a) for a in np.asarray(xs[1])]
            y = xs[0]
            for a in sorted(int(v) for v in ax):
                y = jnp.expand_dims(y, a)
            return y

        return fn

    def gather(node):
        ax = _axis_attr(node, 0)
        return lambda xs, t, r: jnp.take(xs[0], xs[1].astype(jnp.int32),
                                         axis=ax)

    def constant(node):
        t = node.attrs.get("value")
        arr = jnp.asarray(t.array if isinstance(t, proto.Tensor) else t)
        return lambda xs, tr, r: arr

    def pad(node):
        mode = node.attrs.get("mode", b"constant")
        if isinstance(mode, bytes):
            mode = mode.decode()
        pads_attr = node.attrs.get("pads")

        def fn(xs, t, r):
            pads = pads_attr if pads_attr is not None else \
                [int(p) for p in np.asarray(xs[1])]
            n = xs[0].ndim
            widths = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
            value = (float(np.asarray(xs[2]))
                     if len(xs) > 2 and xs[2] is not None else 0.0)
            if mode == "constant":
                return jnp.pad(xs[0], widths, constant_values=value)
            return jnp.pad(xs[0], widths,
                           mode="edge" if mode == "edge" else "reflect")

        return fn

    def lrn(node):
        alpha = float(node.attrs.get("alpha", 1e-4))
        beta = float(node.attrs.get("beta", 0.75))
        bias = float(node.attrs.get("bias", 1.0))
        size = int(node.attrs["size"])

        def fn(xs, t, r):
            x = xs[0]
            sq = x * x
            half = size // 2
            pad = [(0, 0), (half, size - 1 - half)] + \
                [(0, 0)] * (x.ndim - 2)
            acc = jax.lax.reduce_window(
                jnp.pad(sq, pad), 0.0, jax.lax.add,
                (1, size) + (1,) * (x.ndim - 2),
                (1,) * x.ndim, "VALID")
            return x / jnp.power(bias + alpha / size * acc, beta)

        return fn

    def cast(node):
        to = int(node.attrs["to"])
        dtype = proto._DTYPES.get(to, np.float32)
        return lambda xs, t, r: xs[0].astype(dtype)

    def shape_op(node):
        return lambda xs, t, r: jnp.asarray(xs[0].shape, jnp.int64)

    def slice_op(node):
        # opset-10+ takes starts/ends/axes/steps as inputs; opset-1 as
        # attrs.  All must be static (constant-folded) — true for every
        # exporter we target.
        a_starts = node.attrs.get("starts")
        a_ends = node.attrs.get("ends")
        a_axes = node.attrs.get("axes")

        def fn(xs, t, r):
            x = xs[0]
            starts = (a_starts if a_starts is not None
                      else [int(v) for v in np.asarray(xs[1])])
            ends = (a_ends if a_ends is not None
                    else [int(v) for v in np.asarray(xs[2])])
            axes = a_axes
            if axes is None and len(xs) > 3 and xs[3] is not None:
                axes = [int(v) for v in np.asarray(xs[3])]
            if axes is None:
                axes = list(range(len(starts)))
            steps = ([int(v) for v in np.asarray(xs[4])]
                     if len(xs) > 4 and xs[4] is not None
                     else [1] * len(starts))
            idx = [slice(None)] * x.ndim
            for s, e, a, st in zip(starts, ends, axes, steps):
                idx[int(a)] = slice(int(s), int(e), int(st))
            return x[tuple(idx)]

        return fn

    def split_op(node):
        ax = _axis_attr(node, 0)
        a_split = node.attrs.get("split")

        def fn(xs, t, r):
            x = xs[0]
            sizes = (a_split if a_split is not None
                     else ([int(v) for v in np.asarray(xs[1])]
                           if len(xs) > 1 and xs[1] is not None else None))
            if sizes is None:
                n = len(node.outputs)
                return tuple(jnp.split(x, n, axis=ax))
            bounds = np.cumsum(sizes)[:-1].tolist()
            return tuple(jnp.split(x, bounds, axis=ax))

        return fn

    def expand(node):
        def fn(xs, t, r):
            shape = [int(s) for s in np.asarray(xs[1])]
            return jnp.broadcast_to(
                xs[0], np.broadcast_shapes(tuple(xs[0].shape),
                                           tuple(shape)))

        return fn

    def where(node):
        return lambda xs, t, r: jnp.where(xs[0].astype(bool), xs[1], xs[2])

    def _mk_arg(fn):
        def build(node):
            ax = _axis_attr(node, 0)
            keep = int(node.attrs.get("keepdims", 1))

            def f(xs, t, r):
                y = fn(xs[0], axis=ax).astype(jnp.int64)
                return jnp.expand_dims(y, ax) if keep else y

            return f
        return build

    def conv_transpose(node):
        strides = tuple(int(s) for s in node.attrs.get("strides", (1, 1)))
        pads = node.attrs.get("pads")
        group = int(node.attrs.get("group", 1))
        if group != 1:
            raise UnsupportedOnnxOp("ConvTranspose group != 1")
        out_pad = node.attrs.get("output_padding")
        if out_pad is not None and any(int(p) for p in out_pad):
            raise UnsupportedOnnxOp("ConvTranspose output_padding != 0")
        dil = node.attrs.get("dilations")
        if dil is not None and any(int(d) != 1 for d in dil):
            raise UnsupportedOnnxOp("ConvTranspose dilations != 1")
        if node.attrs.get("output_shape") is not None:
            raise UnsupportedOnnxOp("ConvTranspose explicit output_shape")
        ap = node.attrs.get("auto_pad", b"NOTSET")
        ap = ap.decode() if isinstance(ap, bytes) else ap
        if ap not in ("NOTSET", ""):
            raise UnsupportedOnnxOp(f"ConvTranspose auto_pad={ap}")

        def fn(xs, t, r):
            x, w = xs[0], xs[1]          # x NCHW, w (Cin, Cout/g, kH, kW)
            nd = x.ndim - 2
            st = strides if len(strides) == nd else (1,) * nd
            # canonical fractionally-strided conv: flip the kernel
            # spatially, swap to OIHW, dilate the INPUT by the stride,
            # and pad with k-1-p (ONNX deconv pads remove output)
            w_f = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
            w_f = jnp.swapaxes(w_f, 0, 1)            # (Cout, Cin, k...)
            dn = jax.lax.conv_dimension_numbers(
                x.shape, w_f.shape,
                ("NCHW", "OIHW", "NCHW") if nd == 2 else
                ("NCW", "OIW", "NCW"))
            if pads is not None:
                onnx_pad = [(int(pads[i]), int(pads[i + nd]))
                            for i in range(nd)]
            else:
                onnx_pad = [(0, 0)] * nd
            padding = [(w.shape[2 + i] - 1 - onnx_pad[i][0],
                        w.shape[2 + i] - 1 - onnx_pad[i][1])
                       for i in range(nd)]
            y = jax.lax.conv_general_dilated(
                x, w_f, (1,) * nd, padding, lhs_dilation=st,
                dimension_numbers=dn)
            if len(xs) > 2 and xs[2] is not None:
                y = y + xs[2].reshape((1, -1) + (1,) * nd)
            return y

        return fn

    _MAPPERS.update({
        "Softmax": softmax, "LogSoftmax": logsoftmax,
        "LeakyRelu": leaky, "Elu": elu, "HardSigmoid": hard_sigmoid,
        "PRelu": prelu, "Clip": clip, "Flatten": flatten,
        "Reshape": reshape, "Transpose": transpose, "Concat": concat,
        "Squeeze": squeeze, "Unsqueeze": unsqueeze, "Gather": gather,
        "Constant": constant, "Pad": pad, "LRN": lrn, "Cast": cast,
        "Shape": shape_op,
        "ReduceMean": _mk_reduce(jnp.mean), "ReduceSum": _mk_reduce(jnp.sum),
        "ReduceMax": _mk_reduce(jnp.max), "ReduceMin": _mk_reduce(jnp.min),
        "Slice": slice_op, "Split": split_op, "Expand": expand,
        "Where": where, "ArgMax": _mk_arg(jnp.argmax),
        "ArgMin": _mk_arg(jnp.argmin), "ConvTranspose": conv_transpose,
    })


_register_structured()


def _resolve_inputs(env: Dict[str, Any], names: Sequence[str]) -> List:
    """Resolve a node's inputs: trailing omitted optionals ("") are
    dropped, interior ones become None PLACEHOLDERS so later inputs keep
    their spec positions (e.g. Clip with min omitted but max given)."""
    names = list(names)
    while names and not names[-1]:
        names.pop()
    return [env[i] if i else None for i in names]


class OnnxProgram:
    """Topologically ordered op list over a name-keyed tensor env.

    Follows the FunctionModel program protocol (tfpark/model.py): exposes
    ``params``/``state`` and ``call(params, state, *inputs)`` so the
    loaded graph trains/predicts under the standard Estimator.
    Initializers ARE the params (a flat {tensor_name: array} pytree).
    """

    def __init__(self, model: proto.Model):
        g = model.graph
        self.opset = model.opset
        self.params = {t.name: jnp.asarray(t.array)
                       for t in g.initializers
                       if np.issubdtype(t.array.dtype, np.floating)}
        self.consts = {t.name: jnp.asarray(t.array)
                       for t in g.initializers
                       if not np.issubdtype(t.array.dtype, np.floating)}
        self.state: Dict = {}
        init_names = set(self.params) | set(self.consts)
        self.input_names = [vi.name for vi in g.inputs
                            if vi.name not in init_names]
        self.output_names = [vi.name for vi in g.outputs]
        self.nodes = []
        for n in g.nodes:
            if n.op_type not in _MAPPERS:
                raise UnsupportedOnnxOp(
                    f"ONNX op {n.op_type!r} (supported: "
                    f"{sorted(_MAPPERS)})")
            self.nodes.append((n, _MAPPERS[n.op_type](n)))

    def call(self, params, state, *inputs, training=False, rng=None):
        if len(inputs) != len(self.input_names):
            raise ValueError(f"expected {len(self.input_names)} inputs "
                             f"({self.input_names}), got {len(inputs)}")
        env: Dict[str, Any] = dict(self.consts)
        env.update(params)
        env.update(zip(self.input_names, inputs))
        rngs = (jax.random.split(rng, max(1, len(self.nodes)))
                if rng is not None else [None] * len(self.nodes))
        for (n, fn), r in zip(self.nodes, rngs):
            xs = _resolve_inputs(env, n.inputs)
            out = fn(xs, training, r)
            if isinstance(out, tuple) and len(n.outputs) == len(out):
                # true multi-output op (Split): one value per output —
                # including the degenerate single-output Split, whose
                # length-1 tuple must unwrap to the array
                for name, val in zip(n.outputs, out):
                    if name:
                        env[name] = val
            else:
                env[n.outputs[0]] = out
                for extra in n.outputs[1:]:
                    if extra:        # e.g. Dropout mask output — unused
                        env[extra] = out
        outs = [env[o] for o in self.output_names]
        return (outs[0] if len(outs) == 1 else outs), state


def load_onnx_bytes(buf: bytes) -> OnnxProgram:
    return OnnxProgram(proto.decode_model(buf))


def load_onnx(path: str) -> OnnxProgram:
    """Load a ``.onnx`` file into a trainable/predictable program
    (reference onnx_loader.py entry point)."""
    with open(path, "rb") as f:
        return load_onnx_bytes(f.read())


def to_model(program: OnnxProgram):
    """Wrap as a KerasNet (compile/fit/evaluate/predict surface)."""
    from analytics_zoo_tpu.tfpark.model import FunctionModel

    return FunctionModel(program)
