"""ONNX import (reference pyzoo/zoo/pipeline/api/onnx/) — a pure-python
wire-format decoder (proto.py) + op lowering to jax/lax (loader.py), no
``onnx`` package dependency."""

from analytics_zoo_tpu.onnx.loader import (OnnxProgram, UnsupportedOnnxOp,
                                           load_onnx, load_onnx_bytes,
                                           to_model)

__all__ = ["load_onnx", "load_onnx_bytes", "to_model", "OnnxProgram",
           "UnsupportedOnnxOp"]
