"""INT8 quantization ops — the compute path (MXU int8 matmuls).

Reference capability: OpenVINO int8 calibration gets ~2x inference
speedup (InferenceModel.scala:443, wp-bigdl.md:192 Fig. 10).  TPU-native
redesign (SURVEY §2.3): no external runtime — an AQT-style post-training
scheme where
- weights are per-output-channel symmetric int8 (quantize_tensor),
- activations are quantized per-tensor, either dynamically (abs-max of
  the live batch) or statically from a Calibrator's recorded ranges,
- the matmul runs int8 x int8 with int32 accumulation
  (``preferred_element_type``) — the MXU's native high-rate path —
  and one fused f32 rescale at the end.

``quantize_program`` applies this to an ONNX program's Gemm/MatMul nodes,
giving a complete post-training-quantization pipeline for imported
models; ``int8_dot`` is the building block for custom layers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["quantize_tensor", "int8_dot", "Calibrator",
           "quantize_program", "quantize_feature_array",
           "dequantize_features"]


def quantize_feature_array(a: np.ndarray, dtype: str = "uint8"
                           ) -> Tuple[np.ndarray, np.float32, np.float32]:
    """Host-side per-array encode of a float feature array for the
    compressed device cache (data/streaming.py STREAM shards): returns
    ``(q, scale, zero)`` with ``a ≈ q * scale + zero``.

    ``uint8`` is affine (min/max over the shard — tight for bounded
    features like images/embeddings); ``int8`` is symmetric (abs-max,
    zero == 0 — matches the MXU-native convention of
    ``quantize_tensor``).  Scales are per-shard scalars so the decode
    is one fused multiply-add in the kernel (``dequantize_features``).
    """
    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.floating):
        raise TypeError(f"quantize_feature_array needs floats, got "
                        f"{a.dtype}")
    if dtype == "int8":
        amax = float(np.max(np.abs(a))) if a.size else 0.0
        scale = amax / 127.0 if amax > 0 else 1.0
        q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
        return q, np.float32(scale), np.float32(0.0)
    if dtype == "uint8":
        lo = float(a.min()) if a.size else 0.0
        hi = float(a.max()) if a.size else 0.0
        scale = (hi - lo) / 255.0 if hi > lo else 1.0
        q = np.clip(np.round((a - lo) / scale), 0, 255).astype(np.uint8)
        return q, np.float32(scale), np.float32(lo)
    raise ValueError(f"unknown feature cache dtype {dtype!r}; "
                     "known: uint8, int8")


def dequantize_features(q, scale, zero):
    """In-kernel decode of a ``quantize_feature_array`` shard slice:
    one fused multiply-add back to float32 (traced inside the jitted
    shard program, applied AFTER the minibatch gather so only gathered
    rows pay the decode)."""
    return q.astype(jnp.float32) * scale + zero


def quantize_tensor(w, axis: int = -1) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-channel int8: returns (int8 weights, f32 scales)
    with ``scale`` shaped to broadcast along ``axis``."""
    w = jnp.asarray(w)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis % w.ndim)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def int8_dot(x, w_q, w_scale, x_scale=None, weight_only: bool = False):
    """``x @ dequant(w_q)`` computed as an int8 x int8 MXU matmul.

    ``x_scale``: static per-tensor activation scale from calibration;
    None = dynamic (abs-max of the live batch — one extra reduction).
    Accumulation is int32 (``preferred_element_type``), rescale is one
    fused f32 multiply.

    ``weight_only=True`` keeps activations in float and routes through
    the fused dequantize-matmul (ops/dequant_matmul.py): weights stay
    int8 in HBM, tiles decode in-registers after the VMEM load — the
    serving path when ``serving_weight_dtype`` != float32, and the
    right choice when activation quantization error is unacceptable.
    """
    if weight_only:
        from analytics_zoo_tpu.ops.dequant_matmul import dequant_matmul

        return dequant_matmul(x, w_q, jnp.reshape(w_scale, (1, -1)))
    if x_scale is None:
        amax = jnp.max(jnp.abs(x))
        x_scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * x_scale * w_scale.reshape(
        (1,) * (acc.ndim - 1) + (-1,))


class Calibrator:
    """Records per-name activation ranges over representative batches
    (the role of OpenVINO's calibration dataset pass).

    ``observe(name, x)`` during calibration forwards; ``scale(name)``
    afterwards gives the static per-tensor scale (max |x| / 127, with an
    optional percentile clip to shed outliers).
    """

    def __init__(self, percentile: Optional[float] = 99.9):
        self.percentile = percentile
        self._maxes: Dict[str, List[float]] = {}

    def observe(self, name: str, x) -> None:
        x = np.abs(np.asarray(x))
        m = (np.percentile(x, self.percentile)
             if self.percentile is not None else x.max())
        self._maxes.setdefault(name, []).append(float(m))

    def names(self) -> List[str]:
        return sorted(self._maxes)

    def scale(self, name: str) -> float:
        if name not in self._maxes:
            raise KeyError(f"no calibration data for {name!r}; "
                           f"have: {self.names()}")
        amax = max(self._maxes[name])
        return amax / 127.0 if amax > 0 else 1.0

    def scales(self) -> Dict[str, float]:
        return {n: self.scale(n) for n in self._maxes}


# ---------------------------------------------------------------------------
# program-level post-training quantization (ONNX path)
# ---------------------------------------------------------------------------

class QuantizedProgram:
    """An OnnxProgram whose Gemm/MatMul nodes run int8 MXU matmuls.

    Weights of quantized nodes are stored int8 in ``qweights`` (params
    keeps only the non-quantized tensors — biases, norms, ...); with a
    calibrated ``act_scales`` dict the activation quantization is static,
    otherwise dynamic per batch.
    """

    _QUANT_OPS = ("Gemm", "MatMul")

    def __init__(self, program, act_scales: Optional[Dict[str, float]] =
                 None, min_size: int = 512):
        self.base = program
        self.act_scales = dict(act_scales or {})
        self.qweights: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
        self.quantized_nodes: List[str] = []
        # dispatch by NODE (index), not weight name: a weight shared with
        # a non-quantizable consumer must stay in params, and a skipped
        # (e.g. transposed) Gemm on a quantized weight must not silently
        # take the int8 path
        self._qnode_idx: set = set()
        consumers: Dict[str, int] = {}
        for n, _ in program.nodes:
            for i in n.inputs:
                if i:
                    consumers[i] = consumers.get(i, 0) + 1
        params = dict(program.params)
        for idx, (n, _) in enumerate(program.nodes):
            if n.op_type not in self._QUANT_OPS or len(n.inputs) < 2:
                continue
            wname = n.inputs[1]
            if wname not in params or params[wname].ndim != 2:
                continue
            if int(n.attrs.get("transA", 0)) or int(n.attrs.get("transB", 0)):
                continue                       # transposed Gemm: skip
            if consumers.get(wname, 0) != 1:
                continue                       # shared initializer: skip
            w = params[wname]
            if w.size < min_size:
                continue
            self.qweights[wname] = quantize_tensor(w, axis=-1)
            self.quantized_nodes.append(n.name or wname)
            self._qnode_idx.add(idx)
            del params[wname]
        self.params = params
        self.consts = program.consts
        self.state = dict(program.state)
        self.input_names = program.input_names
        self.output_names = program.output_names

    def call(self, params, state, *inputs, training=False, rng=None):
        from analytics_zoo_tpu.onnx.loader import _resolve_inputs

        env: Dict[str, Any] = dict(self.consts)
        env.update(params)
        env.update(zip(self.input_names, inputs))
        for idx, (n, fn) in enumerate(self.base.nodes):
            if idx in self._qnode_idx:
                wname = n.inputs[1]
                x = env[n.inputs[0]]
                w_q, w_scale = self.qweights[wname]
                key = n.name or wname
                y = int8_dot(x, w_q, w_scale.reshape(-1),
                             x_scale=self.act_scales.get(key))
                if n.op_type == "Gemm":
                    y = float(n.attrs.get("alpha", 1.0)) * y
                    if len(n.inputs) > 2 and n.inputs[2]:
                        y = y + float(n.attrs.get("beta", 1.0)) \
                            * env[n.inputs[2]]
                out = y
            else:
                out = fn(_resolve_inputs(env, n.inputs), training, rng)
            env[n.outputs[0]] = out
            for extra in n.outputs[1:]:
                if extra:
                    env[extra] = out
        outs = [env[o] for o in self.output_names]
        return (outs[0] if len(outs) == 1 else outs), state


def quantize_program(program, calibration_inputs: Optional[Sequence] = None,
                     percentile: Optional[float] = 99.9,
                     min_size: int = 512) -> QuantizedProgram:
    """Post-training quantization of an ONNX program.

    With ``calibration_inputs`` (a list of input-arg tuples), runs the
    fp32 program to record activation ranges at each quantizable matmul
    and bakes STATIC activation scales; without, activation quantization
    is dynamic.
    """
    from analytics_zoo_tpu.onnx.loader import _resolve_inputs

    act_scales: Optional[Dict[str, float]] = None
    if calibration_inputs is not None:
        cal = Calibrator(percentile=percentile)
        # activation name -> [node keys]: two matmuls sharing one input
        # each keep their own calibrated scale
        watch: Dict[str, List[str]] = {}
        for n, _ in program.nodes:
            if (n.op_type in QuantizedProgram._QUANT_OPS
                    and len(n.inputs) > 1 and n.inputs[1] in program.params
                    and program.params[n.inputs[1]].ndim == 2):
                watch.setdefault(n.inputs[0], []).append(
                    n.name or n.inputs[1])
        for args in calibration_inputs:
            args = args if isinstance(args, (list, tuple)) else (args,)
            env: Dict[str, Any] = dict(program.consts)
            env.update(program.params)
            env.update(zip(program.input_names,
                           [jnp.asarray(a) for a in args]))
            for n, fn in program.nodes:
                xs = _resolve_inputs(env, n.inputs)
                if n.inputs and n.inputs[0] in watch:
                    for key in watch[n.inputs[0]]:
                        cal.observe(key, xs[0])
                out = fn(xs, False, None)
                env[n.outputs[0]] = out
                for extra in n.outputs[1:]:
                    if extra:
                        env[extra] = out
        watched_keys = {k for keys in watch.values() for k in keys}
        act_scales = {name: cal.scale(name)
                      for name in watched_keys & set(cal._maxes)}
    return QuantizedProgram(program, act_scales=act_scales,
                            min_size=min_size)
