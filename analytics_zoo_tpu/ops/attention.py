"""Attention ops: blockwise (flash-style) attention with online softmax.

Reference capability: the O(L²) full attention inside
api/keras/layers/TransformerLayer.scala:56 and BERT.scala:66 (SURVEY §5.7:
the reference has NO long-context support — sequence length is bounded by
single-node memory).  This module is the TPU-native upgrade: attention is
computed **blockwise over KV chunks with an online softmax** (Rabe &
Staats 2021 / FlashAttention), so peak memory is O(L·block) instead of
O(L²), and the same code is the building block for ring attention
(parallel/sequence.py) where the KV scan runs over devices instead of
chunks.

Two paths, same math:
- ``blockwise_attention``: pure JAX ``lax.scan`` over KV blocks —
  differentiable (XLA derives the backward), runs on any backend.
- ``flash_attention`` (ops/flash_attention.py): hand-written Pallas TPU
  kernel for the forward hot loop; falls back to blockwise elsewhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def reference_attention(q, k, v, mask=None, causal: bool = False,
                        sm_scale: Optional[float] = None):
    """Naive O(L²) attention — the numerics oracle for tests.

    Shapes: q (B, H, Lq, D), k/v (B, H, Lk, D); mask broadcastable to
    (B, H, Lq, Lk) with 1 = attend.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((lq, lk), bool), k=lk - lq)
        logits = jnp.where(cm, logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    # zero fully-masked query rows so every dispatch path (blockwise, ring,
    # flash) agrees: they return 0 there, not the softmax of a constant row
    row_valid = jnp.any(logits > NEG_INF / 2, axis=-1, keepdims=True)
    w = jnp.where(row_valid, w, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def online_softmax_fold(m_prev, l_prev, acc, logits, values,
                        drop_mask=None, keep_prob: float = 1.0):
    """One fold of the online-softmax accumulation — the single source of
    this numerics, shared by blockwise attention (KV-chunk loop) and ring
    attention (device loop, parallel/sequence.py).

    ``logits`` (B,H,Lq,Kblk) must already carry all masking as NEG_INF.
    Returns the updated running (max, normalizer, weighted-value acc);
    fully-masked rows are kept finite-safe and contribute zero.

    ``drop_mask`` (same shape as logits) implements dropout on the softmax
    *probabilities*: the normalizer keeps the undropped sum, only the
    value accumulation is masked/rescaled — since w = p/l this is exactly
    dropout on the normalized weights, without materializing them.
    """
    m_cur = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe,
                              NEG_INF))
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    p_acc = p if drop_mask is None else (
        jnp.where(drop_mask, p, 0.0) / keep_prob)
    acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p_acc,
                                              values)
    m_out = m_safe + jnp.where(jnp.isfinite(m_new), 0.0, NEG_INF)
    return m_out, l_new, acc


def blockwise_attention(q, k, v, mask=None, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        block_size: int = 512,
                        dropout_rate: float = 0.0, dropout_rng=None):
    """Flash-style attention: scan over KV blocks with a running
    (max, sum, acc) online softmax.  O(Lq · block) memory.

    Differentiable end-to-end (the scan is unrolled by XLA's autodiff);
    wrap the call in ``jax.checkpoint`` to trade recompute for memory in
    very long sequences.

    ``dropout_rate`` > 0 (with ``dropout_rng``) applies dropout to the
    softmax probabilities — reference TransformerLayer/BERT attn_drop
    semantics — per KV block via ``fold_in``, keeping the memory bound.
    """
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / jnp.sqrt(d)
    bs = min(block_size, lk)
    nblocks = -(-lk // bs)  # ceil
    pad = nblocks * bs - lk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        padmask = jnp.arange(nblocks * bs) < lk        # (Lk',)
    else:
        padmask = None
    if mask is not None:
        mask = jnp.broadcast_to(mask.astype(bool), (b, h, lq, lk))
        if pad:
            mask = jnp.pad(mask, ((0, 0), (0, 0), (0, 0), (0, pad)))
        # (nblocks, B, H, Lq, bs) scan order
        mask_blocks = jnp.moveaxis(
            mask.reshape(b, h, lq, nblocks, bs), 3, 0)
    k_blocks = jnp.moveaxis(k.reshape(b, h, nblocks, bs, d), 2, 0)
    v_blocks = jnp.moveaxis(v.reshape(b, h, nblocks, bs, d), 2, 0)

    q_scaled = q * scale
    q_pos = jnp.arange(lq) + (lk - lq)  # causal offset for cross lengths

    def step(carry, inputs):
        m_prev, l_prev, acc = carry
        if mask is not None:
            kb, vb, mb, blk = inputs
        else:
            kb, vb, blk = inputs
        logits = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, kb)  # (B,H,Lq,bs)
        if padmask is not None:
            kpos_valid = lax.dynamic_slice_in_dim(padmask, blk * bs, bs)
            logits = jnp.where(kpos_valid[None, None, None, :], logits,
                               NEG_INF)
        if causal:
            kpos = blk * bs + jnp.arange(bs)
            cm = q_pos[:, None] >= kpos[None, :]
            logits = jnp.where(cm[None, None], logits, NEG_INF)
        if mask is not None:
            logits = jnp.where(mb, logits, NEG_INF)
        if dropout_rate > 0.0 and dropout_rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, blk),
                1.0 - dropout_rate, logits.shape)
            return online_softmax_fold(m_prev, l_prev, acc, logits, vb,
                                       drop_mask=keep,
                                       keep_prob=1.0 - dropout_rate), None
        return online_softmax_fold(m_prev, l_prev, acc, logits, vb), None

    # f32 carry: with bf16 inputs the running normalizer/accumulator must
    # not round across KV blocks (matches the Pallas kernel's f32 scratch)
    init = (jnp.full((b, h, lq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, lq), jnp.float32),
            jnp.zeros((b, h, lq, d), jnp.float32))
    blks = jnp.arange(nblocks)
    xs = ((k_blocks, v_blocks, mask_blocks, blks) if mask is not None
          else (k_blocks, v_blocks, blks))
    (m, l, acc), _ = lax.scan(step, init, xs)
    l = jnp.maximum(l, 1e-20)
    return (acc / l[..., None]).astype(q.dtype)


def dot_product_attention(q, k, v, mask=None, causal: bool = False,
                          sm_scale: Optional[float] = None,
                          block_size: int = 512,
                          use_flash: Optional[bool] = None,
                          dropout_rate: float = 0.0, dropout_rng=None):
    """Entry point used by the attention layers.

    Chooses the Pallas flash kernel on TPU when shapes allow, else the
    blockwise scan.  ``use_flash`` forces the choice (tests).
    ``dropout_rate`` > 0 with a ``dropout_rng`` applies probability
    dropout (reference attn_drop semantics) via the blockwise path, which
    keeps the O(Lq · block) memory bound during training.
    """
    from analytics_zoo_tpu.ops import dispatch

    dropping = dropout_rate > 0.0 and dropout_rng is not None
    # r5 true-time routing: the hand-written kernel wins from L≈2048 up
    # (1.31× stock at 2048, 1.53× at 8192 fwd) but the XLA blockwise path
    # is faster below that (0.27 vs 0.35 ms at 1024) — kernel grid
    # overhead dominates short sequences
    path = dispatch.select_path(
        "flash_attention",
        shapes_ok=(mask is None and not dropping
                   and q.shape[-1] % 128 == 0 and q.shape[2] % 128 == 0
                   and k.shape[2] % 128 == 0),
        min_work_met=max(q.shape[2], k.shape[2]) >= 2048,
        force=(None if use_flash is None else
               (dispatch.PATH_PALLAS if use_flash
                else dispatch.PATH_REFERENCE)),
    )
    if path == dispatch.PATH_PALLAS:
        if mask is not None:
            raise ValueError("flash kernel does not take a mask; pass "
                             "use_flash=False (or None for auto dispatch)")
        if dropping:
            raise ValueError("flash kernel does not support attention "
                             "dropout; pass use_flash=False/None")
        from analytics_zoo_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    if not dropping and q.shape[2] * k.shape[2] <= 256 * 256:
        # tiny sequences: one fused softmax beats the scan
        return reference_attention(q, k, v, mask=mask, causal=causal,
                                   sm_scale=sm_scale)
    return blockwise_attention(q, k, v, mask=mask, causal=causal,
                               sm_scale=sm_scale, block_size=block_size,
                               dropout_rate=dropout_rate if dropping else 0.0,
                               dropout_rng=dropout_rng if dropping else None)
