"""Sequence-parallel ring attention: the L axis sharded over ICI.

Reference capability: **absent** (SURVEY §5.7 — the reference's
TransformerLayer/BERT were hard-bounded by single-node O(L²) attention).
``ops/flash_attention.py`` (PR 12) removed the O(L²) *memory* but still
needs the full K/V sequence resident on one chip, so per-chip HBM — not
the mesh — caps context length.  This module removes that bound: the
sequence axis is sharded over a mesh axis (``shard_map``), K/V shards
rotate neighbour-to-neighbour via ``jax.lax.ppermute`` (ICI ring), and
every hop streams the resident K/V block through the *existing* flash
kernel, folding each hop's (out, lse) into the running online-softmax
(m, l, acc) carry — ring attention (Liu et al.) is literally blockwise
attention whose KV loop runs over devices.  Max context becomes a
function of mesh size: per-chip peak attention memory is O(L/ways).

Schedule (forward, ``ways`` hops, double-buffered):

    hop i:   ppermute(K/V) for hop i+1 issued FIRST  ──┐ overlaps
             flash(q_local, K/V from shard (my-i)%n) ──┘ on ICI/MXU
             (m, l, acc) ← online-softmax merge of the hop's (out, lse)

Causal skip: with tail-padding the global order is shard-major, so the
block from source shard ``src=(my-i)%n`` lies wholly *below* the
diagonal when ``src < my`` (full compute, no mask), *on* it when
``src == my`` (hop 0 — intra-block causal mask), and wholly *above* it
when ``src > my`` — those hops are skipped entirely (``lax.cond``
pass-through; the ppermute still runs, keeping the ring in lock-step).

Backward (``jax.custom_vjp``, FlashAttention-2 recipe): the forward
saves per-shard (q, k, v, out, lse) only; the backward re-streams K/V
around the *reverse* ring (ppermute by −1) with (dk, dv) partial sums
riding along with their K/V block — after ``ways`` hops each grad shard
is home.  Per hop the existing Pallas backward kernels recompute the
probability tile from (q, k, global lse) — no (Lq, Lk) matrix and no
gathered KV ever materialize, in forward or backward.

Dispatch (``ops/dispatch.select_path``, counted in
``ops_kernel_selected_total{kernel=ring_attention,path}``):

- mesh routing — no mesh / no seq axis / 1-way mesh → single-device
  blockwise fallback (path "reference");
- min-length routing — below ``RING_MIN_LEN`` total tokens the ring's
  per-hop latency loses to single-chip flash, so "auto" stays local;
- ``ZooConfig.ring_attention`` knob — "auto"/"on"/"off" like
  ``fused_embedding``; "on" rings wherever a mesh allows, "off" pins
  the single-device path;
- ``force`` — explicit test/bench override; "interpret" runs the flash
  kernels under ``pallas_call(interpret=True)`` per hop, which is how
  the CPU tier proves kernel-path parity.

On CPU the auto path is the pure-JAX ring (same shard_map/ppermute
schedule, ``online_softmax_fold`` per hop) — tier-1 stays green with no
TPU in the loop.  Ragged L (not divisible by ``ways``) is tail-padded;
causal masking hides the pad keys from every real query, and the
non-causal ragged case routes to the pure-JAX hops, which mask global
key positions ``>= L`` explicitly (the kernel path rejects that combo).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.ops import dispatch
from analytics_zoo_tpu.ops.attention import (blockwise_attention,
                                             online_softmax_fold)

try:  # jax >= 0.8
    from jax import shard_map  # type: ignore
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

NEG_INF = -1e30

# Below this many total tokens the ring's ways-1 ppermute latencies cost
# more than they save: single-chip flash at L=2048/D=64 is ~11ms on v5e
# while one ICI round-trip alone is ~1μs/hop + per-hop kernel launch —
# the win only appears once per-chip K/V no longer fits VMEM-friendly
# tiles, i.e. multi-k contexts.  Same role as attention's 2048 floor.
RING_MIN_LEN = 4096


# ---------------------------------------------------------------------------
# per-shard helpers (run inside shard_map; all shapes are per-device)
# ---------------------------------------------------------------------------

def _vary_like(x, axis_name, ref):
    """Fresh accumulators must carry the same varying-axes type as the
    q-derived values (including a batch axis under sp x dp)."""
    # lazy: parallel.sequence imports ops.attention, so a top-level import
    # here would close a cycle through ops/__init__ during package init
    from analytics_zoo_tpu.parallel.sequence import mark_varying
    try:
        axes = tuple(jax.typeof(ref).vma | {axis_name})
    except (AttributeError, TypeError):
        axes = axis_name
    return mark_varying(x, axes)


def _hop_masks(i, src, lq, lk, causal, valid_len, total_len):
    """(lq, lk) bool mask for hop ``i`` of the pure-JAX path, or None.

    ``src`` may be traced (it depends on ``axis_index``); the mask is
    built lazily so fully-live hops pay nothing.
    """
    need_valid = valid_len < total_len
    need_causal = causal and i == 0
    if not (need_valid or need_causal):  # zoolint: disable=JG-TRACED-BRANCH(valid_len/total_len/causal/i are static python ints and bools — only src is ever traced)
        return None
    mask = jnp.ones((lq, lk), bool)
    if need_causal:  # zoolint: disable=JG-TRACED-BRANCH(static python bool — hop index and causal flag are trace-time constants)
        # hop 0 holds the diagonal block: local positions line up
        mask = mask & (jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :])
    if need_valid:  # zoolint: disable=JG-TRACED-BRANCH(static python bool — pad geometry is fixed at trace time)
        k_pos = src * lk + jnp.arange(lk)
        mask = mask & (k_pos < valid_len)[None, :]
    return mask


def _ref_hop_fwd(q, kc, vc, m, l, acc, scale, mask):
    """One pure-JAX hop: fold the resident K/V block into (m, l, acc)
    via the shared online-softmax fold."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale,
                        kc.astype(jnp.float32))
    if mask is not None:  # zoolint: disable=JG-TRACED-BRANCH(None-ness is static pytree structure decided per hop at trace time)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    return online_softmax_fold(m, l, acc, logits, vc)


def _kernel_hop_fwd(q, kc, vc, m, l, acc, scale, diag_causal, block_q,
                    block_k, interpret):
    """One flash-kernel hop: the Pallas forward emits this block's
    (out, lse); merging into the carry is the standard flash combine —
    the block contributes (m=lse, l=1, acc=out) in carry coordinates."""
    from analytics_zoo_tpu.ops.flash_attention import _flash_fwd

    o_blk, lse_blk = _flash_fwd(q, kc, vc, scale, diag_causal, block_q,
                                block_k, interpret, with_lse=True)
    m_new = jnp.maximum(m, lse_blk)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(lse_blk - m_new)
    l_new = l * alpha + beta
    acc_new = (acc * alpha[..., None]
               + o_blk.astype(jnp.float32) * beta[..., None])
    return m_new, l_new, acc_new


def _ring_fwd_impl(q, k, v, axis_name, ways, causal, scale, block_q,
                   block_k, kernel, valid_len):
    """Forward ring over the shard's ``ways`` hops.  Returns (out, lse)
    — lse is the backward's residual (FlashAttention-2)."""
    my = lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    total = ways * lk
    interpret = kernel == dispatch.PATH_INTERPRET
    use_kernel = kernel in (dispatch.PATH_PALLAS, dispatch.PATH_INTERPRET)

    vary = functools.partial(_vary_like, axis_name=axis_name, ref=q)
    m = vary(jnp.full((b, h, lq), NEG_INF, jnp.float32))
    l = vary(jnp.zeros((b, h, lq), jnp.float32))
    acc = vary(jnp.zeros((b, h, lq, d), jnp.float32))

    perm = [(j, (j + 1) % ways) for j in range(ways)]
    kc, vc = k, v
    for i in range(ways):
        # double buffer: issue hop i+1's ppermute BEFORE hop i's compute
        # so the neighbour exchange overlaps the flash kernel on ICI
        if i + 1 < ways:
            kn = lax.ppermute(kc, axis_name, perm)
            vn = lax.ppermute(vc, axis_name, perm)
        src = (my - i) % ways  # origin shard of the resident block

        if use_kernel:
            def fold(args, _diag=(causal and i == 0)):
                qa, ka, va, ma, la, aa = args
                return _kernel_hop_fwd(qa, ka, va, ma, la, aa, scale,
                                       _diag, block_q, block_k, interpret)
        else:
            def fold(args, _i=i, _src=src):
                qa, ka, va, ma, la, aa = args
                mask = _hop_masks(_i, _src, lq, lk, causal, valid_len,
                                  total)
                return _ref_hop_fwd(qa, ka, va, ma, la, aa, scale, mask)

        if causal and i > 0:
            # src > my ⟺ the whole block sits above the diagonal —
            # skip the compute entirely; carry passes through unchanged
            m, l, acc = lax.cond(my >= i, fold,
                                 lambda args: (args[3], args[4], args[5]),
                                 (q, kc, vc, m, l, acc))
        else:
            m, l, acc = fold((q, kc, vc, m, l, acc))
        if i + 1 < ways:
            kc, vc = kn, vn

    l_safe = jnp.maximum(l, 1e-20)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return out, lse


def _ref_hop_bwd(q, kc, vc, do, out_lse_delta, scale, mask):
    """Pure-JAX hop of the FlashAttention-2 backward: probabilities
    recomputed from (q, k, global lse); returns the hop's partial
    (dq, dk, dv) contributions."""
    lse, delta = out_lse_delta
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale,
                   kc.astype(jnp.float32))
    if mask is not None:  # zoolint: disable=JG-TRACED-BRANCH(None-ness is static pytree structure decided per hop at trace time)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vc.astype(jnp.float32))
    ds = p * (dp - delta[..., None])
    dq = scale * jnp.einsum("bhqk,bhkd->bhqd", ds, kc.astype(jnp.float32))
    dk = scale * jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _ring_bwd_impl(axis_name, ways, causal, scale, block_q, block_k,
                   kernel, valid_len, res, g):
    """Backward ring: K/V re-stream around the REVERSE ring with their
    (dk, dv) partial sums riding along; after ``ways`` rotations every
    grad shard is back on its home device."""
    q, k, v, out, lse = res
    my = lax.axis_index(axis_name)
    b, h, lq, d = q.shape
    lk = k.shape[2]
    total = ways * lk
    interpret = kernel == dispatch.PATH_INTERPRET
    use_kernel = kernel in (dispatch.PATH_PALLAS, dispatch.PATH_INTERPRET)

    # delta_i = rowsum(dO_i * O_i) — global because out/lse are the
    # full-softmax forward results (same role as in _flash_bwd)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)

    vary = functools.partial(_vary_like, axis_name=axis_name, ref=q)
    dq = vary(jnp.zeros((b, h, lq, d), jnp.float32))
    dk_c = vary(jnp.zeros((b, h, lk, d), jnp.float32))
    dv_c = vary(jnp.zeros((b, h, lk, d), jnp.float32))

    perm = [(j, (j - 1) % ways) for j in range(ways)]
    kc, vc = k, v
    for i in range(ways):
        src = (my + i) % ways  # reverse ring: +i after i rotations
        if i + 1 < ways:
            kn = lax.ppermute(kc, axis_name, perm)
            vn = lax.ppermute(vc, axis_name, perm)

        if use_kernel:
            def hop(args, _diag=(causal and i == 0)):
                qa, ka, va, dqa, dka, dva = args
                from analytics_zoo_tpu.ops.flash_attention import _flash_bwd

                dq_h, dk_h, dv_h = _flash_bwd(qa, ka, va, out, lse, g,
                                              scale, _diag, block_q,
                                              block_k, interpret)
                return (dqa + dq_h, dka + dk_h.astype(jnp.float32),
                        dva + dv_h.astype(jnp.float32))
        else:
            def hop(args, _i=i, _src=src):
                qa, ka, va, dqa, dka, dva = args
                mask = _hop_masks(_i, _src, lq, lk, causal, valid_len,
                                  total)
                dq_h, dk_h, dv_h = _ref_hop_bwd(qa, ka, va, g,
                                                (lse, delta), scale, mask)
                return dqa + dq_h, dka + dk_h, dva + dv_h

        if causal and i > 0:
            # reverse ring: the resident block wrapped (src < my) iff
            # my + i >= ways — only those hops are below the diagonal
            dq, dk_c, dv_c = lax.cond(
                my + i >= ways, hop,
                lambda args: (args[3], args[4], args[5]),
                (q, kc, vc, dq, dk_c, dv_c))
        else:
            dq, dk_c, dv_c = hop((q, kc, vc, dq, dk_c, dv_c))

        # the grads travel WITH their block: ways rotations total bring
        # each (dk, dv) shard home (k/v themselves are done after the
        # last fold and need no final hop)
        dk_c = lax.ppermute(dk_c, axis_name, perm)
        dv_c = lax.ppermute(dv_c, axis_name, perm)
        if i + 1 < ways:
            kc, vc = kn, vn

    return (dq.astype(q.dtype), dk_c.astype(k.dtype),
            dv_c.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9,
                                                    10))
def _ring_shard(q, k, v, axis_name, ways, causal, scale, block_q, block_k,
                kernel, valid_len):
    """Per-shard ring attention (runs inside shard_map).  The custom_vjp
    sits at the shard level so the backward can re-stream K/V instead of
    saving ``ways`` activations per hop."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, ways, causal, scale,
                            block_q, block_k, kernel, valid_len)
    return out


def _ring_shard_fwd(q, k, v, axis_name, ways, causal, scale, block_q,
                    block_k, kernel, valid_len):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, ways, causal, scale,
                              block_q, block_k, kernel, valid_len)
    return out, (q, k, v, out, lse)


def _ring_shard_bwd(axis_name, ways, causal, scale, block_q, block_k,
                    kernel, valid_len, res, g):
    return _ring_bwd_impl(axis_name, ways, causal, scale, block_q,
                          block_k, kernel, valid_len, res, g)


_ring_shard.defvjp(_ring_shard_fwd, _ring_shard_bwd)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                   axis: str = "seq", batch_axis: Optional[str] = None,
                   causal: bool = False, sm_scale: Optional[float] = None,
                   block_q: int = 256, block_k: int = 256,
                   knob: Optional[str] = None,
                   force: Optional[str] = None):
    """Self-attention with the sequence axis sharded over ``mesh[axis]``.

    Shapes: q/k/v (B, H, L, D) — *global* arrays; the op shard_maps them
    over ``axis`` (and optionally ``batch_axis`` on dim 0 for the sp x dp
    composition).  Routing is the counted dispatch contract: without a
    usable mesh (or below ``RING_MIN_LEN``, or knob "off") the call is a
    single-device blockwise fallback; with one, K/V stream around the
    ring and the per-hop compute runs the flash kernel (TPU), its
    interpreter (``force="interpret"``, CPU tier) or the pure-JAX fold.
    """
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} vs {v.shape}")
    b, h, l, d = q.shape
    if k.shape[2] != l:
        raise ValueError(
            "ring attention is self-attention only: q and kv shards must "
            f"rotate together (Lq={l}, Lk={k.shape[2]})")
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)

    ways = 0
    if mesh is not None and axis in getattr(mesh, "shape", {}):
        ways = int(mesh.shape[axis])
    ring_ok = ways > 1 and l >= ways
    pad = (-l) % ways if ring_ok else 0
    kernel_ok = ring_ok and (pad == 0 or causal)

    if force in (dispatch.PATH_PALLAS, dispatch.PATH_INTERPRET) \
            and not kernel_ok:
        raise ValueError(
            "ring_attention kernel path needs a mesh with a >1-way "
            f"'{axis}' axis and L%ways==0 (or causal=True); got "
            f"L={l}, ways={ways}, causal={causal}")
    if knob is None:
        knob = dispatch.config_knob("ring_attention", "auto")

    path = dispatch.select_path("ring_attention", shapes_ok=kernel_ok,
                                min_work_met=l >= RING_MIN_LEN,
                                knob=knob, force=force)

    use_ring = (ring_ok and knob != "off"
                and (force is not None or knob == "on"
                     or l >= RING_MIN_LEN))
    if not use_ring:
        return blockwise_attention(q, k, v, causal=causal,
                                   sm_scale=scale)

    if pad:
        padding = [(0, 0)] * 2 + [(0, pad)] + [(0, 0)]
        q = jnp.pad(q, padding)
        k = jnp.pad(k, padding)
        v = jnp.pad(v, padding)

    spec = P(batch_axis, None, axis, None)
    shard_fn = lambda qs, ks, vs: _ring_shard(
        qs, ks, vs, axis, ways, causal, scale, block_q, block_k, path, l)
    sm_kw = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    if path in (dispatch.PATH_PALLAS, dispatch.PATH_INTERPRET):
        # pallas_call has no replication rule; the kernel hops are
        # verified element-exact against the pure-JAX ring by tests
        sm_kw["check_rep"] = False
    try:
        fn = shard_map(shard_fn, **sm_kw)
    except TypeError:  # pragma: no cover — newer jax renamed the flag
        sm_kw.pop("check_rep", None)
        sm_kw["check_vma"] = False
        fn = shard_map(shard_fn, **sm_kw)
    out = fn(q, k, v)
    return out[:, :, :l] if pad else out
