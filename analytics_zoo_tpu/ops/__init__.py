from analytics_zoo_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    dot_product_attention,
    reference_attention,
)
