from analytics_zoo_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    dot_product_attention,
    reference_attention,
)
from analytics_zoo_tpu.ops.flash_attention import flash_attention  # noqa: F401
from analytics_zoo_tpu.ops.quantization import (  # noqa: F401
    Calibrator,
    int8_dot,
    quantize_program,
    quantize_tensor,
)
