from analytics_zoo_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    dot_product_attention,
    reference_attention,
)
from analytics_zoo_tpu.ops.dequant_matmul import (  # noqa: F401
    dequant_matmul,
    dequant_matmul_reference,
    pack_int4,
    quantize_weights,
    unpack_int4,
)
from analytics_zoo_tpu.ops.dispatch import select_path  # noqa: F401
from analytics_zoo_tpu.ops.embedding_bag import (  # noqa: F401
    embedding_bag,
    embedding_bag_reference,
)
from analytics_zoo_tpu.ops.flash_attention import flash_attention  # noqa: F401
from analytics_zoo_tpu.ops.quantization import (  # noqa: F401
    Calibrator,
    int8_dot,
    quantize_program,
    quantize_tensor,
)
# last: ring_attention pulls in analytics_zoo_tpu.parallel, whose
# modules import the ops submodules above — keep them initialized first
from analytics_zoo_tpu.ops.ring_attention import (  # noqa: F401,E402
    RING_MIN_LEN,
    ring_attention,
)
