"""Shared backend routing for the Pallas kernels in ``ops/``.

Every hand-written kernel in this package (flash_attention, embedding_bag,
dequant_matmul) faces the same three-way choice:

- ``"pallas"``     — compiled Mosaic kernel; requires a TPU backend, the
  TPU pallas extensions importable, and kernel-specific shape limits met.
- ``"interpret"``  — the same kernel run under ``pallas_call(interpret=
  True)``; bit-faithful to the kernel's math on any backend, used by the
  CPU test tier and debugging (never auto-selected: it is orders of
  magnitude slower than XLA).
- ``"reference"``  — the pure-JAX oracle; XLA-compiled, differentiable,
  runs anywhere.

``select_path`` is the single predicate behind all three kernels instead
of three private copies, and records every decision in the
``ops_kernel_selected_total{kernel,path}`` counter so a serving or
training job can assert from metrics alone that the hot loop actually hit
the fused kernel (a silent fall-back to "reference" is a perf bug, not an
error).
"""

from __future__ import annotations

from typing import Optional

import jax

try:  # TPU-specific pallas extensions; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as _pltpu
except Exception:  # pragma: no cover
    _pltpu = None

PATH_PALLAS = "pallas"
PATH_INTERPRET = "interpret"
PATH_REFERENCE = "reference"
_PATHS = (PATH_PALLAS, PATH_INTERPRET, PATH_REFERENCE)


def pallas_available() -> bool:
    """True when the TPU pallas extensions imported (compiled or interpret)."""
    return _pltpu is not None


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def config_knob(name: str, default=None):
    """Read one knob off the global ZooConfig without *creating* a context.

    Kernels dispatch from inside layer forwards; forcing a mesh into
    existence there would be a side effect, so an uninitialised context
    just yields ``default``.
    """
    from analytics_zoo_tpu.core import context as _context

    ctx = _context._GLOBAL_CONTEXT
    if ctx is None:
        return default
    return getattr(ctx.config, name, default)


def record_selection(kernel: str, path: str) -> None:
    """Count one routing decision (trace-time: once per compilation)."""
    from analytics_zoo_tpu.observe import metrics as _metrics

    _metrics.count("ops_kernel_selected_total", 1,
                   flat=f"{kernel}/{path}", kernel=kernel, path=path)


def select_path(kernel: str, *, shapes_ok: bool = True,
                min_work_met: bool = True,
                knob: Optional[str] = None,
                force: Optional[str] = None) -> str:
    """The one backend-routing predicate shared by the ops/ kernels.

    ``shapes_ok``     kernel-specific hard limits (tile divisibility,
                      unsupported features like masks/dropout) — when
                      False the reference path is the only correct one.
    ``min_work_met``  the kernel only *wins* above some problem size;
                      below it the XLA path is faster (grid overhead).
    ``knob``          value of the governing config knob: "auto"/None
                      defers to the predicate, "off" pins the reference
                      path, "on" insists on the kernel wherever shapes
                      allow (overriding min_work_met).
    ``force``         explicit caller override (tests, benches); must be
                      one of the three path names.

    Returns the chosen path name and records it in
    ``ops_kernel_selected_total``.
    """
    if force is not None:
        if force not in _PATHS:
            raise ValueError(f"unknown kernel path {force!r}; "
                             f"expected one of {_PATHS}")
        path = force
    elif knob == "off" or not shapes_ok or not pallas_available():
        path = PATH_REFERENCE
    elif on_tpu() and (min_work_met or knob == "on"):
        path = PATH_PALLAS
    else:
        path = PATH_REFERENCE
    record_selection(kernel, path)
    return path
