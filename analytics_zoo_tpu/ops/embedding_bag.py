"""Pallas TPU fused embedding-bag kernel (multi-hot gather + combine).

Replaces the recommenders' hottest loop — the reference served NCF /
Wide&Deep through BigDL's MKL-DNN ``LookupTable`` + ``Sum`` pair
(SURVEY §L3); the XLA equivalent (``jnp.take`` + masked segment-sum)
materialises the per-index gathered rows as a (B, N, D) intermediate in
HBM: written once by the gather, read once by the reduction.  This kernel
fuses the two: per bag, the N table rows stream HBM→VMEM by async row DMA
(double-buffered across bags, so bag b+1's rows are in flight while bag b
reduces), the masked combine runs on the just-landed VMEM tile, and only
the (B, D) result ever touches HBM.  Ideal traffic drops from
``3·B·N·D`` words to ``B·N·D + B·D`` — neither the one-hot matrix nor
the gathered rows exist outside VMEM scratch.

Autodiff: ``jax.custom_vjp`` with a HAND-WRITTEN Pallas backward that
scatters dTable in the same blocked layout — grid over bag blocks, each
valid (bag, slot) doing a read-modify-write row DMA into the dTable
buffer (aliased in-place over a zeros input).  The RMW chain is fully
serialised per element, which keeps duplicate indices exact everywhere
(including interpret mode); a later revision can sort-and-combine
duplicates to recover DMA overlap.  ``ids`` take the documented
``float0`` zero cotangent.

Backends without pallas are routed to ``embedding_bag_reference`` by
``ops.dispatch.select_path`` (knob: ``ZooConfig.fused_embedding``);
off-TPU the kernel runs under ``interpret=True`` in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from analytics_zoo_tpu.ops import dispatch

COMBINERS = ("sum", "mean", "sqrtn")
# out block is (_BAG_BLOCK, D): 8 bags per grid step keeps the f32 sublane
# tile full while the SMEM ids block stays tiny (8·N int32 scalars)
_BAG_BLOCK = 8


def _check_args(table, ids, combiner):
    if table.ndim != 2:
        raise ValueError(f"table must be (vocab, dim), got {table.shape}")
    if ids.ndim != 2:
        raise ValueError(f"ids must be (bags, max_nnz), got {ids.shape}")
    if combiner not in COMBINERS:
        raise ValueError(f"combiner must be one of {COMBINERS}, "
                         f"got {combiner!r}")


def _bag_mask(ids, pad_id):
    """(B, N) f32 validity mask; ``pad_id=None`` means every slot counts."""
    if pad_id is None:
        return jnp.ones(ids.shape, jnp.float32)
    return (ids != pad_id).astype(jnp.float32)


def _combiner_scale(mask, combiner):
    """(B, 1) f32 per-bag weight applied after the masked sum."""
    if combiner == "sum":
        return jnp.ones((mask.shape[0], 1), jnp.float32)
    n = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    return 1.0 / (n if combiner == "mean" else jnp.sqrt(n))


def embedding_bag_reference(table, ids, combiner: str = "sum",
                            pad_id=0):
    """Pure-JAX oracle: gather + masked segment combine.

    Same math as the kernel, and the numerics source of truth for the
    parity suites.  XLA materialises the (B, N, D) gathered rows here —
    that intermediate is exactly what the fused kernel removes.
    """
    _check_args(table, ids, combiner)
    mask = _bag_mask(ids, pad_id)
    rows = jnp.take(table, ids.astype(jnp.int32), axis=0)    # (B, N, D)
    out = jnp.sum(rows.astype(jnp.float32) * mask[..., None], axis=1)
    out = out * _combiner_scale(mask, combiner)
    return out.astype(table.dtype)


# ---------------------------------------------------------------------------
# forward kernel


def _fwd_kernel(ids_smem, ids_vmem, table_ref, out_ref, rows, sem, *,
                combiner: str, pad_id, vocab: int):
    bb, n = ids_smem.shape

    def _row_copy(b, j, slot):
        idx = jnp.clip(ids_smem[b, j], 0, vocab - 1)  # jnp.take clip parity
        return pltpu.make_async_copy(table_ref.at[idx], rows.at[slot, j],
                                     sem.at[slot, j])

    def _start(b):
        for j in range(n):
            _row_copy(b, j, b % 2).start()

    def _wait(b):
        for j in range(n):
            _row_copy(b, j, b % 2).wait()

    _start(0)
    for b in range(bb):
        if b + 1 < bb:
            _start(b + 1)                      # overlap next bag's DMAs
        _wait(b)
        if pad_id is None:
            mask = jnp.ones((1, n), jnp.float32)
        else:
            mask = (ids_vmem[b, :] != pad_id).astype(jnp.float32)[None, :]
        # masked combine as a (1, N) x (N, D) contraction: one MXU pass,
        # no per-slot control flow
        acc = jax.lax.dot_general(
            mask, rows[b % 2].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (1, D)
        if combiner != "sum":
            cnt = jnp.maximum(jnp.sum(mask), 1.0)
            acc = acc / (cnt if combiner == "mean" else jnp.sqrt(cnt))
        out_ref[b, :] = acc[0].astype(out_ref.dtype)


def _pad_bags(ids, pad_fill):
    """Pad the bag dim to a multiple of the block; returns (ids', B)."""
    b = ids.shape[0]
    rem = (-b) % _BAG_BLOCK
    if rem:
        ids = jnp.pad(ids, ((0, rem), (0, 0)), constant_values=pad_fill)
    return ids, b


def _bag_forward(table, ids, combiner, pad_id, interpret):
    if pltpu is None:  # pragma: no cover
        raise ImportError(
            "pallas TPU support unavailable; embedding_bag should have "
            "been routed to embedding_bag_reference by ops.dispatch")
    vocab, dim = table.shape
    ids = ids.astype(jnp.int32)
    # padded bags gather row 0 and are sliced off; with a pad_id they are
    # also fully masked
    ids, b_real = _pad_bags(ids, pad_fill=pad_id if pad_id is not None
                            else 0)
    b_pad, n = ids.shape
    kernel = functools.partial(_fwd_kernel, combiner=combiner,
                               pad_id=pad_id, vocab=vocab)
    out = pl.pallas_call(
        kernel,
        grid=(b_pad // _BAG_BLOCK,),
        in_specs=[
            pl.BlockSpec((_BAG_BLOCK, n), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((_BAG_BLOCK, n), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # table stays in HBM
        ],
        out_specs=pl.BlockSpec((_BAG_BLOCK, dim), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b_pad, dim), table.dtype),
        scratch_shapes=[
            _VMEM((2, n, dim), table.dtype),        # double-buffered rows
            pltpu.SemaphoreType.DMA((2, n)),
        ],
        interpret=interpret,
    )(ids, ids, table)
    return out[:b_real]


# ---------------------------------------------------------------------------
# backward kernel: blocked dTable scatter


def _bwd_kernel(ids_smem, g_ref, _dtab_in, dtab_ref, row, sem, *,
                pad_id, vocab: int):
    bb, n = ids_smem.shape
    for b in range(bb):
        for j in range(n):
            raw = ids_smem[b, j]
            idx = jnp.clip(raw, 0, vocab - 1)
            live = (raw >= 0) if pad_id is None else (raw != pad_id)

            @pl.when(live)
            def _rmw(idx=idx, b=b):
                rd = pltpu.make_async_copy(dtab_ref.at[idx], row.at[0],
                                           sem.at[0])
                rd.start()
                rd.wait()
                row[0, :] = row[0, :] + g_ref[b, :]
                wr = pltpu.make_async_copy(row.at[0], dtab_ref.at[idx],
                                           sem.at[0])
                wr.start()
                wr.wait()


def _bag_backward(table_shape, table_dtype, ids, g_scaled, pad_id,
                  interpret):
    vocab, dim = table_shape
    ids = ids.astype(jnp.int32)
    # padded bags must scatter nothing: fill with pad_id, or with -1 when
    # pad_id is None (the kernel's `live` guard skips negatives then)
    ids, _ = _pad_bags(ids, pad_fill=pad_id if pad_id is not None else -1)
    b_pad, n = ids.shape
    g_scaled = jnp.pad(
        g_scaled, ((0, b_pad - g_scaled.shape[0]), (0, 0)))
    kernel = functools.partial(_bwd_kernel, pad_id=pad_id, vocab=vocab)
    return pl.pallas_call(
        kernel,
        grid=(b_pad // _BAG_BLOCK,),
        in_specs=[
            pl.BlockSpec((_BAG_BLOCK, n), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((_BAG_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct((vocab, dim), jnp.float32),
        scratch_shapes=[
            _VMEM((1, dim), jnp.float32),
            pltpu.SemaphoreType.DMA((1,)),
        ],
        input_output_aliases={2: 0},        # accumulate into the zeros
        interpret=interpret,
    )(ids, g_scaled.astype(jnp.float32),
      jnp.zeros((vocab, dim), jnp.float32)).astype(table_dtype)


# ---------------------------------------------------------------------------
# custom_vjp plumbing


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _bag(table, ids, combiner, pad_id, interpret):
    return _bag_forward(table, ids, combiner, pad_id, interpret)


def _bag_fwd_rule(table, ids, combiner, pad_id, interpret):
    out = _bag_forward(table, ids, combiner, pad_id, interpret)
    return out, (table, ids)


def _bag_bwd_rule(combiner, pad_id, interpret, res, g):
    table, ids = res
    mask = _bag_mask(ids, pad_id)
    g_scaled = g.astype(jnp.float32) * _combiner_scale(mask, combiner)
    dtable = _bag_backward(table.shape, table.dtype, ids, g_scaled,
                           pad_id, interpret)
    # integer primal: float0 cotangent (documented custom_vjp idiom)
    return dtable, np.zeros(ids.shape, jax.dtypes.float0)


_bag.defvjp(_bag_fwd_rule, _bag_bwd_rule)


# ---------------------------------------------------------------------------
# within-batch duplicate-id dedup (ISSUE 19)
#
# Recommender id streams repeat heavily inside a batch (zipfian traffic):
# the naive lookup pays one table-row DMA per SLOT, duplicates included.
# The dedup path collapses the flattened id block to its unique set with
# ``jnp.unique(size=B*N)`` — static output shape, so it jits — gathers
# each distinct row from the big table exactly once, and scatters back
# through the inverse index (a gather from the SMALL unique set, never
# from HBM-resident table rows).  Big-table rows touched per batch drop
# from ``B*N`` to ``U`` (the distinct count).  The custom_vjp keeps the
# training contract exact: gradients accumulate PER OCCURRENCE (segment-
# summed over the inverse index, then one scatter-add per unique row).


def _dedup_unique(ids, vocab, pad_id):
    """Static-shape unique decomposition of a ``(B, N)`` id block.

    Returns ``(mask, uniq, inv)``: the (B, N) f32 validity mask, the
    length-``B*N`` unique key vector (clipped ids; pad slots collapse to
    the ``-1`` fill so they unify with the tail padding), and the (B, N)
    inverse index with ``uniq[inv] == key``.
    """
    mask = _bag_mask(ids, pad_id)
    clipped = jnp.clip(ids.astype(jnp.int32), 0, vocab - 1)  # take parity
    key = jnp.where(mask > 0, clipped, -1)
    uniq, inv = jnp.unique(key.reshape(-1), size=key.size,
                           fill_value=-1, return_inverse=True)
    return mask, uniq, inv.reshape(ids.shape)


def _dedup_forward(table, ids, combiner, pad_id):
    vocab, _ = table.shape
    mask, uniq, inv = _dedup_unique(ids, vocab, pad_id)
    live = (uniq >= 0).astype(jnp.float32)
    rows_u = jnp.take(table, jnp.clip(uniq, 0, vocab - 1), axis=0)
    rows_u = rows_u.astype(jnp.float32) * live[:, None]      # (U, D)
    gathered = jnp.take(rows_u, inv, axis=0)                 # small-set
    out = jnp.sum(gathered * mask[..., None], axis=1)
    out = out * _combiner_scale(mask, combiner)
    return out.astype(table.dtype), (table, ids)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _dedup_bag(table, ids, combiner, pad_id):
    return _dedup_forward(table, ids, combiner, pad_id)[0]


def _dedup_bag_fwd(table, ids, combiner, pad_id):
    return _dedup_forward(table, ids, combiner, pad_id)


def _dedup_bag_bwd(combiner, pad_id, res, g):
    table, ids = res
    vocab, dim = table.shape
    mask, uniq, inv = _dedup_unique(ids, vocab, pad_id)
    live = (uniq >= 0).astype(jnp.float32)
    g_scaled = g.astype(jnp.float32) * _combiner_scale(mask, combiner)
    # per-occurrence contribution, segment-summed per unique id first so
    # the big-table scatter touches each distinct row exactly once
    contrib = (g_scaled[:, None, :] * mask[..., None]).reshape(-1, dim)
    d_u = jnp.zeros((uniq.shape[0], dim), jnp.float32)
    d_u = d_u.at[inv.reshape(-1)].add(contrib) * live[:, None]
    dtable = jnp.zeros((vocab, dim), jnp.float32)
    dtable = dtable.at[jnp.clip(uniq, 0, vocab - 1)].add(d_u)
    return (dtable.astype(table.dtype),
            np.zeros(ids.shape, jax.dtypes.float0))


_dedup_bag.defvjp(_dedup_bag_fwd, _dedup_bag_bwd)


def embedding_bag_dedup(table, ids, combiner: str = "sum", pad_id=0):
    """``embedding_bag`` through the within-batch dedup path: the same
    bag math (same mask/clip/combiner semantics, parity at rtol 1e-6),
    but each distinct id reads its table row exactly once per batch and
    the backward scatter-adds exactly once per distinct row — duplicate
    ids are free on both sides.  Differentiable wrt ``table``."""
    _check_args(table, ids, combiner)
    return _dedup_bag(table, ids, combiner, pad_id)


def dedup_wanted(*, sharded: bool) -> bool:
    """Resolve the ``dedup_ids`` knob for one lookup site and count the
    decision (``table_dedup_selected_total{decision,reason}``) — the
    PR 12 counted-dispatch contract for the dedup tier.  ``auto`` turns
    dedup ON for sharded lookups (where the unique set also shrinks the
    psum-side work and HBM row traffic pays full price) and OFF for the
    dense path (the fused kernel already streams rows at line rate)."""
    from analytics_zoo_tpu.observe import metrics as _metrics

    knob = dispatch.config_knob("dedup_ids", "auto")
    if knob == "off":
        decision, reason = "off", "knob_off"
    elif knob == "on":
        decision, reason = "on", "knob_on"
    else:
        decision, reason = (("on", "auto_sharded") if sharded
                            else ("off", "auto_dense"))
    _metrics.count("table_dedup_selected_total", 1,
                   flat=f"ops/dedup_{decision}",
                   decision=decision, reason=reason)
    return decision == "on"


# ---------------------------------------------------------------------------
# public entry


def embedding_bag(table, ids, combiner: str = "sum", pad_id=0,
                  interpret: bool = False):
    """Fused multi-hot lookup: ``combine_j table[ids[b, j]]`` per bag.

    ``table`` (vocab, dim) float; ``ids`` (bags, max_nnz) int.  Slots
    equal to ``pad_id`` contribute nothing (``pad_id=None`` counts every
    slot — dense multi-hot like Wide&Deep's wide tower).  ``combiner``
    is ``"sum" | "mean" | "sqrtn"`` over each bag's valid slots.
    Out-of-range ids clip, matching ``jnp.take``.

    Dispatch: the Pallas kernel on TPU (``fused_embedding`` knob:
    auto/on/off), the pure-JAX reference elsewhere; ``interpret=True``
    forces the kernel in interpreter mode (tests).  Differentiable wrt
    ``table`` on every path.
    """
    _check_args(table, ids, combiner)
    path = dispatch.select_path(
        "embedding_bag",
        shapes_ok=table.shape[0] >= 1,
        # below ~4k rows the whole table sits happily in cache/VMEM and
        # XLA's gather wins; the DMA kernel pays off once the table is
        # HBM-resident
        min_work_met=table.shape[0] >= 4096,
        knob=dispatch.config_knob("fused_embedding", "auto"),
        force=dispatch.PATH_INTERPRET if interpret else None,
    )
    if path == dispatch.PATH_REFERENCE:
        return embedding_bag_reference(table, ids, combiner, pad_id)
    return _bag(table, ids, combiner, pad_id,
                path == dispatch.PATH_INTERPRET)


def embedding_gather(table, ids, interpret: bool = False):
    """Plain ``table[ids]`` lookup routed through the bag kernel.

    A gather is the degenerate bag (one id per bag, no combine), so the
    recommenders' single-id and sequence lookups (NCF, the session GRU)
    share the fused DMA pipeline transparently: ids of any shape flatten
    to (num, 1) singleton bags and the result folds back to
    ``ids.shape + (dim,)``.  Off-TPU this is exactly ``jnp.take`` — no
    mask, no reduction — so the XLA graph is unchanged there.
    """
    if table.ndim != 2:
        raise ValueError(f"table must be (vocab, dim), got {table.shape}")
    path = dispatch.select_path(
        "embedding_gather",
        min_work_met=table.shape[0] >= 4096,
        knob=dispatch.config_knob("fused_embedding", "auto"),
        force=dispatch.PATH_INTERPRET if interpret else None,
    )
    if path == dispatch.PATH_REFERENCE:
        return jnp.take(table, ids.astype(jnp.int32), axis=0)
    flat = ids.astype(jnp.int32).reshape((-1, 1))
    out = _bag(table, flat, "sum", None, path == dispatch.PATH_INTERPRET)
    return out.reshape(ids.shape + (table.shape[1],))
