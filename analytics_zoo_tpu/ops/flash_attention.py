"""Pallas TPU flash-attention forward kernel.

Replaces the O(L²) attention inside the reference's TransformerLayer/BERT
(api/keras/layers/TransformerLayer.scala:56, BERT.scala:66) with a fused
blockwise kernel: Q/K/V tiles stream HBM→VMEM, the (block_q, block_k)
logits tile lives only in VMEM, and the online-softmax running (m, l, acc)
state sits in VMEM scratch across the KV grid dimension.  The MXU sees two
matmuls per tile (Q·Kᵀ and P·V); everything else is VPU work fused in
between.

Autodiff: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
recomputes attention gradients via the pure-JAX blockwise path
(ops/attention.py) — i.e. the forward hot loop (serving, eval) gets the
hand-written kernel while training gradients reuse XLA's derivation of the
same math.  Off-TPU the kernel runs in interpreter mode only under tests;
production dispatch falls back to blockwise (see dot_product_attention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                lq: int, lk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip KV blocks strictly above the diagonal.
    q_end = qi * block_q + block_q - 1 + (lk - lq)
    live = (ki * block_k <= q_end) if causal else (ki >= 0)

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # (bq, d)
        k = k_ref[0].astype(jnp.float32)                 # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (lk - lq)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)

        m_prev = m_scr[:, :1]                            # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = (acc_scr[:] * alpha
                      + jax.lax.dot_general(
                          p, v_ref[0].astype(jnp.float32),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-20)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, sm_scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bq = min(block_q, lq)
    bk = min(block_k, lk)
    assert lq % bq == 0 and lk % bk == 0, (
        f"sequence lengths ({lq},{lk}) must divide blocks ({bq},{bk})")
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    grid = (b * h, lq // bq, lk // bk)

    if _VMEM is None:
        raise ImportError(
            "jax.experimental.pallas.tpu unavailable — use "
            "ops.attention.blockwise_attention (dot_product_attention "
            "dispatches there automatically)")
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=bq, block_k=bk, lq=lq, lk=lk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        scratch_shapes=[
            _VMEM((bq, 128), jnp.float32),
            _VMEM((bq, 128), jnp.float32),
            _VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, lq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused attention forward. Shapes q (B,H,Lq,D), k/v (B,H,Lk,D).

    D and the sequence blocks should be multiples of 128 for MXU tiling
    (dispatch in ops/attention.py enforces this).
    """
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret)
    return out, (q, k, v)


def _bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    from analytics_zoo_tpu.ops.attention import blockwise_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, sm_scale=sm_scale,
            block_size=block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd_rule, _bwd_rule)
