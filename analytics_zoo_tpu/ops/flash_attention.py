"""Pallas TPU flash-attention forward kernel.

Replaces the O(L²) attention inside the reference's TransformerLayer/BERT
(api/keras/layers/TransformerLayer.scala:56, BERT.scala:66) with a fused
blockwise kernel: Q/K/V tiles stream HBM→VMEM, the (block_q, block_k)
logits tile lives only in VMEM, and the online-softmax running (m, l, acc)
state sits in VMEM scratch across the KV grid dimension.  The MXU sees two
matmuls per tile (Q·Kᵀ and P·V); everything else is VPU work fused in
between.

Autodiff: ``flash_attention`` carries a ``jax.custom_vjp`` with
HAND-WRITTEN Pallas backward kernels (the FlashAttention-2 recipe): the
forward additionally emits the per-row logsumexp, the backward recomputes
the probability tiles from (q, k, lse) in VMEM — no (Lq, Lk) matrix ever
materialises — and two kernels accumulate dQ (grid over KV blocks) and
dK/dV (grid over Q blocks) in f32 scratch.  flash_attention requires
pallas end-to-end (fwd and bwd); backends without it are routed to the
pure-JAX blockwise path by ``dot_product_attention``'s dispatch.
Off-TPU the kernels run in interpreter mode under tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                sm_scale: float, causal: bool, block_q: int, block_k: int,
                lq: int, lk: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip KV blocks strictly above the diagonal.
    q_end = qi * block_q + block_q - 1 + (lk - lq)
    live = (ki * block_k <= q_end) if causal else (ki >= 0)

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # (bq, d)
        k = k_ref[0].astype(jnp.float32)                 # (bk, d)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + (lk - lq)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(qpos >= kpos, logits, NEG_INF)

        m_prev = m_scr[:, :1]                            # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = (acc_scr[:] * alpha
                      + jax.lax.dot_general(
                          p, v_ref[0].astype(jnp.float32),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-20)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _fwd_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                    acc_scr, *, sm_scale, causal, block_q, block_k, lq, lk):
    """Forward that also emits logsumexp rows (residual for the bwd)."""
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                sm_scale=sm_scale, causal=causal, block_q=block_q,
                block_k=block_k, lq=lq, lk=lk)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == nk - 1)
    def _emit_lse():
        l = jnp.maximum(l_scr[:, :1], 1e-20)
        row = (m_scr[:, 0] + jnp.log(l[:, 0])).astype(jnp.float32)
        # lse block is (1, 8, bq): the row dim is padded to the TPU's
        # 8-sublane tile floor (a (1, bq) block is an illegal sub-tile);
        # all 8 sublanes carry the same row, the caller reads sublane 0
        lse_ref[0] = jnp.broadcast_to(row[None, :], lse_ref.shape[1:])


def _pick_block(block: int, length: int) -> int:
    """Largest block <= ``block`` that divides ``length`` (halving keeps
    it a multiple of 128 down to the tile floor)."""
    b = min(block, length)
    while length % b:
        b //= 2
    return b


def _blocks(q, k, block_q, block_k):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    bq = _pick_block(block_q, lq)
    bk = _pick_block(block_k, lk)
    assert lq % bq == 0 and lk % bk == 0, (
        f"sequence lengths ({lq},{lk}) must divide blocks ({bq},{bk})")
    if _VMEM is None:
        raise ImportError(
            "jax.experimental.pallas.tpu unavailable — use "
            "ops.attention.blockwise_attention (dot_product_attention "
            "dispatches there automatically)")
    return b, h, lq, lk, d, bq, bk


def _flash_fwd(q, k, v, sm_scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool,
               with_lse: bool = False):
    b, h, lq, lk, d, bq, bk = _blocks(q, k, block_q, block_k)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    grid = (b * h, lq // bq, lk // bk)

    common = dict(sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
                  lq=lq, lk=lk)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    scratch = [
        _VMEM((bq, 128), jnp.float32),
        _VMEM((bq, 128), jnp.float32),
        _VMEM((bq, d), jnp.float32),
    ]
    o_spec = pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0))
    if with_lse:
        out, lse = pl.pallas_call(
            functools.partial(_fwd_lse_kernel, **common),
            grid=grid,
            in_specs=in_specs,
            out_specs=[o_spec,
                       pl.BlockSpec((1, 8, bq),
                                    lambda bh, qi, ki: (bh, 0, qi))],
            out_shape=[jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
                       jax.ShapeDtypeStruct((b * h, 8, lq), jnp.float32)],
            scratch_shapes=scratch,
            interpret=interpret,
        )(qf, kf, vf)
        return out.reshape(b, h, lq, d), lse[:, 0, :].reshape(b, h, lq)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, **common),
        grid=grid,
        in_specs=in_specs,
        out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, lq, d)


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2): probabilities recomputed from
# (q, k, lse); dQ accumulates over KV blocks, dK/dV over Q blocks.
# ---------------------------------------------------------------------------

def _recompute_p(q, k, lse_rows, qi, ki, *, sm_scale, causal, block_q,
                 block_k, lq, lk):
    """(bq, bk) probability tile from streamed q/k and the saved lse."""
    s = jax.lax.dot_general(
        q.astype(jnp.float32) * sm_scale, k.astype(jnp.float32),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0) + (lk - lq)
        kpos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    p = jnp.exp(s - lse_rows[:, None])
    return jnp.where(s <= NEG_INF / 2, 0.0, p)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, sm_scale, causal, block_q, block_k, lq, lk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_end = qi * block_q + block_q - 1 + (lk - lq)
    live = (ki * block_k <= q_end) if causal else (ki >= 0)

    @pl.when(live)
    def _body():
        # lse/delta blocks are (1, 8, bq) — sublane-padded rows; take
        # sublane 0 (see _emit_lse)
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0, 0], qi, ki,
                         sm_scale=sm_scale, causal=causal, block_q=block_q,
                         block_k=block_k, lq=lq, lk=lk)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dq_scr[:] = dq_scr[:] + sm_scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                    block_q, block_k, lq, lk):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal: this k block only sees q rows at/after the diagonal
    q_end = qi * block_q + block_q - 1 + (lk - lq)
    live = (ki * block_k <= q_end) if causal else (qi >= 0)

    @pl.when(live)
    def _body():
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0, 0], qi, ki,
                         sm_scale=sm_scale, causal=causal, block_q=block_q,
                         block_k=block_k, lq=lq, lk=lk)
        do = do_ref[0].astype(jnp.float32)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dk_scr[:] = dk_scr[:] + sm_scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bk, d)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, sm_scale, causal, block_q, block_k,
               interpret):
    b, h, lq, lk, d, bq, bk = _blocks(q, k, block_q, block_k)
    qf = q.reshape(b * h, lq, d)
    kf = k.reshape(b * h, lk, d)
    vf = v.reshape(b * h, lk, d)
    dof = g.reshape(b * h, lq, d)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, fused by XLA.
    # Rows are sublane-padded to (BH, 8, L): a (1, bq) block is an
    # illegal TPU sub-tile, (1, 8, bq) satisfies the (8, 128) tile floor
    # and the kernels read sublane 0.
    delta = jnp.sum(dof.astype(jnp.float32)
                    * out.reshape(b * h, lq, d).astype(jnp.float32),
                    axis=-1)
    lse8 = jnp.broadcast_to(lse.reshape(b * h, 1, lq), (b * h, 8, lq))
    delta8 = jnp.broadcast_to(delta[:, None, :], (b * h, 8, lq))

    common = dict(sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
                  lq=lq, lk=lk)
    q_spec3 = pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0))
    k_spec3 = pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0))
    row_spec3 = pl.BlockSpec((1, 8, bq), lambda bh, qi, ki: (bh, 0, qi))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b * h, lq // bq, lk // bk),
        in_specs=[q_spec3, k_spec3, k_spec3, q_spec3, row_spec3, row_spec3],
        out_specs=q_spec3,
        out_shape=jax.ShapeDtypeStruct((b * h, lq, d), q.dtype),
        scratch_shapes=[_VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse8, delta8)

    q_specK = pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0))
    k_specK = pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0))
    row_specK = pl.BlockSpec((1, 8, bq), lambda bh, ki, qi: (bh, 0, qi))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(b * h, lk // bk, lq // bq),
        in_specs=[q_specK, k_specK, k_specK, q_specK, row_specK, row_specK],
        out_specs=[k_specK, k_specK],
        out_shape=[jax.ShapeDtypeStruct((b * h, lk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, lk, d), v.dtype)],
        scratch_shapes=[_VMEM((bk, d), jnp.float32),
                        _VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse8, delta8)
    return (dq.reshape(b, h, lq, d), dk.reshape(b, h, lk, d),
            dv.reshape(b, h, lk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    sm_scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: bool = False):
    """Fused attention forward. Shapes q (B,H,Lq,D), k/v (B,H,Lk,D).

    D and the sequence blocks should be multiples of 128 for MXU tiling
    (dispatch in ops/attention.py enforces this).  Default blocks are
    256x256 — measured fastest on v5e at L=2048/D=64 (10.7ms fwd vs
    12.3ms at 128x128 and 14.8ms for the XLA blockwise path; fwd+bwd
    13.7ms vs 22.8ms blockwise).  ``_blocks`` clamps them for short
    sequences.
    """
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    # (no blockwise fallback here: if pallas were unavailable the
    # FORWARD would already have raised — non-pallas backends are routed
    # to blockwise_attention by dot_product_attention's dispatch)
    q, k, v, out, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q,
                      block_k, interpret)


flash_attention.defvjp(_fwd_rule, _bwd_rule)
