"""Pallas TPU fused dequantize-matmul kernel (int8 / packed-int4 weights).

The serving tier's quantized forward (reference: OpenVINO int8 calibration,
InferenceModel.scala:443) stores replica weights compressed; the XLA path
(``dequantize_pytree`` → matmul) decodes each weight back to a full f32
array in HBM before the MXU sees it, so the HBM win evaporates exactly
where bandwidth matters.  This kernel keeps the decode inside the matmul:
quantized weight tiles travel HBM→VMEM at 1 byte (int8) or a nibble
(packed int4) per element, are widened to f32 in-registers after the VMEM
load — extending ``ops/quantization.py``'s per-output-channel scales and
the in-kernel shard decode idea from the data tier — and the MXU consumes
the decoded tile directly.  Weight HBM traffic is 1/4 (int8) or 1/8
(int4) of the f32 leg; the per-channel rescale folds into the K-loop
finalize.

int4 packing is two's-complement nibbles along the K axis: packed byte
``(q[2k+1] << 4) | (q[2k] & 0xF)``, odd K padded with a zero nibble
(``rows`` carries the true K).  Autodiff: ``jax.custom_vjp`` — serving
never differentiates this, but the parity suites do; the backward is the
pure-JAX ``dx = g @ dequant(w).T`` (materialising f32 weights is fine off
the hot path), with ``float0``/zero cotangents for ``q``/``scale``.

Backends without pallas are routed to ``dequant_matmul_reference`` by
``ops.dispatch.select_path``; off-TPU the kernel runs under
``interpret=True`` in tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

from analytics_zoo_tpu.ops import dispatch

BITS = (8, 4)


def pack_int4(q4):
    """(K, N) int8 values in [-8, 7] → (ceil(K/2), N) packed bytes."""
    k = q4.shape[0]
    if k % 2:
        q4 = jnp.pad(q4, ((0, 1), (0, 0)))
    q32 = q4.astype(jnp.int32)
    # (hi << 4) | (lo & 0xF) stays in [-128, 127]: exact int8 round-trip
    packed = (q32[1::2] << 4) | (q32[0::2] & 0xF)
    return packed.astype(jnp.int8)


def unpack_int4(packed, rows: int):
    """Inverse of ``pack_int4``: (Kp, N) bytes → (rows, N) int8 nibbles."""
    b32 = packed.astype(jnp.int32)
    lo = (b32 << 28) >> 28                       # sign-extend low nibble
    hi = b32 >> 4                                # arithmetic: sign-extends
    full = jnp.stack([lo, hi], axis=1).reshape(2 * packed.shape[0],
                                               packed.shape[1])
    return full[:rows].astype(jnp.int8)


def quantize_weights(w, bits: int = 8):
    """Symmetric per-output-channel quantization of a (K, N) weight.

    Returns ``(q, scale)``: ``q`` int8 — (K, N) values for ``bits=8``
    (same scheme as ``quantize_tensor(w, axis=-1)``), nibble-packed
    (ceil(K/2), N) for ``bits=4`` — and ``scale`` f32 (1, N).
    """
    if bits not in BITS:
        raise ValueError(f"bits must be one of {BITS}, got {bits}")
    w = jnp.asarray(w)
    if w.ndim != 2:
        raise ValueError(f"weights must be (in, out), got {w.shape}")
    qmax = 127.0 if bits == 8 else 7.0
    amax = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / qmax).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -qmax, qmax).astype(jnp.int8)
    return (pack_int4(q) if bits == 4 else q), scale


def _dequant(q, scale, bits: int, rows: Optional[int]):
    """f32 weight matrix back from its quantized storage (oracle path)."""
    if bits == 4:
        q = unpack_int4(q, rows if rows is not None else 2 * q.shape[0])
    return q.astype(jnp.float32) * scale


def dequant_matmul_reference(x, q, scale, bits: int = 8,
                             rows: Optional[int] = None):
    """Pure-JAX oracle: ``x @ (unpack(q) * scale)`` — XLA materialises
    the dequantized f32 weight; the fused kernel never does."""
    w = _dequant(q, jnp.reshape(scale, (1, -1)), bits, rows)
    out = jax.lax.dot_general(
        x.astype(jnp.float32), w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# kernel


def _dq_kernel(x_ref, w_ref, s_ref, o_ref, acc, *, bits: int):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    wq = w_ref[...]                              # int8 tile, VMEM
    if bits == 4:                                # in-register nibble decode
        b32 = wq.astype(jnp.int32)
        lo = (b32 << 28) >> 28
        hi = b32 >> 4
        wq = jnp.stack([lo, hi], axis=1).reshape(2 * wq.shape[0],
                                                 wq.shape[1])
    w = wq.astype(jnp.float32)                   # the MXU sees f32 tiles
    acc[:] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[:] = (acc[:] * s_ref[0][None, :]).astype(o_ref.dtype)


def _pick_block(block: int, length: int) -> int:
    b = min(block, length)
    while length % b:
        b //= 2
    return b


def _pad_to(a, dim: int, size: int, value=0):
    rem = (-a.shape[dim]) % size
    if not rem:
        return a
    pads = [(0, 0)] * a.ndim
    pads[dim] = (0, rem)
    return jnp.pad(a, pads, constant_values=value)


def _dq_forward(x, q, scale, bits, rows, interpret):
    if pltpu is None:  # pragma: no cover
        raise ImportError(
            "pallas TPU support unavailable; dequant_matmul should have "
            "been routed to dequant_matmul_reference by ops.dispatch")
    m, k = x.shape
    n = q.shape[1]
    k_store = 2 * q.shape[0] if bits == 4 else q.shape[0]
    if k > k_store:
        raise ValueError(f"x K dim {k} exceeds stored weight rows "
                         f"{k_store}")
    if k < k_store:                      # odd-K int4: one zero nibble row
        x = jnp.pad(x, ((0, 0), (0, k_store - k)))
    # block the (possibly padded) problem; every dim padded up to its
    # block so index maps stay dense
    bm = _pick_block(128, ((m + 7) // 8) * 8)
    bn = _pick_block(128, ((n + 127) // 128) * 128)
    bk = _pick_block(512, ((k_store + 1) // 2) * 2)
    if bk % 2:
        bk *= 2                          # int4 tiles cover whole bytes
    x = _pad_to(_pad_to(x, 0, bm), 1, bk)
    q = _pad_to(_pad_to(q, 0, bk // 2 if bits == 4 else bk), 1, bn)
    scale = _pad_to(jnp.reshape(scale, (1, -1)), 1, bn)
    mp, kp = x.shape
    np_ = q.shape[1]
    grid = (mp // bm, np_ // bn, kp // bk)
    wblk = bk // 2 if bits == 4 else bk
    out = pl.pallas_call(
        functools.partial(_dq_kernel, bits=bits),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
            pl.BlockSpec((wblk, bn), lambda mi, ni, ki: (ki, ni)),
            pl.BlockSpec((1, bn), lambda mi, ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        scratch_shapes=[_VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, q, scale)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _dq(x, q, scale, bits, rows, interpret):
    return _dq_forward(x, q, scale, bits, rows, interpret)


def _dq_fwd_rule(x, q, scale, bits, rows, interpret):
    return _dq_forward(x, q, scale, bits, rows, interpret), (q, scale)


def _dq_bwd_rule(bits, rows, interpret, res, g):
    q, scale = res
    w = _dequant(q, jnp.reshape(scale, (1, -1)), bits, rows)
    dx = jax.lax.dot_general(
        g.astype(jnp.float32), w, (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (dx, np.zeros(q.shape, jax.dtypes.float0),
            jnp.zeros_like(scale))


_dq.defvjp(_dq_fwd_rule, _dq_bwd_rule)


# ---------------------------------------------------------------------------
# public entry


def dequant_matmul(x, q, scale, bits: int = 8, rows: Optional[int] = None,
                   interpret: bool = False):
    """``x @ dequant(q, scale)`` with the dequantize fused into the matmul.

    ``x`` (..., K) float; ``q`` int8 weight storage — (K, N) for
    ``bits=8``, nibble-packed (ceil(K/2), N) for ``bits=4`` (``rows=K``
    disambiguates odd K); ``scale`` f32 per-output-channel, (N,) or
    (1, N).  Returns (..., N) in ``x.dtype``.

    Dispatch: the Pallas kernel on TPU, the pure-JAX reference elsewhere;
    ``interpret=True`` forces the kernel in interpreter mode (tests).
    Differentiable wrt ``x`` on every path.
    """
    if bits not in BITS:
        raise ValueError(f"bits must be one of {BITS}, got {bits}")
    k = x.shape[-1]
    lead = x.shape[:-1]
    path = dispatch.select_path(
        "dequant_matmul",
        shapes_ok=q.ndim == 2,
        # tiny matmuls: XLA's fused dequant+dot already runs at latency,
        # the kernel pays off once weights are HBM-resident
        min_work_met=q.size >= 256 * 256,
        force=dispatch.PATH_INTERPRET if interpret else None,
    )
    if path == dispatch.PATH_REFERENCE:
        return dequant_matmul_reference(x, q, scale, bits, rows)
    x2 = x.reshape((-1, k))
    out = _dq(x2, q, scale, bits, rows, path == dispatch.PATH_INTERPRET)
    return out.reshape(lead + (q.shape[1],))
