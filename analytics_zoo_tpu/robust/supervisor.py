"""Supervisor — background health checks + stage heartbeats.

The serving pipeline's self-healing loop (docs/SERVING.md "Failure
semantics"): a single daemon thread runs a set of registered *checks*
every ``interval_s``.  Checks are plain callables that inspect state and
repair it — rebuild quarantined replicas, abandon a hung harvest,
restart a dead stage thread, publish health gauges.  A check that raises
is logged and counted (``robust/supervisor_check_error/<name>``) but
never kills the supervisor: the healer must be harder to kill than the
thing it heals.

:class:`Heartbeat` is the companion liveness registry: each pipeline
stage stamps ``beat(stage)`` as it iterates, and the supervisor's stage
watchdog reads ``age(stage)`` to tell a wedged thread (stale beat while
work is pending) from an idle one.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from analytics_zoo_tpu.observe import metrics as obs

logger = logging.getLogger("analytics_zoo_tpu.robust")


class Heartbeat:
    """Thread-safe per-stage liveness stamps (monotonic clock)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: Dict[str, float] = {}

    def beat(self, stage: str) -> None:
        with self._lock:
            self._beats[stage] = self._clock()

    def age(self, stage: str) -> float:
        """Seconds since the stage last beat (0.0 if it never has —
        a stage that hasn't started yet is not "stalled")."""
        with self._lock:
            t = self._beats.get(stage)
            return 0.0 if t is None else max(0.0, self._clock() - t)

    def ages(self) -> Dict[str, float]:
        with self._lock:
            now = self._clock()
            return {k: max(0.0, now - t) for k, t in self._beats.items()}


class Supervisor:
    """Daemon thread running registered repair checks on an interval.

    ``stop()`` is idempotent and safe to call from any thread (including
    a check itself).  Checks run sequentially in registration order each
    tick, so a check may rely on an earlier one having run (e.g. the
    harvest watchdog quarantines before the rebuild check looks for
    quarantined slots).
    """

    def __init__(self, interval_s: float = 0.25, name: str = "supervisor"):
        self.interval_s = max(0.01, float(interval_s))
        self.name = name
        self._checks: List[Tuple[str, Callable[[], object], int]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick = 0

    def add_check(self, name: str, fn: Callable[[], object],
                  every: int = 1) -> "Supervisor":
        """Register a repair check.  ``every=k`` runs it on every k-th
        tick only — slow controllers (the serving autoscaler) ride the
        same supervisor thread at a coarser cadence than the hot repair
        checks."""
        with self._lock:
            self._checks.append((name, fn, max(1, int(every))))
        return self

    def start(self) -> "Supervisor":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def run_checks_once(self, tick: Optional[int] = None) -> None:
        """One synchronous pass over the checks (tests drive this
        directly for determinism instead of waiting out the interval).
        ``tick=None`` runs EVERY check regardless of its ``every=``
        cadence; the supervisor loop passes its tick counter so coarse
        checks fire on their multiple only."""
        with self._lock:
            checks = list(self._checks)
        for name, fn, every in checks:
            if self._stop.is_set():
                return
            if tick is not None and tick % every != 0:
                continue
            try:
                fn()
            except Exception:
                obs.count("supervisor_check_errors_total", check=name,
                          flat=f"robust/supervisor_check_error/{name}")
                logger.exception("supervisor check %r failed; supervisor "
                                 "continues", name)

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            self._tick += 1
            self.run_checks_once(tick=self._tick)
