"""CircuitBreaker — consecutive-failure replica health state machine.

The serving DeviceExecutor gives every model replica one breaker; the
breaker decides, per dispatch, whether the replica may receive work
(docs/SERVING.md "Failure semantics").  Three states:

- **closed**     — normal operation.  Failures increment a consecutive
                   counter; any success clears it.  ``failure_threshold``
                   consecutive failures open the breaker.
- **open**       — quarantined: ``allow()`` refuses all work until
                   ``cooldown_s`` has elapsed since opening.
- **half-open**  — after the cooldown, exactly ONE probe dispatch is let
                   through.  Success closes the breaker (the replica is
                   restored); failure re-opens it and the cooldown
                   restarts.

The health view collapses to the three-stage replica lifecycle:
``healthy`` (closed, no recent failures) → ``degraded`` (closed, some
consecutive failures below the threshold) → ``quarantined`` (open or
probing).

Like :class:`~analytics_zoo_tpu.robust.retry.RetryPolicy`, the clock is
injectable so chaos tests step time deterministically instead of
sleeping.  All methods are thread-safe: ``allow()`` is called from the
executor's dispatch thread while ``record_*`` arrive from the harvest
thread and ``force_open`` from the supervisor's watchdog.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict

from analytics_zoo_tpu.core.profiling import TIMERS
from analytics_zoo_tpu.observe import metrics as obs

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with a single-probe half-open state."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 2.0,
                 name: str = "breaker",
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_t = 0.0
        self._probe_inflight = False
        self.open_count = 0     # times the breaker has opened (ever)
        self.failures = 0       # total recorded failures (ever)

    # -- state views -------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def health(self) -> str:
        """The replica-lifecycle view: healthy → degraded → quarantined."""
        with self._lock:
            if self._state != CLOSED:
                return "quarantined"
            return "degraded" if self._consecutive else "healthy"

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def open_age_s(self) -> float:
        """Seconds since the breaker last opened (0 while closed)."""
        with self._lock:
            if self._state == CLOSED:
                return 0.0
            return max(0.0, self.clock() - self._opened_t)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state,
                    "health": ("quarantined" if self._state != CLOSED else
                               "degraded" if self._consecutive else
                               "healthy"),
                    "consecutive_failures": self._consecutive,
                    "failures": self.failures,
                    "opens": self.open_count,
                    "open_age_s": (0.0 if self._state == CLOSED
                                   else max(0.0,
                                            self.clock() - self._opened_t))}

    # -- decisions ---------------------------------------------------------
    def allow(self) -> bool:
        """May a dispatch go to this replica right now?  In the open
        state this is also where the half-open transition happens: the
        first call after the cooldown claims the single probe slot."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() - self._opened_t < self.cooldown_s:
                    return False
                self._state = HALF_OPEN
                self._probe_inflight = True
                TIMERS.incr(f"robust/breaker_probe/{self.name}")
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            TIMERS.incr(f"robust/breaker_probe/{self.name}")
            return True

    def record_success(self) -> bool:
        """Outcome hook.  Returns True when this success CLOSED a
        previously open/probing breaker (the replica was restored)."""
        with self._lock:
            self._probe_inflight = False
            self._consecutive = 0
            restored = self._state != CLOSED
            self._state = CLOSED
        if restored:
            obs.count("breaker_transitions_total", breaker=self.name,
                      to="closed", flat=f"robust/breaker_closed/{self.name}")
        return restored

    def record_failure(self) -> bool:
        """Outcome hook.  Returns True when this failure OPENED the
        breaker (threshold reached, or a half-open probe failed)."""
        with self._lock:
            self.failures += 1
            self._probe_inflight = False
            self._consecutive += 1
            was_open = self._state == OPEN
            trip = (self._state == HALF_OPEN
                    or self._consecutive >= self.failure_threshold)
            if trip:
                self._state = OPEN
                self._opened_t = self.clock()
                if not was_open:
                    self.open_count += 1
        if trip and not was_open:
            # the flight recorder watches this labeled counter: any
            # breaker opening inside a window trips a snapshot
            obs.count("breaker_transitions_total", breaker=self.name,
                      to="open", flat=f"robust/breaker_open/{self.name}")
            return True
        return False

    def force_open(self) -> bool:
        """Quarantine immediately (supervisor watchdog: a hung replica
        never *returns* a failure, so the breaker is opened for it).
        Returns True if the breaker was not already open."""
        with self._lock:
            self.failures += 1
            self._probe_inflight = False
            self._consecutive = max(self._consecutive + 1,
                                    self.failure_threshold)
            was_open = self._state == OPEN
            self._state = OPEN
            self._opened_t = self.clock()
            if not was_open:
                self.open_count += 1
        if not was_open:
            obs.count("breaker_transitions_total", breaker=self.name,
                      to="open", flat=f"robust/breaker_open/{self.name}")
            return True
        return False

    def reset(self) -> None:
        """Back to a fresh closed breaker (a rebuilt replica starts with
        a clean slate; historical ``opens``/``failures`` are kept for
        telemetry)."""
        with self._lock:
            self._state = CLOSED
            self._consecutive = 0
            self._probe_inflight = False


class QuarantineBroadcast:
    """Epoch-tagged atomic group quarantine for mesh replicas.

    A mesh replica is ONE failure domain spread over many breakers (one
    per mesh-replica slot, and — across hosts — one per surviving
    process).  When a member host dies, every survivor observes the
    loss independently (its own dispatch barrier times out), so the
    naive reaction would trip the same breakers repeatedly and at
    slightly different times.  The broadcast makes the reaction atomic
    and idempotent: a loss event is tagged with the host-roster *epoch*
    it was observed at, and ``trip(epoch, breakers)`` force-opens the
    whole set exactly once per epoch — later observers of the same
    epoch are no-ops, so concurrent harvest threads, supervisor ticks
    and barrier-timeout handlers collapse into one quarantine.

    Thread-safe; the epoch ledger is guarded by its own lock while the
    breakers use theirs (``force_open``), so there is no nested-lock
    order to get wrong.
    """

    def __init__(self, name: str = "mesh"):
        self.name = name
        self._lock = threading.Lock()
        self._seen: set = set()
        self._last_epoch = 0

    @property
    def last_epoch(self) -> int:
        with self._lock:
            return self._last_epoch

    def tripped(self, epoch: int) -> bool:
        with self._lock:
            return int(epoch) in self._seen

    def trip(self, epoch: int, breakers) -> bool:
        """Force-open every breaker in ``breakers`` for loss ``epoch``.
        Returns True when THIS call performed the trip, False when the
        epoch was already quarantined (idempotent re-observation)."""
        epoch = int(epoch)
        with self._lock:
            if epoch in self._seen:
                return False
            self._seen.add(epoch)
            self._last_epoch = max(self._last_epoch, epoch)
        for b in breakers:
            b.force_open()
        TIMERS.incr(f"robust/quarantine_broadcast/{self.name}")
        return True
