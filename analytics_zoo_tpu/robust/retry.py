"""RetryPolicy — exponential backoff + jitter + deadline + sliding window.

Replaces the ad-hoc retry loops that had grown independently in the
Estimator (sliding-window failure counting, Topology.scala:1179-1261
semantics) and the serving transports.  One policy object covers both
usage shapes:

- ``policy.call(fn, ...)`` — functional: run ``fn``, retrying on the
  configured exception types with backoff until attempts/deadline run
  out (queue I/O, checkpoint writes).
- ``policy.state()`` → :class:`RetryState` — loop-style: an explicit
  failure recorder for retry loops that restore state between attempts
  (the Estimator's retry-from-checkpoint), keeping the reference's
  sliding-window semantics (``failure_retry_interval_s``: old failures
  age out so long jobs survive rare transient faults).

Every attempt/backoff/deadline event is counted in
``core.profiling.TIMERS`` under ``robust/retry_*`` so chaos tests can
assert on behaviour instead of timing.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple, Type

from analytics_zoo_tpu.core.profiling import TIMERS

logger = logging.getLogger("analytics_zoo_tpu.robust")


class RetryDeadlineExceeded(RuntimeError):
    """The retry deadline expired before an attempt succeeded.  The
    causing exception of the last attempt is chained as ``__cause__``."""


@dataclass
class RetryPolicy:
    """Exponential backoff with full-jitter, bounded by attempts and an
    optional wall-clock deadline.

    ``window_s`` gives the sliding-window semantics the Estimator's
    failure retry needs: only failures younger than the window count
    against ``max_attempts``.
    """

    max_attempts: int = 5
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.1            # +/- fraction of the computed delay
    deadline_s: Optional[float] = None
    window_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    name: str = "retry"
    # observer invoked once per FAILED attempt inside ``call`` (attempt
    # number, exception) before the backoff sleep — lets call sites feed
    # labeled metrics (e.g. ``dist_init_retries_total``) without wrapping
    # the retried function
    on_retry: Optional[Callable[[int, BaseException], None]] = None
    # injectable for determinism in tests (and to keep chaos suites fast)
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    seed: Optional[int] = None
    _rng: random.Random = field(init=False, repr=False, default=None)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    @classmethod
    def from_config(cls, cfg, **overrides) -> "RetryPolicy":
        """Policy from the ``retry_*`` config knobs (core/config.py)."""
        kw = dict(max_attempts=cfg.retry_max_attempts,
                  base_delay_s=cfg.retry_base_delay_s,
                  max_delay_s=cfg.retry_max_delay_s,
                  multiplier=cfg.retry_multiplier,
                  jitter=cfg.retry_jitter,
                  deadline_s=cfg.retry_deadline_s)
        kw.update(overrides)
        return cls(**kw)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(self.max_delay_s,
                self.base_delay_s * (self.multiplier ** max(0, attempt - 1)))
        if self.jitter:
            d *= 1.0 + self._rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)

    def state(self) -> "RetryState":
        return RetryState(self)

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying on ``retry_on`` with
        backoff.  Raises the last error once attempts are exhausted, or
        :class:`RetryDeadlineExceeded` once the deadline would pass."""
        start = self.clock()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                attempt += 1
                TIMERS.incr(f"robust/retry_attempts/{self.name}")
                if self.on_retry is not None:
                    try:
                        self.on_retry(attempt, e)
                    except Exception:
                        logger.debug("%s: on_retry observer raised",
                                     self.name, exc_info=True)
                if attempt >= self.max_attempts:
                    TIMERS.incr(f"robust/retry_exhausted/{self.name}")
                    raise
                d = self.delay(attempt)
                if (self.deadline_s is not None
                        and self.clock() - start + d > self.deadline_s):
                    TIMERS.incr(f"robust/retry_deadline/{self.name}")
                    raise RetryDeadlineExceeded(
                        f"{self.name}: deadline {self.deadline_s}s exceeded "
                        f"after {attempt} attempts") from e
                logger.warning("%s: attempt %d/%d failed (%s); retrying in "
                               "%.3fs", self.name, attempt,
                               self.max_attempts, e, d)
                self.sleep(d)


class RetryState:
    """Loop-style failure recorder for a :class:`RetryPolicy`.

    ``record_failure()`` returns whether the caller should retry (ages
    failures out of the sliding window first); ``backoff()`` sleeps the
    policy's next delay.  The caller owns the actual retry (restoring a
    checkpoint, rebuilding an iterator, ...).
    """

    def __init__(self, policy: RetryPolicy):
        self.policy = policy
        self.start = policy.clock()
        self.fail_times: List[float] = []

    @property
    def failures(self) -> int:
        return len(self.fail_times)

    def record_failure(self) -> bool:
        p = self.policy
        now = p.clock()
        if p.window_s is not None:
            self.fail_times = [t for t in self.fail_times
                               if now - t < p.window_s]
        self.fail_times.append(now)
        TIMERS.incr(f"robust/retry_attempts/{p.name}")
        if len(self.fail_times) > p.max_attempts:
            TIMERS.incr(f"robust/retry_exhausted/{p.name}")
            return False
        if (p.deadline_s is not None
                and now - self.start > p.deadline_s):
            TIMERS.incr(f"robust/retry_deadline/{p.name}")
            return False
        return True

    def backoff(self) -> None:
        self.policy.sleep(self.policy.delay(len(self.fail_times)))

    def describe(self) -> str:
        p = self.policy
        win = (f" within {p.window_s:.0f}s window"
               if p.window_s is not None else "")
        return f"{len(self.fail_times)}/{p.max_attempts}{win}"
