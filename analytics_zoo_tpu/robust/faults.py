"""FaultInjector — deterministic fault injection at named sites.

Chaos testing substrate: production code consults cheap hooks
(``fire(site)`` / ``inject(site)``) that are no-ops unless an injector
is active, and tests activate an injector with an explicit plan — *the
Nth call at this site fails in this way* — so every chaos scenario is
deterministic and replayable (no random sleeps, no flaky races).

Sites wired into the stack (call granularity in parentheses):

- ``checkpoint.write``    — one per ``save_pytree`` (torn file / raise)
- ``prefetch.producer``   — one per item the producer thread yields
- ``data.shard_upload``   — one per shard the STREAM uploader stages
                            (raise → uploader crash mid-rotation; the
                            Estimator falls back to the host path for
                            the epoch's remaining shards)
- ``data.shard_torn``     — one per shard staged (default action:
                            truncate the staged rows, caught by the
                            plan's shape validation exactly like a
                            real torn read)
- ``data.shard_skew``     — one per shard the STREAM uploader stages
                            (payload: seconds this host straggles
                            before staging; with ``exc`` it raises
                            instead — under multi-controller the
                            peers' ``zoo_data_shard`` barrier turns a
                            straggle past the deadline into
                            ``HostLostError``)
- ``data.host_lost``      — one per shard the STREAM uploader stages
                            (raise → typed ``HostLostError``,
                            simulating this host discovering a dead
                            peer during shard staging)
- ``estimator.step``      — one per train-step dispatch on the host
                            input paths (poison batch → NaN loss / raise)
- ``estimator.preempt``   — one per train-step; firing simulates SIGTERM
- ``estimator.resident_nan_rows`` — one per device-resident epoch fit
                            (payload: row indices to poison)
- ``dist.barrier_timeout``— one per ``core.context.dist_barrier`` call
                            (firing simulates a peer missing the
                            deadline: typed ``HostLostError``)
- ``dist.shard_write``    — one per distributed checkpoint shard write
                            (raise / ``torn`` truncation, mirroring
                            ``checkpoint.write`` at shard granularity)
- ``dist.host_lost``      — one per distributed save/restore entry
                            (raise → simulate discovering a dead peer
                            before any I/O happens)
- ``queue.io``            — one per retried serving-queue I/O operation
- ``serving.replica_crash``  — one per device-executor batch dispatch
                            (raise → breaker failure → quarantine)
- ``serving.replica_hang``   — one per harvest readback (payload:
                            seconds to wedge; the harvest watchdog must
                            abandon + requeue + respawn)
- ``serving.decode_error``   — one per record in the decode pool
- ``serving.queue_io``       — one per respond-stage ``set_result``
                            (above the backend's own ``queue.io`` site;
                            absorbed by the respond retry policy)
- ``serving.respond_error``  — one per respond-stage result format

Usage::

    fi = FaultInjector()
    fi.plan("checkpoint.write", at=2, action="torn")
    fi.plan("prefetch.producer", at=5, exc=RuntimeError("disk gone"))
    with fi:
        run_training()
    assert fi.fired["checkpoint.write"] == 1

Thread-safe: sites are consulted from producer/writer threads.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

from analytics_zoo_tpu.core.profiling import TIMERS

_ACTIVE: Optional["FaultInjector"] = None
_ACTIVE_LOCK = threading.Lock()


class _Plan:
    __slots__ = ("at", "exc", "action", "payload")

    def __init__(self, at, exc, action, payload):
        self.at = at            # set of 0-based call indices
        self.exc = exc          # exception instance/class to raise
        self.action = action    # site-specific action tag ("torn", "nan"...)
        self.payload = payload  # site-specific extra data


class FaultInjector:
    """Deterministic planned faults, keyed by (site, call index)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: Dict[str, List[_Plan]] = {}
        self._calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}

    # -- planning ----------------------------------------------------------
    def plan(self, site: str, at: Union[int, Iterable[int]] = 0, *,
             exc: Optional[BaseException] = None,
             action: Optional[str] = None,
             payload: Any = None) -> "FaultInjector":
        """Arm ``site`` to fail at the given 0-based call indices."""
        idx = {int(at)} if isinstance(at, (int, np.integer)) \
            else {int(i) for i in at}
        with self._lock:
            self._plans.setdefault(site, []).append(
                _Plan(idx, exc, action, payload))
        return self

    # -- activation --------------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is not None:
                raise RuntimeError("another FaultInjector is already active")
            _ACTIVE = self
        return self

    def __exit__(self, *exc_info) -> None:
        global _ACTIVE
        with _ACTIVE_LOCK:
            _ACTIVE = None

    # -- consultation ------------------------------------------------------
    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    def _consult(self, site: str) -> Optional[_Plan]:
        with self._lock:
            i = self._calls.get(site, 0)
            self._calls[site] = i + 1
            for plan in self._plans.get(site, ()):
                if i in plan.at:
                    self.fired[site] = self.fired.get(site, 0) + 1
                    TIMERS.incr(f"robust/fault_injected/{site}")
                    return plan
        return None


def get_injector() -> Optional[FaultInjector]:
    return _ACTIVE


def fire(site: str) -> Optional[_Plan]:
    """Consult ``site``; returns the matching plan if a fault fires at
    this call index (None otherwise, and always None when no injector
    is active — the happy-path cost is one global read)."""
    inj = _ACTIVE
    if inj is None:
        return None
    return inj._consult(site)


def inject(site: str) -> None:
    """Consult ``site`` and raise its planned exception if one fires
    (for sites whose only failure mode is an exception)."""
    plan = fire(site)
    if plan is not None and plan.exc is not None:
        raise plan.exc


def poison_nan(arrays):
    """NaN-fill every float array in ``arrays`` (non-float pass through
    untouched) — used by the ``estimator.step`` NaN action: NaN inputs
    guarantee a NaN loss through any differentiable model."""
    out = []
    for a in arrays:
        a = np.asarray(a)
        if np.issubdtype(a.dtype, np.floating):
            a = np.full_like(a, np.nan)
        out.append(a)
    return out
