"""Exceptions of the resilience layer.

``SERVING_ERROR_CODES`` is the registry of every stable ``code`` a
typed serving error payload may carry; docs/SERVING.md "Failure
semantics" pins the same table and ``tests/test_doc_drift.py``
machine-checks the two against each other.
"""

from __future__ import annotations

# code -> one-line meaning.  The single source of truth for the typed
# error payload contract (``deploy.serving.error_payload`` refuses no
# code, but every code the pipeline emits is declared here).
SERVING_ERROR_CODES = {
    "expired": "client TTL elapsed before the pipeline could serve it",
    "overloaded": "shed at admission: projected wait exceeds the TTL",
    "malformed": "record cannot be decoded/encoded for serving",
    "decode_error": "decode stage raised while materializing tensors",
    "model_error": "model forward failed (or no healthy replica)",
    "host_lost": "a peer process missed a coordination barrier deadline",
    "mesh_replica_lost": "the mesh replica lost a host; the whole "
                         "slice quarantined atomically",
    "internal": "unclassified server-side failure",
}


class ServingError(Exception):
    """Base of the serving pipeline's typed error contract: every error
    carries a stable ``code`` that rides the structured error payload
    (docs/SERVING.md "Failure semantics") so clients can branch on the
    failure class instead of parsing messages.  Codes in use:
    ``expired``, ``overloaded``, ``malformed``, ``decode_error``,
    ``model_error``, ``internal``."""

    code = "internal"

    def __init__(self, message: str, code: str = None):
        super().__init__(message)
        if code is not None:
            self.code = code


class DeadlineExpired(ServingError):
    """The record's client TTL elapsed before (or while) the pipeline
    could serve it — the work was shed, not attempted and failed."""

    code = "expired"


class ServingOverloaded(ServingError):
    """Shed at admission: the estimated pipeline wait already exceeds
    the record's remaining TTL, so serving it would only waste device
    time on an answer the client will have given up on."""

    code = "overloaded"


class MalformedRecordError(ServingError, ValueError):
    """The record cannot be decoded/encoded for serving (no tensor
    fields, non-encodable dtype, invalid TTL).  Raised client-side by
    ``InputQueue`` validation and worker-side by the decode stage."""

    code = "malformed"


class HostLostError(RuntimeError):
    """A peer process failed to reach a coordination barrier within its
    deadline and is presumed dead (killed, preempted, or partitioned).

    Raised by ``core.context.dist_barrier`` instead of hanging forever
    on a dead peer: the distributed checkpoint commit protocol bounds
    every cross-process wait by ``dist_barrier_timeout_s``, so a host
    dying mid-save surfaces as this typed error within the deadline —
    the surviving processes exit (or get restarted by the orchestrator)
    instead of wedging the whole job.

    Deliberately NOT retried by the Estimator's failure-retry loop: a
    dead peer cannot be fixed by a local restore-and-retry; the run
    must be relaunched (possibly at a different process count —
    restore reshards, see docs/ROBUSTNESS.md).
    """

    code = "host_lost"

    def __init__(self, message: str, barrier: str = "",
                 timeout_s: float = None):
        super().__init__(message)
        self.barrier = barrier
        self.timeout_s = timeout_s


class MeshReplicaLostError(HostLostError):
    """A mesh replica (one mesh slice serving as a single logical
    replica — docs/SERVING.md "Pod-scale serving") lost a member host
    or missed a dispatch barrier deadline.

    Carries the failure-domain coordinates every surviving host agrees
    on: ``replica_id`` (which mesh-replica slot), ``lost_process_id``
    (the presumed-dead peer, -1 when only the barrier timed out), and
    ``epoch`` (the roster epoch the loss was observed at — the
    quarantine broadcast trips each breaker at most once per epoch, so
    concurrent observers of the same death collapse into ONE atomic
    quarantine).  In-flight batches on the lost replica requeue onto
    healthy replicas or terminate as typed payloads with this code.
    """

    code = "mesh_replica_lost"

    def __init__(self, message: str, replica_id: int = -1,
                 lost_process_id: int = -1, epoch: int = 0,
                 barrier: str = "", timeout_s: float = None):
        super().__init__(message, barrier=barrier, timeout_s=timeout_s)
        self.replica_id = int(replica_id)
        self.lost_process_id = int(lost_process_id)
        self.epoch = int(epoch)


class TrainingPreempted(Exception):
    """Raised by ``Estimator.fit`` after a preemption (SIGTERM or an
    injected fault) has been handled: the final synchronous checkpoint
    is already on disk when this propagates.  ``fit(resume=True)``
    continues the run exactly where it left off.

    Deliberately NOT retried by the failure-retry loop — a preemption
    means the host is going away.
    """

    def __init__(self, message: str, step: int = -1):
        super().__init__(message)
        self.step = step
