"""Exceptions of the resilience layer."""

from __future__ import annotations


class TrainingPreempted(Exception):
    """Raised by ``Estimator.fit`` after a preemption (SIGTERM or an
    injected fault) has been handled: the final synchronous checkpoint
    is already on disk when this propagates.  ``fit(resume=True)``
    continues the run exactly where it left off.

    Deliberately NOT retried by the failure-retry loop — a preemption
    means the host is going away.
    """

    def __init__(self, message: str, step: int = -1):
        super().__init__(message)
        self.step = step
