"""Exceptions of the resilience layer."""

from __future__ import annotations


class ServingError(Exception):
    """Base of the serving pipeline's typed error contract: every error
    carries a stable ``code`` that rides the structured error payload
    (docs/SERVING.md "Failure semantics") so clients can branch on the
    failure class instead of parsing messages.  Codes in use:
    ``expired``, ``overloaded``, ``malformed``, ``decode_error``,
    ``model_error``, ``internal``."""

    code = "internal"

    def __init__(self, message: str, code: str = None):
        super().__init__(message)
        if code is not None:
            self.code = code


class DeadlineExpired(ServingError):
    """The record's client TTL elapsed before (or while) the pipeline
    could serve it — the work was shed, not attempted and failed."""

    code = "expired"


class ServingOverloaded(ServingError):
    """Shed at admission: the estimated pipeline wait already exceeds
    the record's remaining TTL, so serving it would only waste device
    time on an answer the client will have given up on."""

    code = "overloaded"


class MalformedRecordError(ServingError, ValueError):
    """The record cannot be decoded/encoded for serving (no tensor
    fields, non-encodable dtype, invalid TTL).  Raised client-side by
    ``InputQueue`` validation and worker-side by the decode stage."""

    code = "malformed"


class HostLostError(RuntimeError):
    """A peer process failed to reach a coordination barrier within its
    deadline and is presumed dead (killed, preempted, or partitioned).

    Raised by ``core.context.dist_barrier`` instead of hanging forever
    on a dead peer: the distributed checkpoint commit protocol bounds
    every cross-process wait by ``dist_barrier_timeout_s``, so a host
    dying mid-save surfaces as this typed error within the deadline —
    the surviving processes exit (or get restarted by the orchestrator)
    instead of wedging the whole job.

    Deliberately NOT retried by the Estimator's failure-retry loop: a
    dead peer cannot be fixed by a local restore-and-retry; the run
    must be relaunched (possibly at a different process count —
    restore reshards, see docs/ROBUSTNESS.md).
    """

    def __init__(self, message: str, barrier: str = "",
                 timeout_s: float = None):
        super().__init__(message)
        self.barrier = barrier
        self.timeout_s = timeout_s


class TrainingPreempted(Exception):
    """Raised by ``Estimator.fit`` after a preemption (SIGTERM or an
    injected fault) has been handled: the final synchronous checkpoint
    is already on disk when this propagates.  ``fit(resume=True)``
    continues the run exactly where it left off.

    Deliberately NOT retried by the failure-retry loop — a preemption
    means the host is going away.
    """

    def __init__(self, message: str, step: int = -1):
        super().__init__(message)
        self.step = step
