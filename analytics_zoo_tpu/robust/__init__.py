"""Resilience substrate shared by train/, deploy/ and data/.

The reference inherits fault tolerance from Spark (task retry, driver
recovery, lineage recompute under ``DistriOptimizer``).  The TPU-native
single-controller stack has no scheduler underneath it, so the
equivalent machinery lives here, as three reusable pieces:

- :class:`RetryPolicy` — exponential backoff + jitter + deadline +
  sliding failure window, used by the Estimator's failure-retry loop,
  checkpoint writes and the serving queue I/O.
- :class:`FaultInjector` — deterministic fault injection at named sites
  (torn checkpoint files, prefetch producer crashes, NaN losses,
  simulated preemptions), the substrate of the chaos test suite.
- :class:`TrainingPreempted` — raised by ``Estimator.fit`` after the
  preemption handler has flushed its final synchronous checkpoint.
- :class:`CircuitBreaker` — consecutive-failure health state machine
  (closed → open → half-open probe) guarding each serving model replica.
- :class:`Supervisor` / :class:`Heartbeat` — the serving pipeline's
  self-healing loop: background repair checks (replica rebuild, harvest
  watchdog, stage restart) plus per-stage liveness stamps.
- The :class:`ServingError` family — the typed error codes riding the
  serving pipeline's structured error payloads.

See docs/ROBUSTNESS.md for the end-to-end guarantees.
"""

from analytics_zoo_tpu.robust.breaker import (CircuitBreaker,
                                              QuarantineBroadcast)
from analytics_zoo_tpu.robust.errors import (SERVING_ERROR_CODES,
                                             DeadlineExpired, HostLostError,
                                             MalformedRecordError,
                                             MeshReplicaLostError,
                                             ServingError, ServingOverloaded,
                                             TrainingPreempted)
from analytics_zoo_tpu.robust.faults import FaultInjector, fire, inject
from analytics_zoo_tpu.robust.retry import (RetryDeadlineExceeded,
                                            RetryPolicy, RetryState)
from analytics_zoo_tpu.robust.supervisor import Heartbeat, Supervisor

__all__ = [
    "RetryPolicy", "RetryState", "RetryDeadlineExceeded",
    "FaultInjector", "fire", "inject", "TrainingPreempted",
    "HostLostError", "MeshReplicaLostError", "SERVING_ERROR_CODES",
    "CircuitBreaker", "QuarantineBroadcast", "Supervisor", "Heartbeat",
    "ServingError", "DeadlineExpired", "ServingOverloaded",
    "MalformedRecordError",
]
