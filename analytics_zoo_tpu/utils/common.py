"""Small shared utilities (reference pyzoo/zoo/common/utils.py file
helpers — minus the Py4J plumbing, which has no equivalent here)."""

from __future__ import annotations

import glob
import os
from typing import Any, List


def get_file_list(path: str, recursive: bool = False) -> List[str]:
    """List files under a path/glob (reference get_file_list)."""
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "*")
        return sorted(f for f in glob.glob(pattern, recursive=recursive)
                      if os.path.isfile(f))
    return sorted(f for f in glob.glob(path) if os.path.isfile(f))


def to_list(x: Any) -> List[Any]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]
