"""Structure flatten/pack (reference pyzoo/zoo/util/nest.py — the
tf.nest contract over lists/tuples/dicts)."""

from __future__ import annotations

from typing import Any, Callable, List


def _is_structure(x: Any) -> bool:
    return isinstance(x, (list, tuple, dict))


def flatten(structure: Any) -> List[Any]:
    """Depth-first leaf list; dict leaves ordered by sorted key
    (tf.nest semantics)."""
    if not _is_structure(structure):
        return [structure]
    if isinstance(structure, dict):
        items = [structure[k] for k in sorted(structure)]
    else:
        items = structure
    out: List[Any] = []
    for v in items:
        out.extend(flatten(v))
    return out


def pack_sequence_as(structure: Any, flat: List[Any]) -> Any:
    """Inverse of flatten: rebuild ``structure``'s shape from ``flat``."""
    def build(s, it):
        if not _is_structure(s):
            return next(it)
        if isinstance(s, dict):
            return type(s)((k, build(s[k], it)) for k in sorted(s))
        vals = [build(v, it) for v in s]
        return type(s)(vals) if not isinstance(s, tuple) else tuple(vals)

    it = iter(flat)
    try:
        packed = build(structure, it)
    except (StopIteration, RuntimeError) as e:
        # RuntimeError covers StopIteration surfacing through generators
        raise ValueError(
            f"too few leaves ({len(flat)}) for structure") from e
    leftovers = list(it)
    if leftovers:
        raise ValueError(f"{len(leftovers)} extra leaves for structure")
    return packed


def map_structure(fn: Callable, structure: Any) -> Any:
    return pack_sequence_as(structure, [fn(x) for x in flatten(structure)])
