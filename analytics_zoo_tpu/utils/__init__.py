"""Utility surface (reference pyzoo/zoo/util/: nest.py structure
flatten/pack, tf.py graph export helpers, common file utils).

``nest`` flatten/pack mirrors the reference's nest.py (itself the
tf.nest contract); graph export collapses into
``nn.net.Net.export_tf_saved_model`` (jax2tf) — the reference's
freeze-graph machinery (util/tf.py:50-199) has no meaning without a TF
session in the loop.
"""

from analytics_zoo_tpu.utils.common import get_file_list, to_list
from analytics_zoo_tpu.utils.nest import (flatten, map_structure,
                                          pack_sequence_as)

__all__ = ["flatten", "pack_sequence_as", "map_structure",
           "get_file_list", "to_list"]
