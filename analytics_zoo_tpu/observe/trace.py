"""Structured tracing: trace/span ids that ride along with work as it
hops threads, plus a bounded ring of completed spans so any request's
timeline is reconstructable after the fact.

The model is deliberately small:

- a **trace** is a string id grouping the spans of one logical unit of
  work (one serving record, one ``Estimator.fit`` run, one standalone
  checkpoint op);
- a **span** is a named interval inside a trace with a parent pointer
  (``parent`` is the parent span's ``sid``, ``None`` for the root), a
  terminal ``status`` (``"ok"`` or a typed error/shed code such as
  ``"expired"``), and free-form ``attrs``;
- completed spans land in a bounded deque (the *span ring*); live spans
  sit in a side table until ended.  Nothing is sampled away below the
  ring bound — eviction is strictly oldest-first.

Spans are cheap (a dict append under a lock) and are safe to create on
any thread: the serving pipeline starts a root span at queue-claim time
and threads the ``(trace, sid)`` pair through the decode pool, the
DynamicBatcher and the DeviceExecutor to the respond pool.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Span", "Tracer", "TRACER", "span", "find_orphans"]

_DEFAULT_RING = int(os.environ.get("ZOO_OBSERVE_SPAN_RING", "4096"))


class Span:
    """One timed interval.  Created via ``Tracer.start``; call
    ``end(status, **attrs)`` exactly once (double-end is a no-op)."""

    __slots__ = ("trace", "sid", "parent", "name", "t0", "t1", "status",
                 "attrs", "_tracer")

    def __init__(self, tracer: "Tracer", trace: str, sid: int,
                 parent: Optional[int], name: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.trace = trace
        self.sid = sid
        self.parent = parent
        self.name = name
        self.t0 = time.time()
        self.t1: Optional[float] = None
        self.status = "open"
        self.attrs = attrs

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def end(self, status: str = "ok", **attrs: Any) -> None:
        self._tracer._finish(self, status, attrs)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace": self.trace, "sid": self.sid, "parent": self.parent,
            "name": self.name, "t0": self.t0, "t1": self.t1,
            "duration_s": self.duration_s, "status": self.status,
            "attrs": dict(self.attrs),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name} trace={self.trace} sid={self.sid} "
                f"parent={self.parent} status={self.status})")

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.end(status="error", error=repr(exc))
        else:
            self.end()


class Tracer:
    """Issues spans and keeps the bounded ring of completed ones."""

    def __init__(self, ring: int = _DEFAULT_RING):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._done: deque = deque(maxlen=max(16, int(ring)))
        self._active: Dict[int, Span] = {}
        self._sinks: List[Callable[[Dict[str, Any]], None]] = []

    # -- span lifecycle ----------------------------------------------------

    def start(self, name: str, trace: Optional[str] = None,
              parent: Optional[int] = None, **attrs: Any) -> Span:
        sid = next(self._ids)
        sp = Span(self, trace or f"t{sid}", sid, parent, name, attrs)
        with self._lock:
            self._active[sid] = sp
        return sp

    def _finish(self, sp: Span, status: str,
                attrs: Dict[str, Any]) -> None:
        sinks: List[Callable[[Dict[str, Any]], None]] = []
        with self._lock:
            if sp.sid not in self._active:
                return  # already ended; keep the first terminal status
            del self._active[sp.sid]
            sp.t1 = time.time()
            sp.status = status
            if attrs:
                sp.attrs.update(attrs)
            self._done.append(sp)
            sinks = list(self._sinks)
        if sinks:
            d = sp.to_dict()
            for fn in sinks:
                try:
                    fn(d)
                except Exception:
                    pass  # a broken sink must never break the pipeline

    # -- sinks -------------------------------------------------------------

    def add_sink(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    # -- introspection -----------------------------------------------------

    def resize(self, ring: int) -> None:
        with self._lock:
            if self._done.maxlen != max(16, int(ring)):
                self._done = deque(self._done, maxlen=max(16, int(ring)))

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def completed_count(self) -> int:
        return len(self._done)

    def ring_size(self) -> int:
        return self._done.maxlen or 0

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Completed spans, oldest first, as plain dicts."""
        with self._lock:
            spans = list(self._done)
        if limit is not None:
            spans = spans[-limit:]
        return [s.to_dict() for s in spans]

    def spans(self, trace: str) -> List[Dict[str, Any]]:
        """All completed spans of one trace, ordered by (t0, sid)."""
        with self._lock:
            hits = [s for s in self._done if s.trace == trace]
        hits.sort(key=lambda s: (s.t0, s.sid))
        return [s.to_dict() for s in hits]

    def verify_chain(self, trace: str) -> Dict[str, Any]:
        """Reconstruct one trace and check its structural integrity.

        ``complete`` means: a root span exists (parent None), every
        non-root span's parent sid is present in the trace, and the
        root carries a terminal status (anything but ``"open"``).
        """
        spans = self.spans(trace)
        roots = [s for s in spans if s["parent"] is None]
        orphans = find_orphans(spans)
        root = roots[0] if roots else None
        return {
            "trace": trace,
            "spans": spans,
            "root": root,
            "orphans": orphans,
            "terminal": root["status"] if root else None,
            "complete": bool(root) and not orphans and
            bool(root) and root["status"] != "open",
        }

    def clear(self) -> None:
        with self._lock:
            self._done.clear()
            self._active.clear()


def find_orphans(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans whose parent sid is missing from the same span list."""
    sids = {s["sid"] for s in spans}
    return [s for s in spans
            if s["parent"] is not None and s["parent"] not in sids]


TRACER = Tracer()


@contextmanager
def span(name: str, trace: Optional[str] = None,
         parent: Optional[int] = None, tracer: Optional[Tracer] = None,
         **attrs: Any):
    """``with span("train/epoch", trace=t, epoch=3) as sp: ...`` — ends
    with status ``"ok"``, or ``"error"`` if the body raises."""
    sp = (tracer or TRACER).start(name, trace=trace, parent=parent,
                                  **attrs)
    try:
        yield sp
    except BaseException as e:
        sp.end(status="error", error=repr(e))
        raise
    else:
        sp.end()
