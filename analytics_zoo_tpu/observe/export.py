"""Exporters for the observability layer.

Three formats, one registry:

- ``to_prometheus`` — the Prometheus text exposition format (counters
  and gauges verbatim; histograms as summaries with ``quantile``
  labels plus ``_sum``/``_count``).  ``parse_prometheus`` is the exact
  inverse used by the round-trip tests.
- ``JsonlEventLog`` — an append-only structured event log (one JSON
  object per line: spans as they complete, metric dumps, flight
  records, free-form markers).  Attach to a ``Tracer`` to stream spans.
- ``publish_to_summary`` — bridges gauges / counters / histogram
  percentiles into the no-TF TensorBoard writer (``core/summary.py``)
  so training dashboards see the same series that serving ``health()``
  exposes.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, Optional, Tuple

from analytics_zoo_tpu.observe.metrics import (METRICS, MetricsRegistry,
                                               render_series)
from analytics_zoo_tpu.observe.trace import Tracer

__all__ = ["to_prometheus", "parse_prometheus", "JsonlEventLog",
           "publish_to_summary"]


def _esc(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    f = float(value)
    if f != f:
        return "NaN"
    return repr(f) if f != int(f) else str(int(f))


def _render(name: str, labels, extra: Tuple[Tuple[str, str], ...] = ()
            ) -> str:
    pairs = tuple(labels) + tuple(extra)
    if not pairs:
        return name
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in pairs)
    return f"{name}{{{inner}}}"


def to_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Dump the registry in Prometheus text format (version 0.0.4)."""
    reg = registry if registry is not None else METRICS
    lines = []
    for name, kind, help_, series in reg.collect():
        if help_:
            lines.append(f"# HELP {name} {_esc(help_)}")
        ptype = "summary" if kind == "histogram" else kind
        lines.append(f"# TYPE {name} {ptype}")
        for labels, value in series:
            if kind == "histogram":
                for q, key in (("0.5", "p50"), ("0.99", "p99")):
                    if value[key] is not None:
                        lines.append(
                            f"{_render(name, labels, (('quantile', q),))}"
                            f" {_fmt(value[key])}")
                lines.append(
                    f"{_render(name + '_sum', labels)} "
                    f"{_fmt(value['sum'])}")
                lines.append(
                    f"{_render(name + '_count', labels)} "
                    f"{_fmt(value['count'])}")
            else:
                lines.append(f"{_render(name, labels)} {_fmt(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


_LINE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$')
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unesc(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Inverse of ``to_prometheus``: returns ``{"series": {rendered ->
    float}, "types": {name -> type}}``.  Raises ``ValueError`` on any
    line that is neither a comment nor a well-formed sample."""
    series: Dict[str, float] = {}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable prometheus line: {raw!r}")
        labels = tuple(sorted(
            (k, _unesc(v))
            for k, v in _LABEL_RE.findall(m.group("labels") or "")))
        series[render_series(m.group("name"), labels)] = \
            float(m.group("value"))
    return {"series": series, "types": types}


class JsonlEventLog:
    """Append-only JSONL event stream; one object per line, each with
    ``ts`` and ``kind``.  Thread-safe; a write failure disables the log
    rather than poisoning the emitting pipeline."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, kind: str, **payload: Any) -> None:
        rec = {"ts": time.time(), "kind": kind}
        rec.update(payload)
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                self._close_locked()

    def span_sink(self, span_dict: Dict[str, Any]) -> None:
        """``tracer.add_sink(log.span_sink)`` streams completed spans."""
        self.emit("span", span=span_dict)

    def metrics_dump(self, registry: Optional[MetricsRegistry] = None,
                     delta: Optional[Dict[str, Any]] = None) -> None:
        reg = registry if registry is not None else METRICS
        self.emit("metrics", dump=delta if delta is not None
                  else reg.delta(None))

    def attach(self, tracer: Tracer) -> None:
        tracer.add_sink(self.span_sink)

    def detach(self, tracer: Tracer) -> None:
        tracer.remove_sink(self.span_sink)

    def _close_locked(self) -> None:
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._close_locked()


def publish_to_summary(writer, step: int,
                       registry: Optional[MetricsRegistry] = None,
                       prefix: str = "") -> int:
    """Write the registry into a ``core.summary.SummaryWriter``.

    Gauges and counters land under their rendered series name;
    histograms land as ``<series>/p50`` and ``<series>/p99``.  Returns
    the number of scalars written.  ``prefix`` filters by metric name
    (e.g. ``"train_"``).
    """
    reg = registry if registry is not None else METRICS
    wrote = 0
    for name, kind, _help, series in reg.collect():
        if prefix and not name.startswith(prefix):
            continue
        for labels, value in series:
            tag = _render(name, labels)
            if kind == "histogram":
                for key in ("p50", "p99"):
                    if value[key] is not None:
                        writer.add_scalar(f"{tag}/{key}", value[key],
                                          step)
                        wrote += 1
            else:
                writer.add_scalar(tag, float(value), step)
                wrote += 1
    return wrote
