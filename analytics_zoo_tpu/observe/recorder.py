"""The flight recorder: an SLO watcher that captures evidence *at the
moment things go wrong* instead of asking the operator to reproduce.

``FlightRecorder.check`` is designed to run as a ``Supervisor`` check.
It reads the labeled-metrics registry in **windows** (snapshot/delta):
every ``window_s`` it closes the current window and evaluates

- each ``SLO`` — a p99 bound on one histogram series (e.g.
  ``serving_stage_seconds{stage=e2e}`` p99 ≤ 50 ms, with a minimum
  sample count so idle windows can't trip);
- each *watched counter* — any positive window delta trips (e.g.
  ``breaker_transitions_total{to=open}``: a breaker trip is itself an
  incident worth a recording).

A trip produces a **flight record**: the window's metrics delta, the
current gauges, and the offending spans (slowest-first plus every
non-ok terminal) pulled from the tracer ring — written as JSON to
``out_dir`` (if set) and kept in a small in-memory deque either way.
Optionally it also arms a short ``jax.profiler`` device trace via
``core.profiling.trace`` on a background thread, so a breach leaves a
real profile behind.  A cooldown stops a sustained breach from
producing a snapshot storm.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from analytics_zoo_tpu.observe import metrics as _m
from analytics_zoo_tpu.observe.metrics import (METRICS, MetricsRegistry,
                                               render_series)
from analytics_zoo_tpu.observe.trace import TRACER, Tracer

__all__ = ["SLO", "FlightRecorder"]


class SLO:
    """A p99 bound on one histogram series over the watch window."""

    def __init__(self, name: str, metric: str,
                 labels: Optional[Dict[str, str]] = None,
                 p99_ms: float = 0.0, min_count: int = 10):
        self.name = name
        self.metric = metric
        self.labels = dict(labels or {})
        self.p99_ms = float(p99_ms)
        self.min_count = int(min_count)
        self.series = render_series(
            metric, tuple(sorted((k, str(v))
                                 for k, v in self.labels.items())))

    def breached(self, delta: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        hist = delta["histograms"].get(self.series)
        if not hist or hist["count"] < self.min_count:
            return None
        p99 = hist.get("p99")
        if p99 is None or p99 * 1000.0 <= self.p99_ms:
            return None
        return {"slo": self.name, "series": self.series,
                "p99_ms": p99 * 1000.0, "limit_ms": self.p99_ms,
                "count": hist["count"]}


class FlightRecorder:
    def __init__(self, slos: Sequence[SLO] = (),
                 watch_counters: Sequence[Tuple[str,
                                                Dict[str, str]]] = (),
                 window_s: float = 5.0,
                 out_dir: Optional[str] = None,
                 tracer: Optional[Tracer] = None,
                 registry: Optional[MetricsRegistry] = None,
                 profile_dir: Optional[str] = None,
                 profile_ms: float = 200.0,
                 cooldown_s: float = 30.0,
                 max_spans: int = 200,
                 clock: Callable[[], float] = time.monotonic):
        self.slos = list(slos)
        self.watch_counters = [
            (name, dict(labels or {})) for name, labels in watch_counters]
        self.window_s = float(window_s)
        self.out_dir = out_dir
        self.profile_dir = profile_dir
        self.profile_ms = float(profile_ms)
        self.cooldown_s = float(cooldown_s)
        self.max_spans = int(max_spans)
        self._tracer = tracer if tracer is not None else TRACER
        self._registry = registry if registry is not None else METRICS
        self._clock = clock
        self._lock = threading.Lock()
        self._win_snap = None
        self._win_t0: Optional[float] = None
        self._last_trip: Optional[float] = None
        self._records: deque = deque(maxlen=8)
        self._seq = 0
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)

    # -- the supervisor check ----------------------------------------------

    def check(self) -> Optional[str]:
        """Close the window if due and evaluate; returns the written
        flight-record path (or reason) when one was captured."""
        now = self._clock()
        with self._lock:
            if self._win_snap is None:
                self._win_snap = self._registry.snapshot()
                self._win_t0 = now
                return None
            if now - self._win_t0 < self.window_s:
                return None
            delta = self._registry.delta(self._win_snap)
            self._win_snap = self._registry.snapshot()
            self._win_t0 = now
            reasons = self._evaluate_locked(delta)
            if not reasons:
                return None
            if self._last_trip is not None and \
                    now - self._last_trip < self.cooldown_s:
                return None
            self._last_trip = now
            rec = self._capture_locked("slo_breach", reasons, delta)
        self._after_capture(rec)
        return rec.get("path") or rec["reason"]

    def _evaluate_locked(self, delta: Dict[str, Any]) -> List[Dict]:
        reasons = []
        for slo in self.slos:
            hit = slo.breached(delta)
            if hit:
                reasons.append(hit)
        for name, labels in self.watch_counters:
            want = tuple(sorted((k, str(v)) for k, v in labels.items()))
            tripped = 0
            for series, n in delta["counters"].items():
                if not series.startswith(name):
                    continue
                if all(f'{k}="{v}"' in series for k, v in want) and n > 0:
                    tripped += n
            if tripped:
                reasons.append({"counter": render_series(name, want),
                                "delta": tripped})
        return reasons

    # -- manual trigger (breaker trips, operator request) ------------------

    def trigger(self, reason: str,
                detail: Optional[Dict[str, Any]] = None) -> Optional[str]:
        now = self._clock()
        with self._lock:
            if self._last_trip is not None and \
                    now - self._last_trip < self.cooldown_s:
                return None
            self._last_trip = now
            delta = self._registry.delta(self._win_snap)
            rec = self._capture_locked(reason, [detail or {}], delta)
        self._after_capture(rec)
        return rec.get("path") or rec["reason"]

    # -- capture -----------------------------------------------------------

    def _offending_spans(self) -> List[Dict[str, Any]]:
        win_t0 = time.time() - self.window_s * 2
        spans = [s for s in self._tracer.snapshot()
                 if s["t1"] is not None and s["t1"] >= win_t0]
        bad = [s for s in spans if s["status"] not in ("ok", "open")]
        slow = sorted((s for s in spans if s["status"] == "ok"),
                      key=lambda s: -(s["duration_s"] or 0.0))
        picked = (bad + slow)[: self.max_spans]
        picked.sort(key=lambda s: (s["t0"], s["sid"]))
        return picked

    def _capture_locked(self, reason: str, details: List[Dict],
                        delta: Dict[str, Any]) -> Dict[str, Any]:
        self._seq += 1
        rec: Dict[str, Any] = {
            "reason": reason,
            "details": details,
            "ts": time.time(),
            "seq": self._seq,
            "metrics_delta": delta,
            "spans": self._offending_spans(),
            "spans_active": self._tracer.active_count(),
        }
        if self.out_dir:
            path = os.path.join(self.out_dir,
                                f"flight_{self._seq:04d}.json")
            try:
                with open(path, "w", encoding="utf-8") as f:
                    json.dump(rec, f, default=str, indent=1)
                rec["path"] = path
            except OSError:
                pass  # capture still lives in memory
        self._records.append(rec)
        return rec

    def _after_capture(self, rec: Dict[str, Any]) -> None:
        _m.count("observe_flight_records_total",
                 flat="observe/flight_records", reason=rec["reason"])
        if self.profile_dir:
            t = threading.Thread(target=self._profile_once,
                                 name="flight-profiler", daemon=True)
            t.start()

    def _profile_once(self) -> None:
        """Arm a short device trace; must never propagate a failure."""
        try:
            from analytics_zoo_tpu.core import profiling
            with profiling.trace(self.profile_dir):
                time.sleep(self.profile_ms / 1000.0)
        except Exception:
            pass

    # -- introspection -----------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def last_record(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._records[-1] if self._records else None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            last = self._records[-1] if self._records else None
            return {
                "flight_records": self._seq,
                "last_reason": last["reason"] if last else None,
                "last_path": (last or {}).get("path"),
                "window_s": self.window_s,
                "slos": [s.name for s in self.slos],
            }
