"""analytics_zoo_tpu.observe — the unified observability layer.

Four parts (see docs/OBSERVABILITY.md):

- ``trace``    — trace/span ids + the bounded span ring (``TRACER``)
- ``metrics``  — labeled counters/gauges/histograms with
  snapshot/delta semantics (``METRICS``), mirrored onto the legacy
  flat ``core.profiling.TIMERS`` names via the ``flat=`` helpers
- ``export``   — Prometheus text dump, JSONL event log, TensorBoard
  bridge
- ``recorder`` — the SLO-watching flight recorder
"""

from analytics_zoo_tpu.observe.export import (JsonlEventLog,
                                              parse_prometheus,
                                              publish_to_summary,
                                              to_prometheus)
from analytics_zoo_tpu.observe.metrics import (CATALOG, METRICS,
                                               MetricsRegistry,
                                               MetricsSnapshot, count,
                                               observe, render_series,
                                               set_gauge, time_stage)
from analytics_zoo_tpu.observe.recorder import SLO, FlightRecorder
from analytics_zoo_tpu.observe.trace import (TRACER, Span, Tracer,
                                             find_orphans, span)

__all__ = [
    "TRACER", "Span", "Tracer", "span", "find_orphans",
    "CATALOG", "METRICS", "MetricsRegistry", "MetricsSnapshot",
    "count", "observe", "set_gauge", "time_stage", "render_series",
    "JsonlEventLog", "to_prometheus", "parse_prometheus",
    "publish_to_summary",
    "SLO", "FlightRecorder",
]
