"""Labeled metrics: counters / gauges / histograms with label sets
(``stage=decode``, ``replica=2``, ``code=expired``), layered over the
flat ``core.profiling.Timers`` registry.

Two things distinguish this from the flat Timers bag:

- **labels** — one metric name fans out into series keyed by sorted
  ``(key, value)`` label tuples, so dashboards and tests can slice
  ``serving_shed_total`` by ``code`` instead of pattern-matching flat
  counter names;
- **snapshot/delta semantics** — ``snapshot()`` marks a point in time
  and ``delta(snap)`` reads the *window* since it (counter increments,
  current gauges, histogram percentiles computed over only the samples
  observed inside the window).  The supervisor's SLO watcher and tests
  read windows, not process-lifetime totals.

The migration story for existing call sites is the ``flat=`` mirror on
the module-level helpers: ``count("serving_shed_total", code="expired",
flat="serving/shed_expired")`` bumps the labeled series *and* the
legacy flat counter, so ``health()`` sections and older tests keep
working while new consumers read labels.

Every metric name the repo emits is declared in ``CATALOG`` below;
``docs/OBSERVABILITY.md`` pins the same list and
``tests/test_doc_drift.py`` machine-checks the two against each other.
Emitting an undeclared name still works but is itself counted
(``observe_undeclared_metrics_total``) so drift is visible.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Tuple

from analytics_zoo_tpu.core.profiling import TIMERS

__all__ = ["CATALOG", "MetricsRegistry", "MetricsSnapshot", "METRICS",
           "count", "set_gauge", "observe", "time_stage", "render_series"]

LabelTuple = Tuple[Tuple[str, str], ...]
SeriesKey = Tuple[str, LabelTuple]

_HIST_RING = 1024

# name -> (type, help, allowed label keys).  The single source of truth
# for metric names; OBSERVABILITY.md pins this table and test_doc_drift
# checks it.
CATALOG: Dict[str, Tuple[str, str, Tuple[str, ...]]] = {
    # serving pipeline (the ``model`` label names the serving model in a
    # multi-model pipeline; single-model paths emit model="default")
    "serving_stage_seconds": (
        "histogram", "per-stage latency of the serving pipeline",
        ("model", "stage")),
    "serving_records_total": (
        "counter", "records answered, by outcome (ok|error)",
        ("model", "outcome")),
    "serving_shed_total": (
        "counter", "records shed before the device, by typed code",
        ("code", "model")),
    "serving_errors_total": (
        "counter", "typed error payloads returned, by code",
        ("code", "model")),
    "serving_batches_total": (
        "counter", "batches dispatched to a device replica",
        ("model", "replica")),
    "serving_batch_rows_total": (
        "counter", "rows dispatched to a device replica",
        ("model", "replica")),
    "serving_batch_retries_total": (
        "counter", "batches retried on a healthy peer replica",
        ("model",)),
    "serving_long_doc_batches_total": (
        "counter", "batches routed to a long-document mesh replica "
        "(sequence length >= LONG_DOC_TOKENS)", ("model",)),
    "serving_replica_events_total": (
        "counter", "replica lifecycle events "
        "(quarantined|restored|rebuilt)", ("event", "model", "replica")),
    "serving_mesh_replica_events_total": (
        "counter", "mesh-replica (pod failure domain) lifecycle events "
        "(quarantined|shed|rebuilt|host_lost)", ("event", "model")),
    "serving_shm_lease_reclaims_total": (
        "counter", "shm result-slot leases harvested because the owner "
        "process died before get_result", ()),
    "serving_stage_restarts_total": (
        "counter", "dead stage threads respawned by the supervisor",
        ("stage",)),
    "serving_inflight": (
        "gauge", "records currently inside the pipeline", ()),
    "serving_replicas_healthy": (
        "gauge", "replicas currently accepting batches", ("model",)),
    "serving_compile_cache_events_total": (
        "counter", "persistent AOT compile-cache outcomes "
        "(hit|miss|corrupt|version_skew)", ("event", "model")),
    "serving_autoscale_actions_total": (
        "counter", "autoscaler decisions applied, by resource "
        "(decode_workers|replicas|batch_deadline) and direction "
        "(up|down)", ("direction", "model", "resource")),
    "inference_novel_batch_shapes_total": (
        "counter", "novel batch signatures dispatched (one per live XLA "
        "compile)", ("model",)),
    "inference_compile_count": (
        "gauge", "distinct live-compiled program shapes "
        "(cache-warmed shapes excluded)", ("model",)),
    "serving_heartbeat_age_seconds": (
        "gauge", "age of each stage's last heartbeat", ("stage",)),
    "serving_wire_bytes_total": (
        "counter", "tensor payload bytes crossing the serving wire, by "
        "codec (json_b64|binary|file|shm)", ("codec",)),
    "serving_codec_seconds": (
        "histogram", "wire codec encode/decode wall time, by codec and "
        "direction", ("codec", "op")),
    # load harness (analytics_zoo_tpu/loadgen — docs/LOADGEN.md)
    "loadgen_requests_total": (
        "counter", "requests offered by the open-loop generator, by "
        "traffic leg and target model", ("leg", "model")),
    "loadgen_outcomes_total": (
        "counter", "terminal outcomes observed by loadgen clients "
        "(ok | typed error code | lost)", ("model", "outcome")),
    "loadgen_schedule_lag_seconds": (
        "histogram", "how far behind its Poisson slot each send fired "
        "(open-loop honesty: stays flat while the server stalls)",
        ("leg",)),
    "loadgen_open_loop_drops_total": (
        "counter", "scheduled sends the transport refused (ring full, "
        "queue closed) — the schedule moves on instead of blocking",
        ("leg",)),
    # robustness
    "breaker_transitions_total": (
        "counter", "circuit breaker state transitions",
        ("breaker", "to")),
    "supervisor_check_errors_total": (
        "counter", "supervisor checks that raised", ("check",)),
    # training
    "train_steps_total": (
        "counter", "optimizer steps dispatched, by dispatch kind "
        "(1|K|epoch|shard)", ("kind",)),
    "train_step_seconds": (
        "histogram", "wall time of one step dispatch", ("kind",)),
    "train_epoch_seconds": ("histogram", "wall time of one epoch", ()),
    "train_loss": ("gauge", "last epoch mean loss", ()),
    "train_throughput_rows_per_s": (
        "gauge", "last epoch training throughput", ()),
    # data pipeline (STREAM tier + host prefetch)
    "data_shard_upload_ms": (
        "histogram", "host->device staging time per streamed shard "
        "(load + encode + device_put, paid on the uploader thread)",
        ()),
    "data_shard_wait_ms": (
        "histogram", "time the training loop blocked waiting for a "
        "shard lease (steady-state overlap target: ~0)", ()),
    "data_stream_overlap_frac": (
        "gauge", "fraction of shard-upload time hidden behind compute "
        "over the last fit (1 - wait/upload, clipped to [0, 1])", ()),
    "data_decode_bytes_total": (
        "counter", "compressed shard bytes decoded in-kernel, by cache "
        "dtype (uint8|int8)", ("dtype",)),
    "data_stream_fallbacks_total": (
        "counter", "mid-rotation uploader failures absorbed by the "
        "host path, by reason", ("reason",)),
    "data_path_selected_total": (
        "counter", "FeatureSet input-path router decisions, by chosen "
        "path and bounded reason code (cache_level_host | fits_budget "
        "| over_budget | sliced | stream_infeasible)",
        ("path", "reason")),
    "table_placement_selected_total": (
        "counter", "embedding-table placement router decisions "
        "(replicated | sharded | stream), by bounded reason code "
        "(requested | no_model_axis | axis_indivisible | fits_budget "
        "| over_budget | sharded_over_budget)",
        ("placement", "reason")),
    # hot-row replication cache + dedup tier (parallel/hot_cache.py,
    # ops/embedding_bag.py embedding_bag_dedup)
    "table_hot_cache_lookups_total": (
        "counter", "hot-row cache routing decisions per id "
        "(hit = served from the chip-local replica, no exchange; "
        "miss = rode the cold sharded-psum bucket)",
        ("outcome", "table")),
    "table_hot_cache_bytes_saved_total": (
        "counter", "exchange bytes hot ids did NOT move over the model "
        "axis (hits x row dim x dtype bytes)", ("table",)),
    "table_hot_cache_refresh_total": (
        "counter", "hot-row cache lifecycle events (refresh | "
        "invalidate_swap | invalidate_reload ...)", ("event", "table")),
    "table_hot_cache_hit_rate": (
        "gauge", "cumulative hot-row cache hit fraction per table",
        ("table",)),
    "table_dedup_selected_total": (
        "counter", "within-batch duplicate-id dedup routing decisions "
        "per lookup site, by decision and bounded reason "
        "(knob_on | knob_off | auto_sharded | auto_dense)",
        ("decision", "reason")),
    "prefetch_queue_depth": (
        "gauge", "batches queued ahead of the consumer in the prefetch "
        "pipeline", ()),
    "prefetch_producer_stalls_total": (
        "counter", "producer put() attempts that found the prefetch "
        "queue full (consumer is the bottleneck)", ()),
    # ops/ kernel dispatch
    "ops_kernel_selected_total": (
        "counter", "kernel backend-routing decisions (trace-time, once "
        "per compilation), by kernel and chosen path "
        "(pallas | interpret | reference)", ("kernel", "path")),
    # checkpointing
    "checkpoint_seconds": (
        "histogram", "checkpoint op wall time", ("op",)),
    "checkpoint_total": (
        "counter", "checkpoint ops, by op and status", ("op", "status")),
    # distributed checkpointing / multi-controller coordination
    "checkpoint_shard_bytes": (
        "histogram", "bytes per distributed checkpoint shard written",
        ()),
    "checkpoint_barrier_wait_ms": (
        "histogram", "wait at the distributed checkpoint barriers, by "
        "commit phase", ("phase",)),
    "dist_barrier_timeouts_total": (
        "counter", "barriers that deadline-expired with a presumed-dead "
        "peer", ("phase",)),
    "dist_init_retries_total": (
        "counter", "jax.distributed.initialize attempts retried",
        ()),
    "dist_peer_loss_total": (
        "counter", "pod peer losses detected Python-side (barrier "
        "deadlines) and survived — the stock coordination client's "
        "heartbeat detector would have terminated the process", ()),
    # the observability layer itself
    "observe_flight_records_total": (
        "counter", "flight-recorder snapshots captured, by reason",
        ("reason",)),
    "observe_undeclared_metrics_total": (
        "counter", "emissions against names missing from CATALOG", ()),
}


def _labels_of(labels: Dict[str, Any]) -> LabelTuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def render_series(name: str, labels: LabelTuple) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "seq", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self.seq = 0  # monotonically increasing sample number
        self.samples: deque = deque(maxlen=_HIST_RING)  # (seq, value)


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q / 100.0 * (len(vs) - 1)))))
    return vs[idx]


class MetricsSnapshot:
    """An immutable mark; feed it back to ``registry.delta``."""

    __slots__ = ("ts", "counters", "gauges", "hist_marks")

    def __init__(self, ts: float, counters: Dict[SeriesKey, float],
                 gauges: Dict[SeriesKey, float],
                 hist_marks: Dict[SeriesKey, Tuple[int, float, int]]):
        self.ts = ts
        self.counters = counters
        self.gauges = gauges
        self.hist_marks = hist_marks  # (count, total, seq)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[SeriesKey, float] = {}
        self._gauges: Dict[SeriesKey, float] = {}
        self._hists: Dict[SeriesKey, _Hist] = {}

    # -- write path --------------------------------------------------------

    def _declared(self, name: str) -> bool:
        if name in CATALOG:
            return True
        key = ("observe_undeclared_metrics_total", ())
        self._counters[key] = self._counters.get(key, 0) + 1
        return False

    def inc(self, name: str, n: float = 1, **labels: Any) -> None:
        key = (name, _labels_of(labels))
        with self._lock:
            self._declared(name)
            self._counters[key] = self._counters.get(key, 0) + n

    def set(self, name: str, value: float, **labels: Any) -> None:
        key = (name, _labels_of(labels))
        with self._lock:
            self._declared(name)
            self._gauges[key] = float(value)

    def observe(self, name: str, value: float, **labels: Any) -> None:
        key = (name, _labels_of(labels))
        with self._lock:
            self._declared(name)
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            v = float(value)
            h.count += 1
            h.total += v
            h.vmin = v if h.vmin is None else min(h.vmin, v)
            h.vmax = v if h.vmax is None else max(h.vmax, v)
            h.seq += 1
            h.samples.append((h.seq, v))

    # -- read path ---------------------------------------------------------

    def snapshot(self) -> MetricsSnapshot:
        with self._lock:
            return MetricsSnapshot(
                time.time(), dict(self._counters), dict(self._gauges),
                {k: (h.count, h.total, h.seq)
                 for k, h in self._hists.items()})

    def delta(self, since: Optional[MetricsSnapshot]) -> Dict[str, Any]:
        """The window since ``since`` (or process lifetime if None).

        Histogram percentiles are computed over only the samples whose
        sequence number postdates the snapshot — a true window read, to
        the extent the per-series sample ring (last ``1024``) reaches
        back that far.
        """
        with self._lock:
            now = time.time()
            counters = {}
            for k, v in self._counters.items():
                prev = since.counters.get(k, 0) if since else 0
                if v - prev:
                    counters[render_series(*k)] = v - prev
            gauges = {render_series(*k): v
                      for k, v in self._gauges.items()}
            hists = {}
            for k, h in self._hists.items():
                c0, t0, s0 = (since.hist_marks.get(k, (0, 0.0, 0))
                              if since else (0, 0.0, 0))
                dcount = h.count - c0
                if not dcount:
                    continue
                window = [v for s, v in h.samples if s > s0]
                hists[render_series(*k)] = {
                    "count": dcount,
                    "total": h.total - t0,
                    "mean": (h.total - t0) / dcount,
                    "p50": _percentile(window, 50),
                    "p99": _percentile(window, 99),
                    "max": max(window) if window else None,
                    "window_samples": len(window),
                }
        return {
            "window_s": (now - since.ts) if since else None,
            "counters": counters, "gauges": gauges, "histograms": hists,
        }

    def collect(self) -> Iterable[Tuple[str, str, str,
                                        List[Tuple[LabelTuple, Any]]]]:
        """(name, type, help, [(labels, value-or-hist)]) for exporters,
        sorted by name for stable output."""
        with self._lock:
            by_name: Dict[str, List[Tuple[LabelTuple, Any]]] = {}
            kinds: Dict[str, str] = {}
            for (name, labels), v in self._counters.items():
                by_name.setdefault(name, []).append((labels, v))
                kinds[name] = "counter"
            for (name, labels), v in self._gauges.items():
                by_name.setdefault(name, []).append((labels, v))
                kinds[name] = "gauge"
            for (name, labels), h in self._hists.items():
                summary = {
                    "count": h.count, "sum": h.total,
                    "p50": _percentile([v for _, v in h.samples], 50),
                    "p99": _percentile([v for _, v in h.samples], 99),
                }
                by_name.setdefault(name, []).append((labels, summary))
                kinds[name] = "histogram"
        out = []
        for name in sorted(by_name):
            help_ = CATALOG.get(name, ("", "", ()))[1]
            out.append((name, kinds[name], help_,
                        sorted(by_name[name], key=lambda kv: kv[0])))
        return out

    def series_count(self) -> int:
        with self._lock:
            return (len(self._counters) + len(self._gauges) +
                    len(self._hists))

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


METRICS = MetricsRegistry()


# -- module-level helpers with the flat-Timers mirror -----------------------


def count(name: str, n: float = 1, flat: Optional[str] = None,
          **labels: Any) -> None:
    METRICS.inc(name, n, **labels)
    if flat:
        TIMERS.incr(flat, int(n))


def set_gauge(name: str, value: float, flat: Optional[str] = None,
              **labels: Any) -> None:
    METRICS.set(name, value, **labels)
    if flat:
        TIMERS.set_gauge(flat, value)


def observe(name: str, seconds: float, flat: Optional[str] = None,
            **labels: Any) -> None:
    METRICS.observe(name, seconds, **labels)
    if flat:
        TIMERS.observe(flat, seconds)


@contextmanager
def time_stage(name: str, flat: Optional[str] = None, **labels: Any):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        observe(name, time.perf_counter() - t0, flat=flat, **labels)
