"""Image pipeline: ``ImageSet`` + OpenCV-backed preprocessors.

Reference capability: feature/image/ — ``ImageSet`` (ImageSet.scala:46,98,
119; read:236) and the ~33 ``Image*`` preprocessors (Resize, CenterCrop,
RandomCrop, Flip, Brightness/Contrast/Hue/Saturation, ChannelNormalize,
ChannelOrder, Expand, AspectScale, PixelNormalizer, MatToTensor...).

TPU-native design: preprocessing runs on the **host CPU** (cv2/numpy — the
same OpenCV the reference reaches through JNI) producing dense NHWC float32
batches that feed the device infeed.  There is no Spark: a "distributed"
ImageSet is a host-sharded list; multi-host sharding slices the file list
by ``jax.process_index()``.  Transform chaining keeps the reference's
``->`` combinator as ``|`` / ``.chain()``.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

try:
    import cv2
    _HAS_CV2 = True
except Exception:  # pragma: no cover
    cv2 = None
    _HAS_CV2 = False


class ImageFeature(dict):
    """Mutable record for one image flowing through the pipeline
    (reference feature/image ImageFeature: keys bytes/mat/label/path...)."""

    @property
    def image(self) -> np.ndarray:
        return self["image"]

    @image.setter
    def image(self, v) -> None:
        self["image"] = v

    @property
    def label(self):
        return self.get("label")


class ImagePreprocessing:
    """Chainable per-image transform (reference Preprocessing[A,B] with
    ``->``, feature/common/Preprocessing.scala)."""

    def apply(self, feat: ImageFeature, rng: np.random.RandomState
              ) -> ImageFeature:
        raise NotImplementedError

    def __or__(self, other: "ImagePreprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    def chain(self, other: "ImagePreprocessing") -> "ChainedPreprocessing":
        return self | other

    def __call__(self, feat, rng=None):
        rng = rng or np.random.RandomState()
        return self.apply(feat, rng)


class ChainedPreprocessing(ImagePreprocessing):
    def __init__(self, stages: Sequence[ImagePreprocessing]):
        self.stages = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, feat, rng):
        for s in self.stages:
            feat = s.apply(feat, rng)
        return feat


class ImageResize(ImagePreprocessing):
    """Reference: feature/image/ImageResize.scala."""

    def __init__(self, resize_h: int, resize_w: int, mode: str = "bilinear"):
        self.h, self.w = resize_h, resize_w
        self.interp = (cv2.INTER_NEAREST if mode == "nearest"
                       else cv2.INTER_LINEAR) if _HAS_CV2 else mode

    def apply(self, feat, rng):
        feat.image = cv2.resize(feat.image, (self.w, self.h),
                                interpolation=self.interp)
        return feat


class ImageAspectScale(ImagePreprocessing):
    """Scale the short edge to ``min_size`` keeping aspect ratio, cap the
    long edge (reference ImageAspectScale.scala)."""

    def __init__(self, min_size: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.min_size, self.max_size = min_size, max_size
        self.multiple = scale_multiple_of

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        short, long_ = min(h, w), max(h, w)
        scale = self.min_size / short
        if scale * long_ > self.max_size:
            scale = self.max_size / long_
        nh, nw = int(round(h * scale)), int(round(w * scale))
        if self.multiple > 1:
            nh = (nh // self.multiple) * self.multiple
            nw = (nw // self.multiple) * self.multiple
        feat.image = cv2.resize(img, (nw, nh))
        feat["scale"] = scale
        return feat


class ImageRandomAspectScale(ImagePreprocessing):
    """Pick a random short-edge size from ``scales``
    (reference ImageRandomAspectScale.scala)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000):
        self.scales = list(scales)
        self.max_size = max_size

    def apply(self, feat, rng):
        size = self.scales[rng.randint(len(self.scales))]
        return ImageAspectScale(size, self.max_size).apply(feat, rng)


class ImageCenterCrop(ImagePreprocessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = crop_height, crop_width

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        top = max((h - self.ch) // 2, 0)
        left = max((w - self.cw) // 2, 0)
        feat.image = img[top:top + self.ch, left:left + self.cw]
        return feat


class ImageRandomCrop(ImagePreprocessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = crop_height, crop_width

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        top = rng.randint(0, max(h - self.ch, 0) + 1)
        left = rng.randint(0, max(w - self.cw, 0) + 1)
        feat.image = img[top:top + self.ch, left:left + self.cw]
        return feat


class ImageHFlip(ImagePreprocessing):
    def apply(self, feat, rng):
        feat.image = feat.image[:, ::-1]
        return feat


class ImageRandomHFlip(ImagePreprocessing):
    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, feat, rng):
        if rng.rand() < self.p:
            feat.image = feat.image[:, ::-1]
        return feat


class ImageChannelOrder(ImagePreprocessing):
    """BGR <-> RGB swap (reference ImageChannelOrder)."""

    def apply(self, feat, rng):
        feat.image = feat.image[..., ::-1]
        return feat


class ImageBrightness(ImagePreprocessing):
    """Add a uniform delta in [delta_low, delta_high]
    (reference image/Brightness)."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        delta = rng.uniform(self.lo, self.hi)
        feat.image = feat.image.astype(np.float32) + delta
        return feat


class ImageContrast(ImagePreprocessing):
    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        factor = rng.uniform(self.lo, self.hi)
        feat.image = feat.image.astype(np.float32) * factor
        return feat


class ImageSaturation(ImagePreprocessing):
    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        factor = rng.uniform(self.lo, self.hi)
        img = feat.image.astype(np.float32)
        gray = img.mean(axis=-1, keepdims=True)
        feat.image = gray + (img - gray) * factor
        return feat


class ImageHue(ImagePreprocessing):
    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        delta = rng.uniform(self.lo, self.hi)
        img = np.clip(feat.image, 0, 255).astype(np.uint8)
        hsv = cv2.cvtColor(img, cv2.COLOR_BGR2HSV).astype(np.int32)
        hsv[..., 0] = (hsv[..., 0] + int(delta)) % 180
        feat.image = cv2.cvtColor(hsv.astype(np.uint8),
                                  cv2.COLOR_HSV2BGR).astype(np.float32)
        return feat


class ImageColorJitter(ImagePreprocessing):
    """Random brightness/contrast/saturation in random order
    (reference ImageColorJitter.scala)."""

    def __init__(self, brightness=(-32, 32), contrast=(0.5, 1.5),
                 saturation=(0.5, 1.5)):
        self.stages = [ImageBrightness(*brightness),
                       ImageContrast(*contrast),
                       ImageSaturation(*saturation)]

    def apply(self, feat, rng):
        for i in rng.permutation(len(self.stages)):
            feat = self.stages[i].apply(feat, rng)
        return feat


class ImageExpand(ImagePreprocessing):
    """Randomly place the image on a larger mean-filled canvas
    (reference ImageExpand.scala, SSD augmentation)."""

    def __init__(self, means=(123.0, 117.0, 104.0), max_expand_ratio: float = 4.0):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio

    def apply(self, feat, rng):
        img = feat.image.astype(np.float32)
        h, w = img.shape[:2]
        ratio = rng.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        top = rng.randint(0, nh - h + 1)
        left = rng.randint(0, nw - w + 1)
        canvas = np.ones((nh, nw, img.shape[2]), np.float32) * self.means
        canvas[top:top + h, left:left + w] = img
        feat.image = canvas
        feat["expand"] = (top, left, ratio)
        return feat


class ImageChannelNormalize(ImagePreprocessing):
    """Per-channel (x - mean) / std.

    Means/stds are given in R,G,B order but applied reversed (B,G,R)
    because pipeline images are OpenCV BGR — exactly as the reference does
    (ImageChannelNormalize.scala builds Array(meanB, meanG, meanR)).
    """

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    def apply(self, feat, rng):
        feat.image = (feat.image.astype(np.float32) - self.mean) / self.std
        return feat


class ImagePixelNormalizer(ImagePreprocessing):
    """Subtract a full per-pixel mean image (reference ImagePixelNormalizer)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply(self, feat, rng):
        feat.image = feat.image.astype(np.float32) - self.means
        return feat


class ImageSetToSample(ImagePreprocessing):
    """Finalize: ensure float32 HWC tensor (reference ImageSetToSample /
    ImageMatToTensor — with NHWC, the TPU-native layout, not NCHW)."""

    def apply(self, feat, rng):
        img = np.asarray(feat.image, np.float32)
        if img.ndim == 2:
            img = img[..., None]
        feat["sample"] = np.ascontiguousarray(img)
        return feat


ImageMatToTensor = ImageSetToSample


class ImageSet:
    """Collection of ImageFeatures + lazy transform chain.

    Reference: feature/image/ImageSet.scala (read:236 local/distributed).
    ``to_feature_set`` materializes into batchable arrays once every image
    has a fixed shape.
    """

    def __init__(self, features: List[ImageFeature],
                 transforms: Optional[ImagePreprocessing] = None,
                 seed: int = 0):
        self.features = features
        self.transforms = transforms
        self.seed = seed

    # -- constructors ------------------------------------------------------
    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True, max_images: Optional[int] = None,
             num_shards: int = 1, shard_index: int = 0) -> "ImageSet":
        """Read images from a directory (or glob).  With ``with_label``,
        immediate subdirectory names become class labels (sorted order),
        matching the reference's folder-per-class convention.
        Multi-host: pass num_shards=jax.process_count()."""
        if os.path.isdir(path):
            pats = [os.path.join(path, "**", "*.*")]
        else:
            pats = [path]
        files = sorted(f for p in pats for f in _glob.glob(p, recursive=True)
                       if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")))
        label_map: Dict[str, int] = {}
        if with_label:
            # Build labels from the FULL listing (before shard/truncate) so
            # every host agrees on class→id even with uneven shards.
            classes = sorted({os.path.basename(os.path.dirname(f))
                              for f in files})
            base = 1 if one_based_label else 0
            label_map = {c: i + base for i, c in enumerate(classes)}
        files = files[shard_index::num_shards]
        if max_images:
            files = files[:max_images]
        feats = []
        for f in files:
            img = cv2.imread(f, cv2.IMREAD_COLOR)
            if img is None:
                continue
            feat = ImageFeature(image=img, path=f)
            if with_label:
                feat["label"] = label_map[os.path.basename(os.path.dirname(f))]
            feats.append(feat)
        im = ImageSet(feats)
        im.label_map = label_map
        return im

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray],
                    labels: Optional[Sequence] = None) -> "ImageSet":
        feats = []
        for i, img in enumerate(images):
            f = ImageFeature(image=np.asarray(img))
            if labels is not None:
                f["label"] = labels[i]
            feats.append(f)
        return ImageSet(feats)

    # -- transform ---------------------------------------------------------
    def transform(self, preprocessing: ImagePreprocessing) -> "ImageSet":
        t = (preprocessing if self.transforms is None
             else self.transforms | preprocessing)
        return ImageSet(self.features, t, self.seed)

    def get_image(self, idx: int = 0) -> np.ndarray:
        """Apply the chain to one image (debug/peek)."""
        rng = np.random.RandomState(self.seed + idx)
        feat = ImageFeature(self.features[idx])
        if self.transforms is not None:
            feat = self.transforms.apply(feat, rng)
        return feat.get("sample", feat.image)

    def __len__(self):
        return len(self.features)

    # -- materialization ---------------------------------------------------
    def to_arrays(self, epoch_seed: int = 0, num_workers: Optional[int] = None
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Apply the chain to every image.  Decode/augment run on a
        thread pool (cv2 releases the GIL) — the parallel-decode role of
        the reference's per-partition Spark executors.  Determinism is
        per-index: each image's RandomState depends only on (seed,
        epoch_seed, idx), so worker count never changes the output."""
        def one(idx):
            rng = np.random.RandomState(
                (self.seed + epoch_seed * 1_000_003 + idx) % (2 ** 31))
            feat = ImageFeature(self.features[idx])
            if self.transforms is not None:
                feat = self.transforms.apply(feat, rng)
            return (np.asarray(feat.get("sample", feat.image), np.float32),
                    feat.label)

        n = len(self.features)
        if num_workers is None:
            num_workers = min(8, os.cpu_count() or 1)
        if num_workers > 1 and n >= 4 * num_workers:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=num_workers) as ex:
                results = list(ex.map(one, range(n)))
        else:
            results = [one(i) for i in range(n)]
        imgs = [r[0] for r in results]
        labels = [r[1] for r in results if r[1] is not None]
        x = np.stack(imgs)
        if labels and len(labels) != len(imgs):
            raise ValueError(
                f"{len(imgs) - len(labels)} of {len(imgs)} images have no "
                "label — refusing to silently misalign images and labels")
        y = np.asarray(labels) if labels else None
        return x, y

    def to_feature_set(self, memory_type: str = "DRAM"):
        from analytics_zoo_tpu.data.featureset import FeatureSet

        x, y = self.to_arrays()
        return FeatureSet.from_ndarrays(x, y, memory_type=memory_type)


class ImageBytesToMat(ImagePreprocessing):
    """Decode encoded image bytes (jpeg/png) into a BGR mat
    (reference ImageBytesToMat.scala)."""

    def __init__(self, byte_key: str = "bytes"):
        self.byte_key = byte_key

    def apply(self, feat, rng):
        buf = np.frombuffer(feat[self.byte_key], np.uint8)
        feat.image = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if feat.image is None:
            raise ValueError("undecodable image bytes")
        return feat


class ImagePixelBytesToMat(ImagePreprocessing):
    """Raw pixel bytes (H*W*C uint8) -> mat (reference
    ImagePixelBytesToMat.scala); shape from feature keys or kwargs."""

    def __init__(self, byte_key: str = "bytes"):
        self.byte_key = byte_key

    def apply(self, feat, rng):
        h, w = int(feat["height"]), int(feat["width"])
        c = int(feat.get("nChannels", 3))
        arr = np.frombuffer(feat[self.byte_key], np.uint8)
        feat.image = arr.reshape(h, w, c).copy()
        return feat


class ImageMatToFloats(ImagePreprocessing):
    """Mat -> float32 HWC array under key "floats" (reference
    ImageMatToFloats.scala)."""

    def apply(self, feat, rng):
        img = np.asarray(feat.image, np.float32)
        if img.ndim == 2:
            img = img[..., None]
        feat["floats"] = img
        return feat


class ImageFeatureToTensor(ImagePreprocessing):
    """Finalize feature -> training tensor (reference
    ImageFeatureToTensor.scala); same contract as ImageSetToSample."""

    def apply(self, feat, rng):
        return ImageSetToSample().apply(feat, rng)


class ImageFiller(ImagePreprocessing):
    """Fill a (normalized-coordinate) region with a constant value
    (reference ImageFiller.scala — occlusion augmentation)."""

    def __init__(self, start_x: float, start_y: float, end_x: float,
                 end_y: float, value: float = 255.0):
        self.box = (start_x, start_y, end_x, end_y)
        self.value = value

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        img = img.copy()
        img[int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = self.value
        feat.image = img
        return feat


class ImageFixedCrop(ImagePreprocessing):
    """Crop a fixed box; coords normalized (0..1) or absolute pixels
    (reference ImageFixedCrop.scala)."""

    def __init__(self, x1: float, y1: float, x2: float, y2: float,
                 normalized: bool = True):
        self.box = (x1, y1, x2, y2)
        self.normalized = normalized

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        x1, y1, x2, y2 = self.box
        if self.normalized:
            x1, x2 = x1 * w, x2 * w
            y1, y2 = y1 * h, y2 * h
        feat.image = img[int(y1):int(y2), int(x1):int(x2)].copy()
        feat["crop"] = (int(x1), int(y1), int(x2), int(y2))
        return feat


class ImageMirror(ImageHFlip):
    """Horizontal mirror (reference ImageMirror.scala — same op as
    HFlip)."""


class ImageChannelScaledNormalizer(ImagePreprocessing):
    """(x - channel_mean) * scale (reference
    ImageChannelScaledNormalizer.scala)."""

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 scale: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)  # BGR
        self.scale = scale

    def apply(self, feat, rng):
        feat.image = (feat.image.astype(np.float32) - self.mean) * self.scale
        return feat


class ImageRandomPreprocessing(ImagePreprocessing):
    """Apply an inner preprocessing with probability ``prob``
    (reference ImageRandomPreprocessing.scala)."""

    def __init__(self, preprocessing: ImagePreprocessing, prob: float = 0.5):
        self.inner = preprocessing
        self.prob = prob

    def apply(self, feat, rng):
        if rng.rand() < self.prob:
            return self.inner.apply(feat, rng)
        return feat


class ImageRandomResize(ImagePreprocessing):
    """Resize to a random square size in [min_size, max_size]
    (reference ImageRandomResize.scala)."""

    def __init__(self, min_size: int, max_size: int):
        self.min_size, self.max_size = min_size, max_size

    def apply(self, feat, rng):
        s = int(rng.randint(self.min_size, self.max_size + 1))
        feat.image = cv2.resize(feat.image, (s, s))
        return feat


class ImageRandomCropper(ImagePreprocessing):
    """Random crop to fixed (crop_w, crop_h) with optional mirroring
    (reference ImageRandomCropper.scala)."""

    def __init__(self, crop_width: int, crop_height: int,
                 mirror: bool = True):
        self.cw, self.ch = crop_width, crop_height
        self.mirror = mirror

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        if h < self.ch or w < self.cw:
            img = cv2.resize(img, (max(w, self.cw), max(h, self.ch)))
            h, w = img.shape[:2]
        top = rng.randint(0, h - self.ch + 1)
        left = rng.randint(0, w - self.cw + 1)
        img = img[top:top + self.ch, left:left + self.cw]
        if self.mirror and rng.rand() < 0.5:
            img = img[:, ::-1]
        feat.image = np.ascontiguousarray(img)
        return feat


# ---------------------------------------------------------------------------
# ROI-aware ops: transforms that keep ground-truth boxes consistent with
# the image (reference feature/image/roi/ + RoiTransformer.scala wrapping
# BigDL RoiNormalize/RoiHFlip/RoiResize).  Boxes live in
# feat["bboxes"]: (N, 4) [x1, y1, x2, y2] pixels unless noted.
# ---------------------------------------------------------------------------

class RoiNormalize(ImagePreprocessing):
    """Pixel boxes -> normalized [0, 1] coords (reference RoiNormalize)."""

    def apply(self, feat, rng):
        if "bboxes" in feat:
            h, w = feat.image.shape[:2]
            b = np.asarray(feat["bboxes"], np.float32).copy()
            b[:, [0, 2]] /= w
            b[:, [1, 3]] /= h
            feat["bboxes"] = b
            feat["bboxes_normalized"] = True
        return feat


class RoiHFlip(ImagePreprocessing):
    """Flip image AND boxes horizontally (reference RoiHFlip)."""

    def __init__(self, normalized: bool = True):
        self.normalized = normalized

    def apply(self, feat, rng):
        feat.image = feat.image[:, ::-1].copy()
        if "bboxes" in feat:
            b = np.asarray(feat["bboxes"], np.float32).copy()
            width = 1.0 if self.normalized else feat.image.shape[1]
            b[:, [0, 2]] = width - b[:, [2, 0]]
            feat["bboxes"] = b
        return feat


class RoiResize(ImagePreprocessing):
    """Resize image; scale pixel boxes accordingly (reference RoiResize)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = resize_h, resize_w

    def apply(self, feat, rng):
        h0, w0 = feat.image.shape[:2]
        feat.image = cv2.resize(feat.image, (self.w, self.h))
        if "bboxes" in feat and not feat.get("bboxes_normalized"):
            b = np.asarray(feat["bboxes"], np.float32).copy()
            b[:, [0, 2]] *= self.w / w0
            b[:, [1, 3]] *= self.h / h0
            feat["bboxes"] = b
        return feat


class RandomSampler(ImagePreprocessing):
    """SSD-style random IoU-constrained crop sampler (reference
    RandomSampler.scala / BigDL BatchSampler): pick a random crop whose
    IoU with some ground-truth box meets a sampled threshold; keep boxes
    whose centers fall inside, clipped and shifted."""

    def __init__(self, min_scale: float = 0.3,
                 min_ious=(0.1, 0.3, 0.5, 0.7, 0.9), max_trials: int = 25):
        self.min_scale = min_scale
        self.min_ious = list(min_ious) + [None]   # None = no constraint
        self.max_trials = max_trials

    @staticmethod
    def _iou(boxes, crop):
        x1 = np.maximum(boxes[:, 0], crop[0])
        y1 = np.maximum(boxes[:, 1], crop[1])
        x2 = np.minimum(boxes[:, 2], crop[2])
        y2 = np.minimum(boxes[:, 3], crop[3])
        inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
        area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        area_c = (crop[2] - crop[0]) * (crop[3] - crop[1])
        return inter / np.maximum(area_b + area_c - inter, 1e-9)

    def apply(self, feat, rng):
        if "bboxes" not in feat or len(feat["bboxes"]) == 0:
            return feat
        img = feat.image
        h, w = img.shape[:2]
        boxes = np.asarray(feat["bboxes"], np.float32)
        labels = np.asarray(feat.get("label", np.zeros(len(boxes))))
        min_iou = self.min_ious[rng.randint(len(self.min_ious))]
        if min_iou is None:
            return feat
        for _ in range(self.max_trials):
            cw = rng.uniform(self.min_scale, 1.0) * w
            chh = rng.uniform(self.min_scale, 1.0) * h
            if not 0.5 <= cw / chh <= 2.0:
                continue
            left = rng.uniform(0, w - cw)
            top = rng.uniform(0, h - chh)
            # integer crop box so the cropped image and the shifted boxes
            # share the exact same coordinate frame
            crop = np.array([int(left), int(top), int(left + cw),
                             int(top + chh)], np.float32)
            if self._iou(boxes, crop).max() < min_iou:
                continue
            cx = (boxes[:, 0] + boxes[:, 2]) / 2
            cy = (boxes[:, 1] + boxes[:, 3]) / 2
            keep = ((cx >= crop[0]) & (cx <= crop[2])
                    & (cy >= crop[1]) & (cy <= crop[3]))
            if not keep.any():
                continue
            kept = boxes[keep].copy()
            kept[:, [0, 2]] = np.clip(kept[:, [0, 2]], crop[0], crop[2]) \
                - crop[0]
            kept[:, [1, 3]] = np.clip(kept[:, [1, 3]], crop[1], crop[3]) \
                - crop[1]
            feat.image = img[int(crop[1]):int(crop[3]),
                             int(crop[0]):int(crop[2])].copy()
            feat["bboxes"] = kept
            feat["label"] = labels[keep]
            return feat
        return feat


class RowToImageFeature(ImagePreprocessing):
    """nnframes image-schema row (origin/height/width/nChannels/mode/data)
    -> ImageFeature (reference RowToImageFeature.scala)."""

    def apply(self, feat, rng):
        return feat          # already an ImageFeature

    @staticmethod
    def from_row(row) -> ImageFeature:
        return ImageFeature(image=np.asarray(row["data"]),
                            path=row.get("origin", ""))


class BufferedImageResize(ImageResize):
    """Parity alias for the reference's BufferedImageResize.scala (the
    JVM BufferedImage path vs OpenCV path distinction does not exist
    here — one cv2 resize serves both)."""
