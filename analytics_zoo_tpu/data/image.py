"""Image pipeline: ``ImageSet`` + OpenCV-backed preprocessors.

Reference capability: feature/image/ — ``ImageSet`` (ImageSet.scala:46,98,
119; read:236) and the ~33 ``Image*`` preprocessors (Resize, CenterCrop,
RandomCrop, Flip, Brightness/Contrast/Hue/Saturation, ChannelNormalize,
ChannelOrder, Expand, AspectScale, PixelNormalizer, MatToTensor...).

TPU-native design: preprocessing runs on the **host CPU** (cv2/numpy — the
same OpenCV the reference reaches through JNI) producing dense NHWC float32
batches that feed the device infeed.  There is no Spark: a "distributed"
ImageSet is a host-sharded list; multi-host sharding slices the file list
by ``jax.process_index()``.  Transform chaining keeps the reference's
``->`` combinator as ``|`` / ``.chain()``.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

try:
    import cv2
    _HAS_CV2 = True
except Exception:  # pragma: no cover
    cv2 = None
    _HAS_CV2 = False


class ImageFeature(dict):
    """Mutable record for one image flowing through the pipeline
    (reference feature/image ImageFeature: keys bytes/mat/label/path...)."""

    @property
    def image(self) -> np.ndarray:
        return self["image"]

    @image.setter
    def image(self, v) -> None:
        self["image"] = v

    @property
    def label(self):
        return self.get("label")


class ImagePreprocessing:
    """Chainable per-image transform (reference Preprocessing[A,B] with
    ``->``, feature/common/Preprocessing.scala)."""

    def apply(self, feat: ImageFeature, rng: np.random.RandomState
              ) -> ImageFeature:
        raise NotImplementedError

    def __or__(self, other: "ImagePreprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    def chain(self, other: "ImagePreprocessing") -> "ChainedPreprocessing":
        return self | other

    def __call__(self, feat, rng=None):
        rng = rng or np.random.RandomState()
        return self.apply(feat, rng)


class ChainedPreprocessing(ImagePreprocessing):
    def __init__(self, stages: Sequence[ImagePreprocessing]):
        self.stages = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, feat, rng):
        for s in self.stages:
            feat = s.apply(feat, rng)
        return feat


class ImageResize(ImagePreprocessing):
    """Reference: feature/image/ImageResize.scala."""

    def __init__(self, resize_h: int, resize_w: int, mode: str = "bilinear"):
        self.h, self.w = resize_h, resize_w
        self.interp = (cv2.INTER_NEAREST if mode == "nearest"
                       else cv2.INTER_LINEAR) if _HAS_CV2 else mode

    def apply(self, feat, rng):
        feat.image = cv2.resize(feat.image, (self.w, self.h),
                                interpolation=self.interp)
        return feat


class ImageAspectScale(ImagePreprocessing):
    """Scale the short edge to ``min_size`` keeping aspect ratio, cap the
    long edge (reference ImageAspectScale.scala)."""

    def __init__(self, min_size: int, max_size: int = 1000,
                 scale_multiple_of: int = 1):
        self.min_size, self.max_size = min_size, max_size
        self.multiple = scale_multiple_of

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        short, long_ = min(h, w), max(h, w)
        scale = self.min_size / short
        if scale * long_ > self.max_size:
            scale = self.max_size / long_
        nh, nw = int(round(h * scale)), int(round(w * scale))
        if self.multiple > 1:
            nh = (nh // self.multiple) * self.multiple
            nw = (nw // self.multiple) * self.multiple
        feat.image = cv2.resize(img, (nw, nh))
        feat["scale"] = scale
        return feat


class ImageRandomAspectScale(ImagePreprocessing):
    """Pick a random short-edge size from ``scales``
    (reference ImageRandomAspectScale.scala)."""

    def __init__(self, scales: Sequence[int], max_size: int = 1000):
        self.scales = list(scales)
        self.max_size = max_size

    def apply(self, feat, rng):
        size = self.scales[rng.randint(len(self.scales))]
        return ImageAspectScale(size, self.max_size).apply(feat, rng)


class ImageCenterCrop(ImagePreprocessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = crop_height, crop_width

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        top = max((h - self.ch) // 2, 0)
        left = max((w - self.cw) // 2, 0)
        feat.image = img[top:top + self.ch, left:left + self.cw]
        return feat


class ImageRandomCrop(ImagePreprocessing):
    def __init__(self, crop_height: int, crop_width: int):
        self.ch, self.cw = crop_height, crop_width

    def apply(self, feat, rng):
        img = feat.image
        h, w = img.shape[:2]
        top = rng.randint(0, max(h - self.ch, 0) + 1)
        left = rng.randint(0, max(w - self.cw, 0) + 1)
        feat.image = img[top:top + self.ch, left:left + self.cw]
        return feat


class ImageHFlip(ImagePreprocessing):
    def apply(self, feat, rng):
        feat.image = feat.image[:, ::-1]
        return feat


class ImageRandomHFlip(ImagePreprocessing):
    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, feat, rng):
        if rng.rand() < self.p:
            feat.image = feat.image[:, ::-1]
        return feat


class ImageChannelOrder(ImagePreprocessing):
    """BGR <-> RGB swap (reference ImageChannelOrder)."""

    def apply(self, feat, rng):
        feat.image = feat.image[..., ::-1]
        return feat


class ImageBrightness(ImagePreprocessing):
    """Add a uniform delta in [delta_low, delta_high]
    (reference image/Brightness)."""

    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        delta = rng.uniform(self.lo, self.hi)
        feat.image = feat.image.astype(np.float32) + delta
        return feat


class ImageContrast(ImagePreprocessing):
    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        factor = rng.uniform(self.lo, self.hi)
        feat.image = feat.image.astype(np.float32) * factor
        return feat


class ImageSaturation(ImagePreprocessing):
    def __init__(self, delta_low: float, delta_high: float):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        factor = rng.uniform(self.lo, self.hi)
        img = feat.image.astype(np.float32)
        gray = img.mean(axis=-1, keepdims=True)
        feat.image = gray + (img - gray) * factor
        return feat


class ImageHue(ImagePreprocessing):
    def __init__(self, delta_low: float = -18.0, delta_high: float = 18.0):
        self.lo, self.hi = delta_low, delta_high

    def apply(self, feat, rng):
        delta = rng.uniform(self.lo, self.hi)
        img = np.clip(feat.image, 0, 255).astype(np.uint8)
        hsv = cv2.cvtColor(img, cv2.COLOR_BGR2HSV).astype(np.int32)
        hsv[..., 0] = (hsv[..., 0] + int(delta)) % 180
        feat.image = cv2.cvtColor(hsv.astype(np.uint8),
                                  cv2.COLOR_HSV2BGR).astype(np.float32)
        return feat


class ImageColorJitter(ImagePreprocessing):
    """Random brightness/contrast/saturation in random order
    (reference ImageColorJitter.scala)."""

    def __init__(self, brightness=(-32, 32), contrast=(0.5, 1.5),
                 saturation=(0.5, 1.5)):
        self.stages = [ImageBrightness(*brightness),
                       ImageContrast(*contrast),
                       ImageSaturation(*saturation)]

    def apply(self, feat, rng):
        for i in rng.permutation(len(self.stages)):
            feat = self.stages[i].apply(feat, rng)
        return feat


class ImageExpand(ImagePreprocessing):
    """Randomly place the image on a larger mean-filled canvas
    (reference ImageExpand.scala, SSD augmentation)."""

    def __init__(self, means=(123.0, 117.0, 104.0), max_expand_ratio: float = 4.0):
        self.means = np.asarray(means, np.float32)
        self.max_ratio = max_expand_ratio

    def apply(self, feat, rng):
        img = feat.image.astype(np.float32)
        h, w = img.shape[:2]
        ratio = rng.uniform(1.0, self.max_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        top = rng.randint(0, nh - h + 1)
        left = rng.randint(0, nw - w + 1)
        canvas = np.ones((nh, nw, img.shape[2]), np.float32) * self.means
        canvas[top:top + h, left:left + w] = img
        feat.image = canvas
        feat["expand"] = (top, left, ratio)
        return feat


class ImageChannelNormalize(ImagePreprocessing):
    """Per-channel (x - mean) / std.

    Means/stds are given in R,G,B order but applied reversed (B,G,R)
    because pipeline images are OpenCV BGR — exactly as the reference does
    (ImageChannelNormalize.scala builds Array(meanB, meanG, meanR)).
    """

    def __init__(self, mean_r: float, mean_g: float, mean_b: float,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0):
        self.mean = np.asarray([mean_b, mean_g, mean_r], np.float32)
        self.std = np.asarray([std_b, std_g, std_r], np.float32)

    def apply(self, feat, rng):
        feat.image = (feat.image.astype(np.float32) - self.mean) / self.std
        return feat


class ImagePixelNormalizer(ImagePreprocessing):
    """Subtract a full per-pixel mean image (reference ImagePixelNormalizer)."""

    def __init__(self, means: np.ndarray):
        self.means = np.asarray(means, np.float32)

    def apply(self, feat, rng):
        feat.image = feat.image.astype(np.float32) - self.means
        return feat


class ImageSetToSample(ImagePreprocessing):
    """Finalize: ensure float32 HWC tensor (reference ImageSetToSample /
    ImageMatToTensor — with NHWC, the TPU-native layout, not NCHW)."""

    def apply(self, feat, rng):
        img = np.asarray(feat.image, np.float32)
        if img.ndim == 2:
            img = img[..., None]
        feat["sample"] = np.ascontiguousarray(img)
        return feat


ImageMatToTensor = ImageSetToSample


class ImageSet:
    """Collection of ImageFeatures + lazy transform chain.

    Reference: feature/image/ImageSet.scala (read:236 local/distributed).
    ``to_feature_set`` materializes into batchable arrays once every image
    has a fixed shape.
    """

    def __init__(self, features: List[ImageFeature],
                 transforms: Optional[ImagePreprocessing] = None,
                 seed: int = 0):
        self.features = features
        self.transforms = transforms
        self.seed = seed

    # -- constructors ------------------------------------------------------
    @staticmethod
    def read(path: str, with_label: bool = False,
             one_based_label: bool = True, max_images: Optional[int] = None,
             num_shards: int = 1, shard_index: int = 0) -> "ImageSet":
        """Read images from a directory (or glob).  With ``with_label``,
        immediate subdirectory names become class labels (sorted order),
        matching the reference's folder-per-class convention.
        Multi-host: pass num_shards=jax.process_count()."""
        if os.path.isdir(path):
            pats = [os.path.join(path, "**", "*.*")]
        else:
            pats = [path]
        files = sorted(f for p in pats for f in _glob.glob(p, recursive=True)
                       if f.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")))
        label_map: Dict[str, int] = {}
        if with_label:
            # Build labels from the FULL listing (before shard/truncate) so
            # every host agrees on class→id even with uneven shards.
            classes = sorted({os.path.basename(os.path.dirname(f))
                              for f in files})
            base = 1 if one_based_label else 0
            label_map = {c: i + base for i, c in enumerate(classes)}
        files = files[shard_index::num_shards]
        if max_images:
            files = files[:max_images]
        feats = []
        for f in files:
            img = cv2.imread(f, cv2.IMREAD_COLOR)
            if img is None:
                continue
            feat = ImageFeature(image=img, path=f)
            if with_label:
                feat["label"] = label_map[os.path.basename(os.path.dirname(f))]
            feats.append(feat)
        im = ImageSet(feats)
        im.label_map = label_map
        return im

    @staticmethod
    def from_arrays(images: Sequence[np.ndarray],
                    labels: Optional[Sequence] = None) -> "ImageSet":
        feats = []
        for i, img in enumerate(images):
            f = ImageFeature(image=np.asarray(img))
            if labels is not None:
                f["label"] = labels[i]
            feats.append(f)
        return ImageSet(feats)

    # -- transform ---------------------------------------------------------
    def transform(self, preprocessing: ImagePreprocessing) -> "ImageSet":
        t = (preprocessing if self.transforms is None
             else self.transforms | preprocessing)
        return ImageSet(self.features, t, self.seed)

    def get_image(self, idx: int = 0) -> np.ndarray:
        """Apply the chain to one image (debug/peek)."""
        rng = np.random.RandomState(self.seed + idx)
        feat = ImageFeature(self.features[idx])
        if self.transforms is not None:
            feat = self.transforms.apply(feat, rng)
        return feat.get("sample", feat.image)

    def __len__(self):
        return len(self.features)

    # -- materialization ---------------------------------------------------
    def to_arrays(self, epoch_seed: int = 0
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        imgs, labels = [], []
        for idx, raw in enumerate(self.features):
            rng = np.random.RandomState(
                (self.seed + epoch_seed * 1_000_003 + idx) % (2 ** 31))
            feat = ImageFeature(raw)
            if self.transforms is not None:
                feat = self.transforms.apply(feat, rng)
            imgs.append(np.asarray(feat.get("sample", feat.image), np.float32))
            if feat.label is not None:
                labels.append(feat.label)
        x = np.stack(imgs)
        if labels and len(labels) != len(imgs):
            raise ValueError(
                f"{len(imgs) - len(labels)} of {len(imgs)} images have no "
                "label — refusing to silently misalign images and labels")
        y = np.asarray(labels) if labels else None
        return x, y

    def to_feature_set(self, memory_type: str = "DRAM"):
        from analytics_zoo_tpu.data.featureset import FeatureSet

        x, y = self.to_arrays()
        return FeatureSet.from_ndarrays(x, y, memory_type=memory_type)
