"""Typed, chainable preprocessing combinators.

Reference capability: ``feature/common/Preprocessing.scala`` — a typed
``Preprocessing[A, B]`` composed with ``->`` plus the converter zoo
(SeqToTensor, MLlibVectorToTensor, ScalarToTensor,
FeatureLabelPreprocessing, TensorToSample...).

Host-side equivalents: a ``Preprocessing`` is any single-argument
callable; ``>>`` (and ``chain``) compose left-to-right; converters lower
python/scalar/sequence rows to dense numpy.  The image/text pipelines'
chains and nnframes' feature/label preprocessing params all accept these.
"""

from __future__ import annotations

from typing import Any, Callable, Generic, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Preprocessing", "ChainedPreprocessing", "SeqToTensor",
           "ScalarToTensor", "ArrayToTensor", "ToFloat32",
           "FeatureLabelPreprocessing", "TensorToSample"]


class Preprocessing:
    """A -> B transform, composable with ``>>`` (reference ``->``)."""

    def apply(self, value):
        raise NotImplementedError

    def __call__(self, value):
        return self.apply(value)

    def __rshift__(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    def chain(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return self >> other


class ChainedPreprocessing(Preprocessing):
    def __init__(self, stages: Sequence[Callable]):
        self.stages: List[Callable] = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, value):
        for s in self.stages:
            value = s(value)
        return value


class SeqToTensor(Preprocessing):
    """Python sequence / list-of-lists -> ndarray with optional shape
    check (reference SeqToTensor)."""

    def __init__(self, size: Optional[Sequence[int]] = None,
                 dtype=np.float32):
        self.size = tuple(size) if size is not None else None
        self.dtype = dtype

    def apply(self, value):
        arr = np.asarray(value, self.dtype)
        if self.size is not None:
            arr = arr.reshape(self.size)
        return arr


class ScalarToTensor(Preprocessing):
    """Scalar -> shape-(1,) tensor (reference ScalarToTensor)."""

    def __init__(self, dtype=np.float32):
        self.dtype = dtype

    def apply(self, value):
        return np.asarray([value], self.dtype)


class ArrayToTensor(Preprocessing):
    """ndarray passthrough with dtype/shape normalization (the
    MLlibVectorToTensor role — dense vectors are plain arrays here)."""

    def __init__(self, size: Optional[Sequence[int]] = None,
                 dtype=np.float32):
        self.size = tuple(size) if size is not None else None
        self.dtype = dtype

    def apply(self, value):
        arr = np.asarray(value)
        if arr.dtype != self.dtype:
            arr = arr.astype(self.dtype)
        if self.size is not None:
            arr = arr.reshape(self.size)
        return arr


class ToFloat32(Preprocessing):
    def apply(self, value):
        return np.asarray(value, np.float32)


class TensorToSample(Preprocessing):
    """(feature, label) pair -> sample dict (reference TensorToSample /
    FeatureToTupleAdapter)."""

    def apply(self, value):
        if isinstance(value, tuple) and len(value) == 2:
            return {"feature": value[0], "label": value[1]}
        return {"feature": value}


class FeatureLabelPreprocessing(Preprocessing):
    """Pair transform: independent feature/label sub-chains (reference
    FeatureLabelPreprocessing.scala — the NNEstimator sample
    preprocessing).  Applies to (feature, label) tuples; a bare value is
    treated as feature-only."""

    def __init__(self, feature: Callable, label: Optional[Callable] = None):
        self.feature = feature
        self.label = label

    def apply(self, value):
        if isinstance(value, tuple) and len(value) == 2:
            f, l = value
            return (self.feature(f),
                    self.label(l) if self.label is not None else l)
        return self.feature(value)

    def map_arrays(self, xs: Sequence[np.ndarray],
                   y: Optional[np.ndarray]
                   ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
        """Whole-column application (the vectorised path nnframes uses)."""
        fx = [np.stack([self.feature(row) for row in x])
              if not isinstance(self.feature, (ArrayToTensor, ToFloat32))
              else self.feature(x) for x in xs]
        fy = y
        if y is not None and self.label is not None:
            fy = self.label(y)
        return fx, fy
