"""Text pipeline: TextSet / TextFeature + tokenize→normalize→word2idx→
shape→sample stages, vocabulary build/save, CSV/parquet readers.

Reference capability: feature/text/ — ``TextSet`` (TextSet.scala:43,247;
tokenize:97, word2idx:147, readCSV:345, readParquet:372), ``TextFeature``,
and the stage classes (Tokenizer, Normalizer, WordIndexer, SequenceShaper,
TextFeatureToSample).

TPU-native design: the pipeline runs on the host in plain Python/numpy and
materializes dense int32 id matrices (fixed ``len`` via pad/truncate) that
batch straight onto the device — the Spark RDD becomes a list, and the
"distributed" variant is host-sharding (shard_index/num_shards) like
ImageSet.
"""

from __future__ import annotations

import csv as _csv
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class TextFeature(dict):
    """One text record: keys text / label / tokens / indexed / sample
    (reference feature/text/TextFeature.scala)."""

    @property
    def text(self) -> str:
        return self.get("text", "")

    @property
    def label(self):
        return self.get("label")


class TextSet:
    """Collection of TextFeatures with chainable stages
    (reference TextSet.scala — stages mutate a copied feature list)."""

    def __init__(self, features: List[TextFeature],
                 word_index: Optional[Dict[str, int]] = None):
        self.features = features
        self.word_index = word_index

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        feats = []
        for i, t in enumerate(texts):
            f = TextFeature(text=t)
            if labels is not None:
                f["label"] = int(labels[i])
            feats.append(f)
        return TextSet(feats)

    @staticmethod
    def read(path: str, num_shards: int = 1, shard_index: int = 0
             ) -> "TextSet":
        """Read a folder-per-class text corpus (reference TextSet.read:290:
        path/<category>/*.txt, category names sorted → 0-based labels)."""
        classes = sorted(d for d in os.listdir(path)
                         if os.path.isdir(os.path.join(path, d)))
        label_map = {c: i for i, c in enumerate(classes)}
        feats = []
        for c in classes:
            cdir = os.path.join(path, c)
            for fn in sorted(os.listdir(cdir)):
                fp = os.path.join(cdir, fn)
                if os.path.isfile(fp):
                    with open(fp, encoding="utf-8", errors="ignore") as f:
                        feats.append(TextFeature(text=f.read(),
                                                 label=label_map[c]))
        ts = TextSet(feats[shard_index::num_shards])
        ts.label_map = label_map
        return ts

    @staticmethod
    def read_csv(path: str, text_col="text", label_col: Optional[str] = "label",
                 **kw) -> "TextSet":
        """Reference TextSet.readCSV:345 (uid,text columns)."""
        feats = []
        with open(path, newline="", encoding="utf-8") as f:
            for row in _csv.DictReader(f):
                feat = TextFeature(text=row[text_col])
                if label_col and label_col in row:
                    feat["label"] = int(row[label_col])
                for k, v in row.items():
                    if k not in (text_col, label_col):
                        feat[k] = v
                feats.append(feat)
        return TextSet(feats)

    @staticmethod
    def read_parquet(path: str, text_col="text",
                     label_col: Optional[str] = "label") -> "TextSet":
        """Reference TextSet.readParquet:372."""
        import pandas as pd

        df = pd.read_parquet(path)
        labels = df[label_col].tolist() if label_col in df else None
        return TextSet.from_texts(df[text_col].tolist(), labels)

    # -- stages ------------------------------------------------------------
    def _map(self, fn: Callable[[TextFeature], TextFeature]) -> "TextSet":
        out = TextSet([fn(TextFeature(f)) for f in self.features],
                      self.word_index)
        if hasattr(self, "label_map"):
            out.label_map = self.label_map
        return out

    def tokenize(self) -> "TextSet":
        """Whitespace/punct split (reference Tokenizer.scala)."""
        pat = re.compile(r"[\w']+")

        def fn(f):
            f["tokens"] = pat.findall(f.text)
            return f

        return self._map(fn)

    def normalize(self) -> "TextSet":
        """Lowercase + strip non-alphanumeric tokens
        (reference Normalizer.scala)."""
        def fn(f):
            toks = [t.lower() for t in f.get("tokens", [])]
            f["tokens"] = [t for t in toks if t and not t.isspace()]
            return f

        return self._map(fn)

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1, existing_map: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build (or reuse) the vocabulary and index tokens; ids are
        1-based with 0 reserved for padding/UNK (reference
        TextSet.word2idx:147 + WordIndexer.scala)."""
        if existing_map is not None:
            vocab = dict(existing_map)
        else:
            freq: Dict[str, int] = {}
            for f in self.features:
                for t in f.get("tokens", []):
                    freq[t] = freq.get(t, 0) + 1
            items = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
            items = [kv for kv in items if kv[1] >= min_freq]
            items = items[remove_topN:]
            if max_words_num > 0:
                items = items[:max_words_num]
            vocab = {w: i + 1 for i, (w, _) in enumerate(items)}

        def fn(f):
            f["indexed"] = [vocab.get(t, 0) for t in f.get("tokens", [])]
            return f

        out = self._map(fn)
        out.word_index = vocab
        return out

    def shape_sequence(self, len: int, trunc_mode: str = "pre",  # noqa: A002
                       pad_element: int = 0) -> "TextSet":
        """Pad/truncate to fixed length (reference SequenceShaper.scala;
        ``trunc_mode='pre'`` keeps/pads at the FRONT like the reference —
        the parameter is named ``len`` for API parity)."""
        target = len

        def fn(f):
            seq = list(f.get("indexed", []))
            n = seq.__len__()
            if n > target:
                seq = seq[-target:] if trunc_mode == "pre" else seq[:target]
            elif n < target:
                pad = [pad_element] * (target - n)
                seq = pad + seq if trunc_mode == "pre" else seq + pad
            f["indexed"] = seq
            return f

        return self._map(fn)

    def generate_sample(self) -> "TextSet":
        """Finalize int32 arrays (reference TextFeatureToSample.scala)."""
        def fn(f):
            f["sample"] = np.asarray(f.get("indexed", []), np.int32)
            return f

        return self._map(fn)

    # -- vocabulary persistence (reference TextSet.saveWordIndex) ----------
    def save_word_index(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.word_index or {}, f)

    @staticmethod
    def load_word_index(path: str) -> Dict[str, int]:
        with open(path) as f:
            return json.load(f)

    # -- materialization ---------------------------------------------------
    def __len__(self):
        return len(self.features)

    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        xs = [f.get("sample", np.asarray(f.get("indexed", []), np.int32))
              for f in self.features]
        x = np.stack(xs)
        labels = [f.label for f in self.features if f.label is not None]
        if labels and np.asarray(labels).shape[0] != x.shape[0]:
            raise ValueError("some records lack labels")
        y = np.asarray(labels, np.int32) if labels else None
        return x, y

    def to_feature_set(self, memory_type: str = "DRAM"):
        from analytics_zoo_tpu.data.featureset import FeatureSet

        x, y = self.to_arrays()
        return FeatureSet.from_ndarrays(x, y, memory_type=memory_type)


def load_glove_embeddings(path: str, word_index: Dict[str, int],
                          dim: Optional[int] = None) -> np.ndarray:
    """Build an embedding matrix (1-based ids, row 0 = pad/UNK zeros) from
    a GloVe text file (reference WordEmbedding.scala).

    Delegates to ``WordEmbedding.from_glove`` — the single GloVe parser —
    which infers the dimension from the file and raises if no vocabulary
    word is found (instead of silently returning a zero table).
    """
    from analytics_zoo_tpu.nn.layers.embedding import WordEmbedding

    emb = WordEmbedding.from_glove(path, word_index)
    table = np.asarray(emb.pretrained, np.float32)
    if dim is not None and table.shape[1] != dim:
        raise ValueError(
            f"GloVe file {path} has dim {table.shape[1]}, expected {dim}")
    return table
