from analytics_zoo_tpu.data.featureset import FeatureSet  # noqa: F401
from analytics_zoo_tpu.data.image import (  # noqa: F401
    ImageFeature,
    ImagePreprocessing,
    ImageSet,
)
from analytics_zoo_tpu.data.text import (  # noqa: F401
    TextFeature,
    TextSet,
    load_glove_embeddings,
)
