from analytics_zoo_tpu.data.featureset import FeatureSet  # noqa: F401
