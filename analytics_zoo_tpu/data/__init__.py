from analytics_zoo_tpu.data.featureset import (  # noqa: F401
    CacheLevel,
    FeatureSet,
    SlicedFeatureSet,
)
from analytics_zoo_tpu.data.giant_table import (  # noqa: F401
    SyntheticGiantTable,
)
from analytics_zoo_tpu.data.image import (  # noqa: F401
    ImageFeature,
    ImagePreprocessing,
    ImageSet,
)
from analytics_zoo_tpu.data.preprocessing import (  # noqa: F401
    ChainedPreprocessing,
    FeatureLabelPreprocessing,
    Preprocessing,
    SeqToTensor,
)
from analytics_zoo_tpu.data.zipf import (  # noqa: F401
    zipf_weights,
    zipfian_ids,
)
from analytics_zoo_tpu.data.text import (  # noqa: F401
    TextFeature,
    TextSet,
    load_glove_embeddings,
)
