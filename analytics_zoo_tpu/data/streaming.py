"""STREAM cache tier — double-buffered host→device shard rotation.

Reference capability: the L2 cache *hierarchy* (DRAM / PMEM /
DISK_AND_DRAM, feature/FeatureSet.scala:690-722) — production datasets
don't fit the fast tier, so the reference stages cached partitions in a
slower-but-bigger medium and feeds workers from there.

TPU-native design: the fast tier is HBM and the capacity tier is host
memory (numpy / mmap — ``FeatureSet.read_rows``), so the middle tier
becomes a *rotation*: the dataset is split into budget-sized shards and
a background uploader thread keeps ``ZooConfig.data_stream_slots``
(default 2 — double buffering) shards alive in HBM, uploading shard
N+1 while the Estimator's jitted shard program trains on shard N.  JAX
dispatch is async, so the training loop only ever blocks when an upload
is slower than a whole shard of compute — the steady-state wait is
bounded by ONE upload, counter-verified by
``data_stream_overlap_frac``.

Shuffle is two-level (the reference's cached index-shuffled partitions,
FeatureSet.scala:229, split across the tiers): the shard ORDER is
permuted per epoch from a seed+epoch-deterministic stream (so resume
needs no extra rng state), and rows WITHIN the resident shard are
permuted on device inside the jitted program.

The compressed device cache (``ZooConfig.data_cache_dtype``) encodes
float feature shards to uint8/int8 host-side
(ops/quantization.quantize_feature_array) and decodes them in-kernel
after the minibatch gather — ~4× more rows per HBM byte for
image/embedding features.

Lease/ready protocol (the ``PrefetchIterator`` pattern with slot
recycling): the uploader owns a free-slot queue; ``get()`` hands the
consumer a :class:`ShardLease`, and ``lease.release(after=carry_leaf)``
returns the slot with a sync handle — before re-using that HBM slot
for shard N+2 the uploader blocks on shard N's output, ON ITS OWN
THREAD, so the wait itself overlaps the main thread's dispatch of
shard N+1.

Multi-controller (``jax.process_count() > 1``): each process stages
ONLY the shard rows its local devices own under ``dataset_sharding``
(:class:`ProcessRowView`), assembles the global jax.Array with
``jax.make_array_from_single_device_arrays``, and rendezvouses with
its peers at a per-shard ``dist_barrier`` deadline — a host that dies
or straggles past the deadline surfaces as a typed
``robust.HostLostError`` on every survivor (fault sites
``data.host_lost`` / ``data.shard_skew``).  Both shuffle levels are
pure functions of ``(seed, epoch[, shard_id])``
(:func:`epoch_shard_order` / :func:`shard_permutation`), so all hosts
agree on the full visit order with zero coordination — which is also
what makes the shard cursor elastic: a run preempted at one process
count re-derives the identical rotation at another.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.observe import metrics as obs
from analytics_zoo_tpu.robust import faults
from analytics_zoo_tpu.robust.errors import HostLostError

logger = logging.getLogger("analytics_zoo_tpu.data")

_SENTINEL = object()


def epoch_shard_order(n_shards: int, seed: int, epoch: int,
                      shuffle: bool = True) -> np.ndarray:
    """Shard visit order for ``epoch`` — level 1 of the two-level
    shuffle.  A pure function of ``(seed, epoch)`` consuming NO carried
    rng state and NO process identity, so every host of a
    multi-controller run derives the identical order with zero
    coordination, and a mid-epoch resume re-derives it from the
    manifest's epoch number alone."""
    if not shuffle or n_shards == 1:
        return np.arange(n_shards)
    rs = np.random.RandomState(
        (int(seed) + 7919 * (int(epoch) + 1)) % (2 ** 31 - 1))
    return rs.permutation(n_shards)


def shard_permutation(n_rows: int, seed: int, epoch: int, shard_id: int,
                      *, shuffle: bool = True,
                      pair_structured: bool = False) -> np.ndarray:
    """Level 2 of the two-level shuffle: the in-shard row permutation,
    a pure function of ``(seed, epoch, shard_id)`` — same zero-
    coordination / elastic-resume contract as
    :func:`epoch_shard_order`.  Mirrors the resident tier's
    ``pair_structured`` layout (adjacent (even, odd) row pairs move
    together, e.g. TextMatcher's (query, candidate) pairs)."""
    if not shuffle:
        return np.arange(n_rows, dtype=np.int32)
    rs = np.random.RandomState(
        (int(seed) + 7919 * (int(epoch) + 1)
         + 104729 * (int(shard_id) + 1)) % (2 ** 31 - 1))
    if pair_structured:
        pairs = rs.permutation(n_rows // 2)
        idx = np.stack([pairs * 2, pairs * 2 + 1], axis=1).reshape(-1)
        if n_rows % 2:
            idx = np.concatenate([idx, np.array([n_rows - 1])])
        return idx.astype(np.int32)
    return rs.permutation(n_rows).astype(np.int32)


class ProcessRowView:
    """The shard-local row spans one process's devices own under
    ``dataset_sharding`` — the multi-controller staging contract.

    Built once per fit from the mesh (every shard shares the same
    static ``shard_rows`` geometry, so one view serves all shards).
    ``load_shard`` reads only these spans from the host dataset;
    ``put_shard`` cuts per-device chunks back out of the staged
    concatenation via :meth:`local_slice`.
    """

    def __init__(self, spans: List[Tuple[int, int]], shard_rows: int):
        self.spans = list(spans)        # ascending unique (start, stop)
        self.shard_rows = shard_rows
        self.local_rows = sum(stop - start for start, stop in self.spans)
        self._offset: Dict[Tuple[int, int], int] = {}
        off = 0
        for start, stop in self.spans:
            self._offset[(start, stop)] = off
            off += stop - start

    @property
    def full(self) -> bool:
        """True when this process stages every row (replicated
        sharding, or a single-process 'mesh')."""
        return self.spans == [(0, self.shard_rows)]

    def local_slice(self, start: int, stop: int) -> slice:
        """Map a device's global shard-row span to its offsets in the
        locally staged concatenation."""
        off = self._offset.get((start, stop))
        if off is None:
            raise StreamUploadError(
                f"device span [{start}, {stop}) is not owned by this "
                f"process (owned: {self.spans})")
        return slice(off, off + (stop - start))

    @classmethod
    def build(cls, ctx, shard_rows: int) -> "ProcessRowView":
        """Derive the view from the mesh's data-axis sharding of a
        ``shard_rows``-row leading dimension (identical row partition
        for every array rank — only dim 0 is ever sharded)."""
        from analytics_zoo_tpu.parallel.sharding import dataset_sharding

        sh = dataset_sharding(ctx.mesh, shard_rows, 1, axis=ctx.data_axis)
        idx_map = sh.addressable_devices_indices_map((shard_rows,))
        spans = set()
        for idx in idx_map.values():
            sl = idx[0] if idx else slice(None)
            lo = 0 if sl.start is None else int(sl.start)
            hi = shard_rows if sl.stop is None else int(sl.stop)
            spans.add((lo, hi))
        return cls(sorted(spans), shard_rows)


class StreamUploadError(RuntimeError):
    """A shard failed to stage/upload (uploader crash, torn shard).

    The Estimator catches this mid-rotation and finishes the epoch's
    remaining shards through the host path — the epoch is never lost.
    """


class StreamPlan:
    """Shard geometry for one STREAM fit: how many shards, how many
    rows each, and which arrays travel quantized.

    All shards share ONE static shape (``shard_rows`` rows, a multiple
    of the effective batch), so a single compiled shard program is
    reused across every shard of every epoch.  The tail beyond
    ``n_shards * shard_rows`` rows is dropped per epoch (< one batch
    per shard — the streaming analog of ``drop_remainder``).
    """

    def __init__(self, *, n_rows: int, n_shards: int, shard_rows: int,
                 steps_per_shard: int, eff_batch: int, slots: int,
                 cache_dtype: Optional[str],
                 specs: List[Tuple[Tuple[int, ...], np.dtype]],
                 quantized: Tuple[bool, ...]):
        self.n_rows = n_rows
        self.n_shards = n_shards
        self.shard_rows = shard_rows
        self.steps_per_shard = steps_per_shard
        self.eff_batch = eff_batch
        self.slots = slots
        self.cache_dtype = cache_dtype
        self.specs = specs              # post-transform (row shape, dtype)
        self.quantized = quantized      # per-array: encoded for upload?
        self.dropped_rows = n_rows - n_shards * shard_rows
        self.device_shard_bytes = shard_rows * self._device_row_bytes()
        # bytes of quantized payload each shard dispatch decodes
        # in-kernel (gathered rows only)
        self.decode_bytes_per_shard = steps_per_shard * eff_batch * sum(
            int(np.prod(shape, dtype=np.int64))
            for (shape, _), q in zip(specs, quantized) if q)

    def _device_row_bytes(self) -> int:
        total = 0
        for (shape, dtype), q in zip(self.specs, self.quantized):
            elems = int(np.prod(shape, dtype=np.int64))
            total += elems * (1 if q else dtype.itemsize)
        return total

    # -- epoch geometry ---------------------------------------------------
    def epoch_order(self, seed: int, epoch: int,
                    shuffle: bool) -> np.ndarray:
        """Shard visit order for ``epoch`` (:func:`epoch_shard_order`)."""
        return epoch_shard_order(self.n_shards, seed, epoch, shuffle)

    def shard_perm(self, seed: int, epoch: int, shard_id: int, *,
                   shuffle: bool = True,
                   pair_structured: bool = False) -> np.ndarray:
        """In-shard row permutation (:func:`shard_permutation`) for this
        plan's static ``shard_rows``."""
        return shard_permutation(self.shard_rows, seed, epoch, shard_id,
                                 shuffle=shuffle,
                                 pair_structured=pair_structured)

    def process_view(self, ctx) -> ProcessRowView:
        """This process's :class:`ProcessRowView` of every shard."""
        return ProcessRowView.build(ctx, self.shard_rows)

    # -- host staging -----------------------------------------------------
    def load_shard(self, fs, shard_id: int,
                   view: Optional[ProcessRowView] = None
                   ) -> List[np.ndarray]:
        """Stage shard ``shard_id``'s rows in host memory: a row-span
        read (DRAM view / mmap pages / SlicedFeatureSet cross-slice
        gather) plus the FeatureSet's transforms, applied once per
        shard (row-independent per the lazy per-batch protocol — same
        contract as ``FeatureSet.device_arrays``).  With a ``view``,
        only the spans this process's devices own are read — the
        multi-controller contract: no host ever stages rows it doesn't
        feed."""
        start = shard_id * self.shard_rows
        if view is None or view.full:
            arrays = fs.read_rows(start, start + self.shard_rows)
        else:
            parts = [fs.read_rows(start + lo, start + hi)
                     for lo, hi in view.spans]
            arrays = [np.concatenate([np.asarray(p[j]) for p in parts],
                                     axis=0)
                      if len(parts) > 1 else parts[0][j]
                      for j in range(len(parts[0]))]
        if fs.transforms:
            batch = tuple(np.asarray(a) for a in arrays)
            for fn in fs.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            arrays = list(batch)
        return arrays

    def validate_shard(self, arrays: Sequence[np.ndarray], shard_id: int,
                       view: Optional[ProcessRowView] = None) -> None:
        """Defense against torn reads: every staged array must match the
        plan's static shard shape (this process's row count under
        ``view``) exactly, or the shard is unusable."""
        rows = self.shard_rows if view is None else view.local_rows
        for j, (a, (shape, dtype)) in enumerate(zip(arrays, self.specs)):
            want = (rows,) + tuple(shape)
            if tuple(a.shape) != want or a.dtype != dtype:
                raise StreamUploadError(
                    f"torn shard {shard_id}: array {j} is "
                    f"{a.shape}/{a.dtype}, expected {want}/{dtype}")

    # -- device staging ---------------------------------------------------
    def _stage_rows(self, a: np.ndarray, sharding, view):
        """One row-sharded device array from locally staged rows.
        Single-controller: a plain ``device_put``.  Multi-controller
        (``view``): cut each addressable device's span out of the local
        staging buffer and assemble the global array with
        ``make_array_from_single_device_arrays`` — no host ever
        materializes rows beyond its own."""
        import jax

        if view is None:
            return jax.device_put(a, sharding)
        global_shape = (self.shard_rows,) + tuple(np.shape(a)[1:])
        idx_map = sharding.addressable_devices_indices_map(global_shape)
        dbs = []
        for dev, idx in idx_map.items():
            sl = idx[0] if idx else slice(None)
            lo = 0 if sl.start is None else int(sl.start)
            hi = global_shape[0] if sl.stop is None else int(sl.stop)
            chunk = np.ascontiguousarray(a[view.local_slice(lo, hi)])
            dbs.append(jax.device_put(chunk, dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, dbs)

    def put_shard(self, arrays: Sequence[np.ndarray], ctx,
                  view: Optional[ProcessRowView] = None) -> List[Any]:
        """Encode + upload one staged shard: quantized arrays travel as
        ``{"q", "scale", "zero"}`` pytrees (per-shard scalar scales),
        rows sharded over the mesh's data axis with the same
        ``dataset_sharding`` specs as a DEVICE cache — dp×tp meshes
        keep working.  Blocks until the transfer lands (the uploader
        thread pays this wait, not the training loop)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.ops.quantization import quantize_feature_array
        from analytics_zoo_tpu.parallel.sharding import dataset_sharding

        rep = NamedSharding(ctx.mesh, P())
        out: List[Any] = []
        for a, q in zip(arrays, self.quantized):
            row_shard = dataset_sharding(ctx.mesh, self.shard_rows,
                                         np.ndim(a), axis=ctx.data_axis)
            if q:
                if view is not None and not view.full:
                    # per-process quantization would derive disagreeing
                    # replicated scale/zero scalars; the router disables
                    # the quantized cache under multi-controller
                    raise StreamUploadError(
                        "quantized stream cache is single-controller "
                        "only (per-host scale/zero would disagree)")
                qa, scale, zero = quantize_feature_array(
                    np.asarray(a), self.cache_dtype)
                out.append({"q": self._stage_rows(qa, row_shard, view),
                            "scale": self.put_replicated(scale, ctx),
                            "zero": self.put_replicated(zero, ctx)})
            else:
                out.append(self._stage_rows(np.asarray(a), row_shard,
                                            view))
        jax.block_until_ready(out)
        return out

    def put_replicated(self, a, ctx) -> Any:
        """A mesh-replicated device array (perm vectors, quant scales) —
        every host holds the full value, so assembly is the
        ``device_put_global`` callback path."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel.sharding import device_put_global

        return device_put_global(np.asarray(a),
                                 NamedSharding(ctx.mesh, P()))

    def probe_inputs(self, fs) -> List[np.ndarray]:
        """Tiny (2-row) post-transform host arrays for the Estimator's
        shape-only model build (features only, label excluded)."""
        rows = min(len(fs), 2)
        arrays = fs.read_rows(0, rows)
        if fs.transforms:
            batch = tuple(np.asarray(a) for a in arrays)
            for fn in fs.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            arrays = list(batch)
        return [np.asarray(a) for a in arrays[:-1]]


def plan_stream(fs, budget_bytes: int, eff_batch: int, *, slots: int = 2,
                cache_dtype: Optional[str] = None
                ) -> Tuple[Optional[StreamPlan], str]:
    """Derive the shard geometry for streaming ``fs`` through a
    ``budget_bytes`` HBM bill, or explain why streaming is infeasible:
    returns ``(plan, "")`` or ``(None, reason)``.

    Each shard is sized for one of ``slots`` HBM slots (budget/slots),
    so the steady-state footprint of the rotation — ``slots`` live
    shards — respects the budget the DEVICE tier would have used.
    """
    n = len(fs)
    if n == 0:
        return None, "empty dataset"
    if eff_batch <= 0 or n < eff_batch:
        return None, (f"dataset ({n} rows) smaller than one effective "
                      f"batch ({eff_batch})")
    probe = fs.read_rows(0, min(n, 2))
    if fs.transforms:
        batch = tuple(np.asarray(a) for a in probe)
        for fn in fs.transforms:
            batch = fn(*batch)
            if not isinstance(batch, tuple):
                batch = (batch,)
        probe = list(batch)
    specs = [(tuple(int(s) for s in np.shape(a)[1:]),
              np.dtype(np.asarray(a).dtype)) for a in probe]
    if len(specs) < 2:
        return None, "streaming needs (inputs..., label) arrays"
    if cache_dtype is not None and cache_dtype not in ("uint8", "int8"):
        raise ValueError(f"unknown data_cache_dtype {cache_dtype!r}; "
                         "known: None, uint8, int8")
    # compress float FEATURE arrays only; the label (last array) and
    # integer features (ids, tokens) pass through unquantized
    quantized = tuple(
        cache_dtype is not None and j < len(specs) - 1
        and np.issubdtype(dtype, np.floating)
        for j, (_, dtype) in enumerate(specs))
    slots = max(2, int(slots))
    slot_budget = max(1, int(budget_bytes) // slots)
    row_bytes = sum(
        int(np.prod(shape, dtype=np.int64)) * (1 if q else dtype.itemsize)
        for (shape, dtype), q in zip(specs, quantized))
    max_rows_per_shard = slot_budget // max(1, row_bytes)
    if max_rows_per_shard < eff_batch:
        return None, (
            f"a {slot_budget}B HBM slot holds {max_rows_per_shard} rows "
            f"({row_bytes}B/row) — less than one batch ({eff_batch})")
    n_shards = max(1, -(-n * row_bytes // slot_budget))   # ceil
    shard_rows = ((n // n_shards) // eff_batch) * eff_batch
    if shard_rows == 0:
        return None, (f"{n} rows over {n_shards} shards leaves no full "
                      f"batch of {eff_batch} per shard")
    plan = StreamPlan(
        n_rows=n, n_shards=n_shards, shard_rows=shard_rows,
        steps_per_shard=shard_rows // eff_batch, eff_batch=eff_batch,
        slots=slots, cache_dtype=cache_dtype, specs=specs,
        quantized=quantized)
    if plan.dropped_rows:
        logger.warning(
            "STREAM tier drops %d/%d rows per epoch (%d shards x %d "
            "rows; < one batch per shard, the streaming analog of "
            "drop_remainder)", plan.dropped_rows, n, n_shards, shard_rows)
    return plan, ""


class ShardLease:
    """One uploaded shard, alive in an HBM slot until released.

    ``release(after=...)`` hands the slot back to the uploader with a
    sync handle (any device array produced by this shard's compute);
    the uploader blocks on it — on its own thread — before overwriting
    the slot, which is what makes slot recycling safe without the
    training loop ever waiting on uploads it doesn't need yet.
    """

    __slots__ = ("position", "shard_id", "xs", "y", "perm", "_slot",
                 "_uploader", "_released")

    def __init__(self, position: int, shard_id: int, arrays: List[Any],
                 slot: int, uploader: "ShardUploader",
                 perm: Any = None):
        self.position = position        # index into the epoch's order
        self.shard_id = shard_id        # which fixed partition
        self.xs = arrays[:-1]
        self.y = arrays[-1]
        self.perm = perm                # replicated in-shard row perm
        self._slot = slot
        self._uploader = uploader
        self._released = False

    def release(self, after: Any = None) -> None:
        if self._released:
            return
        self._released = True
        self._uploader._release_slot(self._slot, after)


class ShardUploader:
    """Background shard staging: load → (encode) → ``device_put`` on a
    daemon thread, ``slots`` shards ahead of the consumer at most.

    The ``PrefetchIterator`` contract carried over: producer exceptions
    surface at the consumption point (as :class:`StreamUploadError`;
    a ``HostLostError`` passes through UNWRAPPED so the mesh-death
    signal keeps its type), the sentinel is never dropped, and
    ``close()`` is idempotent and bounded.  What's new is the slot
    protocol (see :class:`ShardLease`) and the fault sites
    ``data.shard_upload`` (planned crash per shard),
    ``data.shard_torn`` (planned truncation caught by shape
    validation), ``data.shard_skew`` (planned straggle — sleeps the
    plan's payload seconds, or raises its exc), and ``data.host_lost``
    (planned peer death — raises ``HostLostError``).

    Multi-controller kwargs: ``view`` restricts staging to this
    process's rows; ``perm_fn(shard_id)`` supplies the
    (seed, epoch, shard)-pure in-shard permutation uploaded replicated
    with the shard; ``barrier_fn(position)`` rendezvouses all hosts
    after each staged shard, ON THIS THREAD — a dead or straggling
    peer turns into a deadline ``HostLostError`` here, which ``get()``
    re-raises typed on the training thread.
    """

    def __init__(self, fs, plan: StreamPlan, order: np.ndarray, ctx, *,
                 start: int = 0, view: Optional[ProcessRowView] = None,
                 perm_fn=None, barrier_fn=None):
        self._plan = plan
        self._ready: "queue.Queue" = queue.Queue()
        self._free: "queue.Queue" = queue.Queue()
        for slot in range(plan.slots):
            self._free.put((slot, None))
        self._stop = threading.Event()
        self._err_lock = threading.Lock()
        self._err: Optional[BaseException] = None
        self._closed = False
        self._close_lock = threading.Lock()
        # stats written by the uploader thread, read by stats() on the
        # training thread — lock-guarded on both sides
        self._stats_lock = threading.Lock()
        self._upload_ms_total = 0.0
        self._uploads = 0

        def put_retry(obj) -> bool:
            while not self._stop.is_set():
                try:
                    self._ready.put(obj, timeout=0.1)
                    return True
                except queue.Full:      # pragma: no cover - unbounded q
                    continue
            return False

        def claim_slot() -> Optional[Tuple[int, Any]]:
            while not self._stop.is_set():
                try:
                    return self._free.get(timeout=0.1)
                except queue.Empty:
                    continue
            return None

        def run():
            try:
                for pos in range(start, len(order)):
                    slot = claim_slot()
                    if slot is None:
                        return          # closed mid-rotation
                    slot_id, after = slot
                    if after is not None:
                        # shard (pos - slots)'s compute must finish
                        # before its HBM slot is overwritten; this wait
                        # runs HERE, overlapping the main thread's
                        # dispatch of the shard in the other slot
                        import jax
                        jax.block_until_ready(after)
                    shard_id = int(order[pos])
                    # chaos hooks: a planned straggler host sleeps (or
                    # raises) here; a planned peer death raises typed
                    faults_skew = faults.fire("data.shard_skew")
                    if faults_skew is not None:
                        if faults_skew.exc is not None:
                            raise faults_skew.exc
                        time.sleep(float(faults_skew.payload or 0.0))
                    lost = faults.fire("data.host_lost")
                    if lost is not None:
                        raise (lost.exc if lost.exc is not None
                               else HostLostError(
                                   f"injected host loss while staging "
                                   f"shard {shard_id}",
                                   barrier="data.host_lost"))
                    # chaos hook: a planned uploader crash surfaces here
                    faults.inject("data.shard_upload")
                    t0 = time.perf_counter()
                    host = plan.load_shard(fs, shard_id, view=view)
                    torn = faults.fire("data.shard_torn")
                    if torn is not None:
                        if torn.exc is not None:
                            raise torn.exc
                        # a torn read delivers short rows; validation
                        # below catches it like the real thing
                        host = [a[:max(0, len(a) // 2)] for a in host]
                    plan.validate_shard(host, shard_id, view=view)
                    dev = plan.put_shard(host, ctx, view=view)
                    perm = (plan.put_replicated(perm_fn(shard_id), ctx)
                            if perm_fn is not None else None)
                    del host            # release staging before waiting
                    dt_ms = (time.perf_counter() - t0) * 1e3
                    obs.observe("data_shard_upload_ms", dt_ms,
                                flat="stream/shard_upload_ms")
                    with self._stats_lock:
                        self._upload_ms_total += dt_ms
                        self._uploads += 1
                    if barrier_fn is not None:
                        # all hosts staged this position, or a deadline
                        # HostLostError fires — on the uploader thread,
                        # overlapping the main thread's dispatch
                        barrier_fn(pos)
                    if not put_retry(ShardLease(pos, shard_id, dev,
                                                slot_id, self,
                                                perm=perm)):
                        return
            except BaseException as e:  # propagate to consumer
                with self._err_lock:
                    self._err = e
            finally:
                put_retry(_SENTINEL)

        self._thread = threading.Thread(
            target=run, daemon=True, name="zoo-shard-uploader")
        self._thread.start()

    # -- consumer side ----------------------------------------------------
    def get(self) -> ShardLease:
        """Next uploaded shard; blocks while the uploader is behind
        (the blocked time is the ``data_shard_wait_ms`` histogram — at
        steady state it should be near zero)."""
        t0 = time.perf_counter()
        item = self._get()
        obs.observe("data_shard_wait_ms", (time.perf_counter() - t0) * 1e3,
                    flat="stream/shard_wait_ms")
        if item is _SENTINEL:
            self._thread.join()
            err = self._error()
            if err is not None:
                if isinstance(err, (StreamUploadError, HostLostError)):
                    raise err
                raise StreamUploadError(
                    f"shard uploader failed: {err}") from err
            raise StreamUploadError(
                "shard uploader exhausted before the rotation finished")
        return item

    def _get(self):
        while True:
            try:
                return self._ready.get(timeout=1.0)
            except queue.Empty:
                if not self._thread.is_alive():
                    try:
                        return self._ready.get_nowait()
                    except queue.Empty:
                        err = self._error()
                        if err is not None:
                            if isinstance(err, (StreamUploadError,
                                                HostLostError)):
                                raise err
                            raise StreamUploadError(
                                f"shard uploader died: {err}") from err
                        raise StreamUploadError(
                            "shard uploader thread died without a "
                            "sentinel") from None

    def _error(self) -> Optional[BaseException]:
        with self._err_lock:
            return self._err

    def _release_slot(self, slot: int, after: Any) -> None:
        self._free.put((slot, after))

    def stats(self) -> Dict[str, float]:
        with self._stats_lock:
            return {"upload_ms_total": self._upload_ms_total,
                    "uploads": float(self._uploads)}

    def close(self, timeout: float = 5.0) -> None:
        """Stop the uploader (early exit / fallback paths).  Idempotent;
        drains the ready queue so a producer blocked in ``put_retry``
        observes the stop flag, then joins with a bounded timeout."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        deadline = None
        while self._thread.is_alive():
            try:
                while True:
                    self._ready.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
            if not self._thread.is_alive():
                break
            if deadline is None:
                deadline = time.monotonic() + timeout
            elif time.monotonic() > deadline:
                logger.warning(
                    "shard uploader did not stop within %.1fs of "
                    "close(); abandoned (daemon thread)", timeout)
                break
