"""3D (medical) image transforms: crop, rotate, affine.

Reference capability: feature/image3d/{Affine,Cropper,Rotation,Warp,
ImageProcessing3D}.scala (~900 LoC, SURVEY.md §2.1).

Host-side numpy/scipy implementations over (D, H, W) or (D, H, W, C)
volumes, chainable with the 2D pipeline's combinator protocol.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.image import ImageFeature, ImagePreprocessing


class Crop3D(ImagePreprocessing):
    """Crop a (D,H,W) patch at ``start`` (or centered)
    (reference image3d/Cropper.scala)."""

    def __init__(self, start: Optional[Sequence[int]] = None,
                 patch_size: Sequence[int] = (32, 32, 32)):
        self.start = tuple(start) if start is not None else None
        self.patch = tuple(patch_size)

    def apply(self, feat, rng):
        vol = feat.image
        if self.start is None:
            start = tuple((s - p) // 2 for s, p in zip(vol.shape, self.patch))
        else:
            start = self.start
        sl = tuple(slice(s, s + p) for s, p in zip(start, self.patch))
        feat.image = vol[sl]
        return feat


class RandomCrop3D(ImagePreprocessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch = tuple(patch_size)

    def apply(self, feat, rng):
        vol = feat.image
        start = tuple(rng.randint(0, max(s - p, 0) + 1)
                      for s, p in zip(vol.shape, self.patch))
        sl = tuple(slice(s, s + p) for s, p in zip(start, self.patch))
        feat.image = vol[sl]
        return feat


class Rotate3D(ImagePreprocessing):
    """Rotate by Euler angles (radians) about the volume center
    (reference image3d/Rotation.scala: rotationAxisAngle)."""

    def __init__(self, yaw: float = 0.0, pitch: float = 0.0,
                 roll: float = 0.0, order: int = 1):
        self.angles = (yaw, pitch, roll)
        self.order = order

    @staticmethod
    def _rot_matrix(yaw, pitch, roll) -> np.ndarray:
        cy, sy = np.cos(yaw), np.sin(yaw)
        cp, sp = np.cos(pitch), np.sin(pitch)
        cr, sr = np.cos(roll), np.sin(roll)
        rz = np.array([[cy, -sy, 0], [sy, cy, 0], [0, 0, 1]])
        ry = np.array([[cp, 0, sp], [0, 1, 0], [-sp, 0, cp]])
        rx = np.array([[1, 0, 0], [0, cr, -sr], [0, sr, cr]])
        return rz @ ry @ rx

    def apply(self, feat, rng):
        mat = self._rot_matrix(*self.angles)
        return AffineTransform3D(mat, order=self.order).apply(feat, rng)


class AffineTransform3D(ImagePreprocessing):
    """Apply a 3x3 linear map (+ translation) about the center
    (reference image3d/Affine.scala)."""

    def __init__(self, mat: np.ndarray,
                 translation: Sequence[float] = (0, 0, 0), order: int = 1):
        self.mat = np.asarray(mat, np.float64)
        self.translation = np.asarray(translation, np.float64)
        self.order = order

    def apply(self, feat, rng):
        from scipy import ndimage

        vol = feat.image
        center = (np.asarray(vol.shape[:3]) - 1) / 2.0
        # scipy pulls: output(x) = input(matrix @ x + offset)
        inv = np.linalg.inv(self.mat)
        offset = center - inv @ (center + self.translation)

        def warp(v3d):
            return ndimage.affine_transform(
                v3d, inv, offset=offset, order=self.order, mode="nearest")

        if vol.ndim == 4:  # (D, H, W, C): per-channel spatial warp
            feat.image = np.stack(
                [warp(vol[..., c]) for c in range(vol.shape[-1])], axis=-1)
        else:
            feat.image = warp(vol)
        return feat


class Warp3D(ImagePreprocessing):
    """Warp a 3D volume by a dense displacement field with trilinear
    interpolation (reference feature/image3d/Warp.scala /
    WarpTransformer).

    ``field``: (D, H, W, 3) voxel displacements (dz, dy, dx); output voxel
    v = volume[v + field[v]], sampled trilinearly, zero-padded outside.
    """

    def __init__(self, field: np.ndarray, clamp: bool = True):
        self.field = np.asarray(field, np.float32)
        self.clamp = clamp

    def apply(self, feat, rng):
        vol = np.asarray(feat.image, np.float32)
        d, h, w = vol.shape[:3]
        extra = vol.ndim - 3                  # trailing channel dims
        zz, yy, xx = np.meshgrid(np.arange(d), np.arange(h), np.arange(w),
                                 indexing="ij")
        src = np.stack([zz, yy, xx], axis=-1).astype(np.float32) + self.field
        if self.clamp:
            src[..., 0] = np.clip(src[..., 0], 0, d - 1)
            src[..., 1] = np.clip(src[..., 1], 0, h - 1)
            src[..., 2] = np.clip(src[..., 2], 0, w - 1)
        # unclipped corner indices: per-corner validity gives true
        # zero-padding (a corner outside the volume contributes 0, the
        # in-range corners keep their trilinear weights)
        z0u = np.floor(src[..., 0]).astype(np.int64)
        y0u = np.floor(src[..., 1]).astype(np.int64)
        x0u = np.floor(src[..., 2]).astype(np.int64)
        wz = src[..., 0] - z0u
        wy = src[..., 1] - y0u
        wx = src[..., 2] - x0u

        def expand(a):
            return a.reshape(a.shape + (1,) * extra)

        wz, wy, wx = expand(wz), expand(wy), expand(wx)

        def corner(zi, yi, xi):
            valid = ((zi >= 0) & (zi < d) & (yi >= 0) & (yi < h)
                     & (xi >= 0) & (xi < w))
            v = vol[np.clip(zi, 0, d - 1), np.clip(yi, 0, h - 1),
                    np.clip(xi, 0, w - 1)]
            return v * expand(valid.astype(np.float32))

        out = ((1 - wz) * (1 - wy) * (1 - wx) * corner(z0u, y0u, x0u)
               + (1 - wz) * (1 - wy) * wx * corner(z0u, y0u, x0u + 1)
               + (1 - wz) * wy * (1 - wx) * corner(z0u, y0u + 1, x0u)
               + (1 - wz) * wy * wx * corner(z0u, y0u + 1, x0u + 1)
               + wz * (1 - wy) * (1 - wx) * corner(z0u + 1, y0u, x0u)
               + wz * (1 - wy) * wx * corner(z0u + 1, y0u, x0u + 1)
               + wz * wy * (1 - wx) * corner(z0u + 1, y0u + 1, x0u)
               + wz * wy * wx * corner(z0u + 1, y0u + 1, x0u + 1))
        feat.image = out.astype(vol.dtype)
        return feat
