"""TFRecord IO + tf.Example parsing — no TensorFlow dependency.

Reference capability: ``TFDataset.from_tfrecord_file``
(pyzoo/zoo/tfpark/tf_dataset.py:458) read TFRecords through a TF graph
per partition.  Here the record framing (length + masked crc32c headers)
is read/written directly — checksums via the native crc32c when built
(native/zoo_native.cpp), python table fallback otherwise — and
``tf.Example`` protos are decoded with the same minimal wire-format
machinery as the ONNX importer (onnx/proto.py).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Sequence, Union

import numpy as np

from analytics_zoo_tpu.native import masked_crc32c
from analytics_zoo_tpu.onnx.proto import (_fields, _key, _ld, _read_varint,
                                          _signed, _write_varint)

__all__ = ["write_tfrecords", "read_tfrecords", "parse_example",
           "make_example", "read_example_file"]


# ---------------------------------------------------------------------------
# record framing:  [len u64][masked_crc(len) u32][data][masked_crc(data) u32]
# ---------------------------------------------------------------------------

def write_tfrecords(path: str, records: Sequence[bytes]) -> None:
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))


def read_tfrecords(path: str, verify: bool = True) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError("truncated TFRecord: partial length header")
            (length,) = struct.unpack("<Q", header)
            hbuf = f.read(4)
            data = f.read(length)
            dbuf = f.read(4)
            if len(hbuf) < 4 or len(data) < length or len(dbuf) < 4:
                raise ValueError("truncated TFRecord: partial record")
            if verify:
                if masked_crc32c(header) != struct.unpack("<I", hbuf)[0]:
                    raise ValueError("corrupt TFRecord length header")
                if masked_crc32c(data) != struct.unpack("<I", dbuf)[0]:
                    raise ValueError("corrupt TFRecord payload")
            yield data


# ---------------------------------------------------------------------------
# tf.Example encode/decode (proto wire format; field numbers from the
# public example.proto/feature.proto spec)
#   Example{ features: 1 = Features{ feature: 1 = map<string, Feature> } }
#   Feature{ bytes_list: 1, float_list: 2, int64_list: 3 }
#   *List{ value: 1 (repeated / packed) }
# ---------------------------------------------------------------------------

FeatureValue = Union[np.ndarray, List[bytes]]


def _decode_list(buf: bytes, kind: str) -> FeatureValue:
    vals: List = []
    for fnum, wtype, val in _fields(buf):
        if fnum != 1:
            continue
        if kind == "bytes":
            vals.append(val)
        elif kind == "float":
            if wtype == 2:      # packed
                vals.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                vals.append(struct.unpack("<f", val)[0])
        else:                   # int64
            if wtype == 2:      # packed varints
                pos = 0
                while pos < len(val):
                    v, pos = _read_varint(val, pos)
                    vals.append(_signed(v))
            else:
                vals.append(_signed(val))
    if kind == "bytes":
        return vals
    return np.asarray(vals,
                      np.float32 if kind == "float" else np.int64)


def _decode_feature(buf: bytes) -> FeatureValue:
    for fnum, _, val in _fields(buf):
        if fnum == 1:
            return _decode_list(val, "bytes")
        if fnum == 2:
            return _decode_list(val, "float")
        if fnum == 3:
            return _decode_list(val, "int64")
    return np.asarray([], np.float32)


def parse_example(buf: bytes) -> Dict[str, FeatureValue]:
    """tf.Example bytes -> {name: ndarray | [bytes]}."""
    out: Dict[str, FeatureValue] = {}
    for fnum, _, val in _fields(buf):               # Example
        if fnum != 1:
            continue
        for f2, _, fmap in _fields(val):            # Features
            if f2 != 1:
                continue
            name, feat = None, None
            for f3, _, v3 in _fields(fmap):         # map entry
                if f3 == 1:
                    name = v3.decode()
                elif f3 == 2:
                    feat = v3
            if name is not None and feat is not None:
                out[name] = _decode_feature(feat)
    return out


def make_example(features: Dict[str, FeatureValue]) -> bytes:
    """{name: array | [bytes]} -> tf.Example bytes (for tests/export)."""
    entries = b""
    for name, value in features.items():
        if isinstance(value, (list, tuple)) and value \
                and isinstance(value[0], (bytes, str)):
            payload = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else v)
                for v in value)
            feat = _ld(1, payload)                  # bytes_list
        else:
            arr = np.asarray(value)
            if np.issubdtype(arr.dtype, np.floating):
                packed = struct.pack(f"<{arr.size}f",
                                     *arr.astype(np.float32).ravel())
                feat = _ld(2, _ld(1, packed))       # float_list packed
            else:
                payload = b"".join(
                    _key(1, 0) + _write_varint(int(v))
                    for v in arr.ravel())
                feat = _ld(3, payload)              # int64_list
        entries += _ld(1, _ld(1, name.encode()) + _ld(2, feat))
    return _ld(1, entries)                          # Example.features


def read_example_file(path: str) -> List[Dict[str, FeatureValue]]:
    """Parse every tf.Example in a TFRecord file
    (the from_tfrecord_file capability, tf_dataset.py:458)."""
    return [parse_example(rec) for rec in read_tfrecords(path)]
