"""Seeded zipfian id draws — ONE source of truth for skewed traffic.

Recommender lookups are zipfian; every leg of the repo that simulates
that skew (the ``bench.py`` sharded-table legs, the loadgen
``ZipfianIdPayload`` class, the hot-cache tests) draws through this
module so their id streams are **byte-identical** for the same
``(vocab, n, s, seed)`` — a bench claim about hit rates at skew s=1.0
is then literally about the distribution the load harness offers.

The draw is a plain ``Generator.choice`` over the normalized
``1/rank**s`` weights (rank 1 = id 0): deterministic from the generator
state, no rejection sampling, so callers that interleave other draws on
the same generator consume exactly one ``choice`` per call.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["zipf_weights", "zipfian_ids"]


def zipf_weights(vocab: int, s: float = 1.0) -> np.ndarray:
    """Normalized zipf pmf over ids ``0..vocab-1``: id k has weight
    ``1/(k+1)**s`` (id 0 is the hottest row).  ``s=0`` is uniform."""
    if vocab <= 0:
        raise ValueError(f"vocab must be positive, got {vocab}")
    ranks = np.arange(1, int(vocab) + 1, dtype=np.float64)
    w = ranks ** -float(s)
    return w / w.sum()


def zipfian_ids(vocab: int, n: int, s: float = 1.0, *, seed: int = 0,
                rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """``n`` int32 ids drawn zipf(s) over ``0..vocab-1``.

    Pass ``rng`` to ride an existing ``np.random.Generator`` stream
    (the loadgen payload path — deterministic per (seed, arrival
    index)); without one, ``default_rng(seed)`` makes the draw
    self-contained.  Same (vocab, n, s) and generator state -> the same
    bytes, whichever caller asks.
    """
    if rng is None:
        rng = np.random.default_rng(seed)
    p = zipf_weights(vocab, s)
    return rng.choice(int(vocab), size=int(n), p=p).astype(np.int32)
