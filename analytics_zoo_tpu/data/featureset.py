"""FeatureSet — host-side dataset abstraction with memory tiers.

Reference capability: ``FeatureSet.rdd(memoryType=...)``
(feature/FeatureSet.scala:690-722) with cached index-shuffled partitions
(CachedDistributedFeatureSet:229), disk spilling (DiskFeatureSet:585,
numSlice DISK_AND_DRAM), and PMEM tiers (feature/pmem/).

TPU-native design: there is no RDD — data lives on the *host* as numpy
arrays (DRAM) or memory-mapped .npy slices on disk (DISK_AND_DRAM /
DIRECT), and is fed to the device mesh by the Estimator, which shards each
batch along the data axis.  PMEM has no TPU-host equivalent; the capacity
use-case is covered by the mmap tier.  Transform pipelines
(``Preprocessing`` chains, feature/common/Preprocessing.scala) become
``.transform(fn)`` stages applied lazily per batch on the host.
"""

from __future__ import annotations

import math
import os
import tempfile
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

MemoryType = str  # "DRAM" | "DISK_AND_DRAM" | "DIRECT"


class FeatureSet:
    """A set of aligned arrays (inputs..., label) with lazy transforms.

    ``batches(batch_size)`` yields tuples of numpy arrays; the final
    element is the label (if present).
    """

    def __init__(self, arrays: Sequence[np.ndarray],
                 memory_type: MemoryType = "DRAM",
                 transforms: Optional[List[Callable]] = None,
                 seed: int = 0):
        if not arrays:
            raise ValueError("FeatureSet needs at least one array")
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("arrays must be aligned on dim 0")
        self.memory_type = memory_type.upper()
        self.transforms = list(transforms or [])
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        if self.memory_type in ("DISK_AND_DRAM", "DIRECT"):
            self.arrays = [self._to_mmap(np.asarray(a)) for a in arrays]
        else:
            self.arrays = [np.asarray(a) for a in arrays]

    # -- constructors (parity with FeatureSet.rdd / ImageSet / TextSet) ---
    @staticmethod
    def from_ndarrays(x, y=None, memory_type: MemoryType = "DRAM",
                      seed: int = 0) -> "FeatureSet":
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        if y is not None:
            xs = xs + [y]
        return FeatureSet(xs, memory_type=memory_type, seed=seed)

    @staticmethod
    def from_npy_files(paths: Sequence[str],
                       memory_type: MemoryType = "DISK_AND_DRAM"
                       ) -> "FeatureSet":
        mode = "r" if memory_type.upper() != "DRAM" else None
        arrays = [np.load(p, mmap_mode=mode) for p in paths]
        fs = FeatureSet.__new__(FeatureSet)
        fs.memory_type = memory_type.upper()
        fs.transforms = []
        fs.seed = 0
        fs._rng = np.random.RandomState(0)
        fs.arrays = list(arrays)
        return fs

    @staticmethod
    def from_parquet(path: str, feature_cols: Sequence[str], label_col: str,
                     memory_type: MemoryType = "DRAM") -> "FeatureSet":
        """Columnar ingestion (replaces the reference's Spark DataFrame
        path, TextSet.readParquet feature/text/TextSet.scala:372)."""
        import pandas as pd  # available via baked-in deps

        df = pd.read_parquet(path)
        arrays = [np.stack(df[c].to_numpy()) for c in feature_cols]
        arrays.append(df[label_col].to_numpy())
        return FeatureSet(arrays, memory_type=memory_type)

    # -- transforms -------------------------------------------------------
    def transform(self, fn: Callable[..., Tuple[np.ndarray, ...]]
                  ) -> "FeatureSet":
        """Append a per-batch transform ``fn(*arrays) -> arrays`` (lazy)."""
        fs = FeatureSet.__new__(FeatureSet)
        fs.arrays = self.arrays
        fs.memory_type = self.memory_type
        fs.transforms = self.transforms + [fn]
        fs.seed = self.seed
        fs._rng = self._rng
        return fs

    # -- iteration --------------------------------------------------------
    def __len__(self) -> int:
        return len(self.arrays[0])

    @property
    def size(self) -> int:
        return len(self)

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = False, pad_to: int = 1,
                shuffle_buffer: Optional[int] = None
                ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield batches; ``pad_to`` rounds batch_size up to a multiple
        (device count) so every batch shards evenly over the mesh.

        ``shuffle_buffer`` (config ``shuffle_buffer`` knob) bounds the
        shuffle window: rows are permuted within contiguous blocks of that
        size and the block order is permuted — a locality-preserving
        shuffle so disk-backed tiers (DISK_AND_DRAM/DIRECT mmaps) read
        near-sequentially instead of seeking across the whole file
        (replaces the reference's cached index-shuffled partitions,
        feature/FeatureSet.scala:229).  ``None``/``>=n`` = full
        permutation.
        """
        n = len(self)
        bs = int(math.ceil(batch_size / pad_to)) * pad_to
        if not shuffle:
            order = np.arange(n)
        elif shuffle_buffer is not None and 0 < shuffle_buffer < n:
            buf = int(shuffle_buffer)
            starts = np.arange(0, n, buf)
            self._rng.shuffle(starts)
            order = np.concatenate([
                s + self._rng.permutation(min(buf, n - s)) for s in starts])
        else:
            order = self._rng.permutation(n)
        steps = n // bs if drop_remainder else int(math.ceil(n / bs))
        for s in range(steps):
            idx = order[s * bs:(s + 1) * bs]
            batch = tuple(self._gather(a, idx) for a in self.arrays)
            for fn in self.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            yield batch

    @staticmethod
    def _gather(a: np.ndarray, idx: np.ndarray) -> np.ndarray:
        """Batch assembly: parallel native row gather for big copies
        (native/zoo_native.cpp — the MTSampleToMiniBatch role), numpy
        fancy indexing otherwise."""
        row_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=int))
        if row_bytes * len(idx) >= (1 << 20) and a.flags["C_CONTIGUOUS"]:
            try:
                from analytics_zoo_tpu.native import (available,
                                                      gather_rows)

                if available():
                    return gather_rows(a, idx)
            except Exception:
                pass
        return np.asarray(a[idx])

    # -- internals --------------------------------------------------------
    @staticmethod
    def _to_mmap(a: np.ndarray) -> np.ndarray:
        """Spill an array to a disk-backed mmap (DISK_AND_DRAM tier)."""
        fd, path = tempfile.mkstemp(suffix=".npy", prefix="zoo_featureset_")
        os.close(fd)
        np.save(path, a)
        return np.load(path, mmap_mode="r")

    # -- slice-wise disk epochs ------------------------------------------
    @staticmethod
    def from_npy_slices(slices: Sequence[Sequence[str]],
                        seed: int = 0) -> "SlicedFeatureSet":
        """Slice-wise disk training (reference DiskFeatureSet numSlice,
        feature/FeatureSet.scala:585): ``slices`` is a list of aligned
        .npy path tuples; one slice is resident in DRAM at a time and
        epochs stream slice-by-slice (slice order + rows-within-slice
        shuffled), bounding host memory to the largest slice."""
        return SlicedFeatureSet(slices, seed=seed)


class SlicedFeatureSet(FeatureSet):
    """A FeatureSet whose rows live in per-slice .npy files on disk;
    only one slice is materialised in DRAM at a time."""

    def __init__(self, slices: Sequence[Sequence[str]], seed: int = 0):
        if not slices:
            raise ValueError("need at least one slice")
        self.slice_paths = [tuple(s) for s in slices]
        width = len(self.slice_paths[0])
        if any(len(s) != width for s in self.slice_paths):
            raise ValueError("every slice must have the same array count")
        self.memory_type = "DISK_AND_DRAM"
        self.transforms = []
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        # row counts from headers only (no data load)
        self._slice_rows = []
        for s in self.slice_paths:
            counts = {len(np.load(p, mmap_mode="r")) for p in s}
            if len(counts) != 1:
                raise ValueError(f"slice {s} arrays are not aligned")
            self._slice_rows.append(counts.pop())

    def transform(self, fn) -> "SlicedFeatureSet":
        fs = SlicedFeatureSet.__new__(SlicedFeatureSet)
        fs.__dict__.update(self.__dict__)
        fs.transforms = self.transforms + [fn]
        return fs

    def __len__(self) -> int:
        return int(sum(self._slice_rows))

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = False, pad_to: int = 1,
                shuffle_buffer: Optional[int] = None
                ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Stream batches slice-by-slice.  Rows left over when a slice
        doesn't divide the batch are CARRIED into the next slice (total
        loss per epoch is < one batch, same as the base class), so small
        slices still contribute every row.  ``shuffle_buffer`` is
        accepted but moot here: the resident slice IS the shuffle window
        by construction."""
        bs = int(math.ceil(batch_size / pad_to)) * pad_to
        order = (self._rng.permutation(len(self.slice_paths)) if shuffle
                 else np.arange(len(self.slice_paths)))
        carry: Optional[List[np.ndarray]] = None

        def emit(batch):
            for fn in self.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            return batch

        for si in order:
            arrays = [np.load(p) for p in self.slice_paths[si]]  # DRAM now
            if carry is not None:
                arrays = [np.concatenate([c, a])
                          for c, a in zip(carry, arrays)]
                carry = None
            n = len(arrays[0])
            rows = self._rng.permutation(n) if shuffle else np.arange(n)
            for s in range(n // bs):
                idx = rows[s * bs:(s + 1) * bs]
                yield emit(tuple(a[idx] for a in arrays))
            rem = rows[(n // bs) * bs:]
            if len(rem):
                carry = [a[rem] for a in arrays]
            del arrays          # release the slice before loading the next
        if carry is not None and not drop_remainder:
            yield emit(tuple(carry))
