"""FeatureSet — host-side dataset abstraction with memory tiers.

Reference capability: ``FeatureSet.rdd(memoryType=...)``
(feature/FeatureSet.scala:690-722) with cached index-shuffled partitions
(CachedDistributedFeatureSet:229), disk spilling (DiskFeatureSet:585,
numSlice DISK_AND_DRAM), and PMEM tiers (feature/pmem/).

TPU-native design: there is no RDD — data lives on the *host* as numpy
arrays (DRAM) or memory-mapped .npy slices on disk (DISK_AND_DRAM /
DIRECT), and is fed to the device mesh by the Estimator, which shards each
batch along the data axis.  PMEM has no TPU-host equivalent; the capacity
use-case is covered by the mmap tier.  Transform pipelines
(``Preprocessing`` chains, feature/common/Preprocessing.scala) become
``.transform(fn)`` stages applied lazily per batch on the host.
"""

from __future__ import annotations

import math
import os
import tempfile
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

MemoryType = str  # "DRAM" | "DISK_AND_DRAM" | "DIRECT"


def npy_header(path: str) -> Tuple[Tuple[int, ...], np.dtype]:
    """(shape, dtype) of a .npy file from its header ONLY — no data is
    read or mapped, so the tier auto-router can classify beyond-memory
    datasets without touching their rows."""
    with open(path, "rb") as f:
        version = np.lib.format.read_magic(f)
        shape, _fortran, dtype = np.lib.format._read_array_header(f, version)
    return tuple(int(s) for s in shape), np.dtype(dtype)


class CacheLevel:
    """Where a FeatureSet's rows live while the Estimator trains from it.

    Mirrors the reference's memory tiers (DRAM / PMEM,
    feature/FeatureSet.scala:690-722) translated to TPU hosts: the
    capacity tier there (PMEM) becomes HBM residency here — the fast
    tier is *on the accelerator*, not a slower-but-bigger host medium.

    - ``HOST``: rows stay on the host (numpy / mmap per ``memory_type``);
      batches are assembled per step and ``device_put`` onto the mesh
      (overlapped via train/prefetch.py).
    - ``DEVICE``: the whole dataset is materialized into HBM once and the
      Estimator's device-resident epoch body shuffles and gathers
      minibatches *inside* the compiled step — zero host→device bytes
      per epoch.  Over ``ZooConfig.data_device_budget_bytes`` it
      upgrades to STREAM (or HOST when streaming is not feasible).
    - ``STREAM``: the middle tier for datasets bigger than HBM (the
      reference's PMEM capacity tier, feature/FeatureSet.scala:690-722,
      made TPU-native): the dataset is split into budget-sized shards
      staged on the host, and a background uploader
      (data/streaming.ShardUploader) rotates them through HBM with
      double-buffered async ``device_put`` — shard N+1 uploads while
      the jitted shard program trains on shard N.  Two-level shuffle
      (shard order per epoch, on-device permutation within the shard);
      optional uint8/int8 compressed shards decoded in-kernel
      (``ZooConfig.data_cache_dtype``).
    """

    HOST = "HOST"
    DEVICE = "DEVICE"
    STREAM = "STREAM"

    _LEVELS = (HOST, DEVICE, STREAM)

    @staticmethod
    def normalize(level: str) -> str:
        lv = str(level).upper()
        if lv not in CacheLevel._LEVELS:
            raise ValueError(f"unknown cache level {level!r}; "
                             f"known: {CacheLevel._LEVELS}")
        return lv


class FeatureSet:
    """A set of aligned arrays (inputs..., label) with lazy transforms.

    ``batches(batch_size)`` yields tuples of numpy arrays; the final
    element is the label (if present).
    """

    def __init__(self, arrays: Sequence[np.ndarray],
                 memory_type: MemoryType = "DRAM",
                 transforms: Optional[List[Callable]] = None,
                 seed: int = 0, cache_level: Optional[str] = None):
        if not arrays:
            raise ValueError("FeatureSet needs at least one array")
        n = len(arrays[0])
        for a in arrays:
            if len(a) != n:
                raise ValueError("arrays must be aligned on dim 0")
        self.memory_type = memory_type.upper()
        self.transforms = list(transforms or [])
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        # None = inherit ZooConfig.data_cache_level at fit time
        self.cache_level = (CacheLevel.normalize(cache_level)
                            if cache_level is not None else None)
        if self.memory_type in ("DISK_AND_DRAM", "DIRECT"):
            self.arrays = [self._to_mmap(np.asarray(a)) for a in arrays]
        else:
            self.arrays = [np.asarray(a) for a in arrays]

    # -- constructors (parity with FeatureSet.rdd / ImageSet / TextSet) ---
    @staticmethod
    def from_ndarrays(x, y=None, memory_type: MemoryType = "DRAM",
                      seed: int = 0,
                      cache_level: Optional[str] = None) -> "FeatureSet":
        xs = list(x) if isinstance(x, (list, tuple)) else [x]
        if y is not None:
            xs = xs + [y]
        return FeatureSet(xs, memory_type=memory_type, seed=seed,
                          cache_level=cache_level)

    @staticmethod
    def from_npy_files(paths: Sequence[str],
                       memory_type: MemoryType = "DISK_AND_DRAM"
                       ) -> "FeatureSet":
        mode = "r" if memory_type.upper() != "DRAM" else None
        arrays = [np.load(p, mmap_mode=mode) for p in paths]
        fs = FeatureSet.__new__(FeatureSet)
        fs.memory_type = memory_type.upper()
        fs.transforms = []
        fs.seed = 0
        fs._rng = np.random.RandomState(0)
        fs.cache_level = None
        fs.arrays = list(arrays)
        return fs

    @staticmethod
    def from_parquet(path: str, feature_cols: Sequence[str], label_col: str,
                     memory_type: MemoryType = "DRAM") -> "FeatureSet":
        """Columnar ingestion (replaces the reference's Spark DataFrame
        path, TextSet.readParquet feature/text/TextSet.scala:372)."""
        import pandas as pd  # available via baked-in deps

        df = pd.read_parquet(path)
        arrays = [np.stack(df[c].to_numpy()) for c in feature_cols]
        arrays.append(df[label_col].to_numpy())
        return FeatureSet(arrays, memory_type=memory_type)

    # -- transforms -------------------------------------------------------
    def transform(self, fn: Callable[..., Tuple[np.ndarray, ...]]
                  ) -> "FeatureSet":
        """Append a per-batch transform ``fn(*arrays) -> arrays`` (lazy)."""
        fs = FeatureSet.__new__(FeatureSet)
        fs.arrays = self.arrays
        fs.memory_type = self.memory_type
        fs.transforms = self.transforms + [fn]
        fs.seed = self.seed
        fs._rng = self._rng
        fs.cache_level = self.cache_level
        return fs

    # -- cache levels (HBM residency) -------------------------------------
    def cache(self, level: str = CacheLevel.DEVICE) -> "FeatureSet":
        """Pin this FeatureSet's cache level (``CacheLevel.HOST`` /
        ``DEVICE``), the analog of the reference's
        ``FeatureSet.rdd(memoryType=...)`` tier selection.  Returns a
        shallow copy sharing the backing arrays."""
        fs = FeatureSet.__new__(FeatureSet)
        fs.__dict__.update(self.__dict__)
        fs.cache_level = CacheLevel.normalize(level)
        return fs

    @property
    def nbytes(self) -> int:
        """Total bytes of the backing arrays (the HBM bill of a DEVICE
        cache, pre-transform)."""
        return int(sum(a.dtype.itemsize * a.size for a in self.arrays))

    def device_arrays(self, ctx=None) -> List["Any"]:
        """Materialize the dataset into HBM: one ``device_put`` per array,
        rows sharded over the mesh's data axis when they divide it
        (parallel/sharding.dataset_sharding), replicated otherwise.

        Transforms are applied ONCE here, over the full arrays — valid
        for row-independent (per-sample) transforms, which is what the
        lazy per-batch protocol already implies; transforms that couple
        rows across a batch would change meaning under a different batch
        size too.  The upload is timed under
        ``featureset/device_cache_put`` so the one-off transfer cost is
        visible next to the per-step timings it eliminates.

        Multi-controller: the upload goes through ``device_put_global``,
        whose per-device callback carves out ONLY the row spans this
        process's devices own under ``dataset_sharding`` — each host
        transfers its share of the dataset into its local HBM, and the
        assembled global jax.Array spans the mesh.
        """
        import jax

        from analytics_zoo_tpu.core.context import get_zoo_context
        from analytics_zoo_tpu.core.profiling import timeit
        from analytics_zoo_tpu.parallel.sharding import (
            dataset_sharding, device_put_global)

        ctx = ctx or get_zoo_context()
        arrays = self.arrays
        if self.transforms:
            batch = tuple(np.asarray(a) for a in arrays)
            for fn in self.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            arrays = list(batch)
        n = len(arrays[0])
        with timeit("featureset/device_cache_put"):
            out = [device_put_global(
                np.asarray(a), dataset_sharding(ctx.mesh, n, np.ndim(a),
                                                axis=ctx.data_axis))
                for a in arrays]
            jax.block_until_ready(out)
        return out

    # -- iteration --------------------------------------------------------
    def __len__(self) -> int:
        return len(self.arrays[0])

    @property
    def size(self) -> int:
        return len(self)

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = False, pad_to: int = 1,
                shuffle_buffer: Optional[int] = None
                ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield batches; ``pad_to`` rounds batch_size up to a multiple
        (device count) so every batch shards evenly over the mesh.

        ``shuffle_buffer`` (config ``shuffle_buffer`` knob) bounds the
        shuffle window: rows are permuted within contiguous blocks of that
        size and the block order is permuted — a locality-preserving
        shuffle so disk-backed tiers (DISK_AND_DRAM/DIRECT mmaps) read
        near-sequentially instead of seeking across the whole file
        (replaces the reference's cached index-shuffled partitions,
        feature/FeatureSet.scala:229).  ``None``/``>=n`` = full
        permutation.
        """
        n = len(self)
        bs = int(math.ceil(batch_size / pad_to)) * pad_to
        if not shuffle:
            order = np.arange(n)
        elif shuffle_buffer is not None and 0 < shuffle_buffer < n:
            buf = int(shuffle_buffer)
            starts = np.arange(0, n, buf)
            self._rng.shuffle(starts)
            order = np.concatenate([
                s + self._rng.permutation(min(buf, n - s)) for s in starts])
        else:
            order = self._rng.permutation(n)
        steps = n // bs if drop_remainder else int(math.ceil(n / bs))
        for s in range(steps):
            idx = order[s * bs:(s + 1) * bs]
            batch = tuple(self._gather(a, idx) for a in self.arrays)
            for fn in self.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            yield batch

    @staticmethod
    def _gather(a: np.ndarray, idx: np.ndarray) -> np.ndarray:
        """Batch assembly: parallel native row gather for big copies
        (native/zoo_native.cpp — the MTSampleToMiniBatch role), numpy
        fancy indexing otherwise."""
        row_bytes = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=int))
        if row_bytes * len(idx) >= (1 << 20) and a.flags["C_CONTIGUOUS"]:
            try:
                from analytics_zoo_tpu.native import (available,
                                                      gather_rows)

                if available():
                    return gather_rows(a, idx)
            except Exception:
                pass
        return np.asarray(a[idx])

    def read_rows(self, start: int, stop: int) -> List[np.ndarray]:
        """Row span [start, stop) of every backing array (views for DRAM
        arrays, lazy page-backed reads for mmap tiers) — the shard
        loader for the STREAM tier."""
        if not (0 <= start <= stop <= len(self)):
            raise ValueError(f"row span [{start}, {stop}) out of range "
                             f"for {len(self)} rows")
        return [a[start:stop] for a in self.arrays]

    # -- internals --------------------------------------------------------
    @staticmethod
    def _to_mmap(a: np.ndarray) -> np.ndarray:
        """Spill an array to a disk-backed mmap (DISK_AND_DRAM tier)."""
        fd, path = tempfile.mkstemp(suffix=".npy", prefix="zoo_featureset_")
        os.close(fd)
        np.save(path, a)
        return np.load(path, mmap_mode="r")

    # -- slice-wise disk epochs ------------------------------------------
    @staticmethod
    def from_npy_slices(slices: Sequence[Sequence[str]],
                        seed: int = 0) -> "SlicedFeatureSet":
        """Slice-wise disk training (reference DiskFeatureSet numSlice,
        feature/FeatureSet.scala:585): ``slices`` is a list of aligned
        .npy path tuples; one slice is resident in DRAM at a time and
        epochs stream slice-by-slice (slice order + rows-within-slice
        shuffled), bounding host memory to the largest slice."""
        return SlicedFeatureSet(slices, seed=seed)


class SlicedFeatureSet(FeatureSet):
    """A FeatureSet whose rows live in per-slice .npy files on disk;
    only one slice is materialised in DRAM at a time."""

    def __init__(self, slices: Sequence[Sequence[str]], seed: int = 0):
        if not slices:
            raise ValueError("need at least one slice")
        self.slice_paths = [tuple(s) for s in slices]
        width = len(self.slice_paths[0])
        if any(len(s) != width for s in self.slice_paths):
            raise ValueError("every slice must have the same array count")
        self.memory_type = "DISK_AND_DRAM"
        self.transforms = []
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        # slice-wise sets exist BECAUSE the data outgrows resident memory;
        # HBM caching is never applicable
        self.cache_level = CacheLevel.HOST
        # row counts and byte totals from headers only (no data load,
        # no mmap): classifying a beyond-memory dataset must not cost a
        # page-cache walk over it
        self._slice_rows = []
        self._disk_bytes = 0
        self._row_specs: Optional[List[Tuple[Tuple[int, ...],
                                             np.dtype]]] = None
        for s in self.slice_paths:
            counts = set()
            specs = []
            for p in s:
                shape, dtype = npy_header(p)
                counts.add(shape[0] if shape else 0)
                specs.append((shape[1:], dtype))
                self._disk_bytes += dtype.itemsize * int(
                    np.prod(shape, dtype=np.int64))
            if len(counts) != 1:
                raise ValueError(f"slice {s} arrays are not aligned")
            if self._row_specs is None:
                self._row_specs = specs
            elif specs != self._row_specs:
                raise ValueError(
                    f"slice {s} row shapes/dtypes differ from the first "
                    f"slice: {specs} vs {self._row_specs}")
            self._slice_rows.append(counts.pop())

    def transform(self, fn) -> "SlicedFeatureSet":
        fs = SlicedFeatureSet.__new__(SlicedFeatureSet)
        fs.__dict__.update(self.__dict__)
        fs.transforms = self.transforms + [fn]
        return fs

    @property
    def nbytes(self) -> int:
        """Summed on-disk bytes across slices, computed at __init__ from
        the .npy headers alone (``npy_header``) — no slice is loaded or
        mapped to answer the budget check."""
        return int(self._disk_bytes)

    def cache(self, level: str = CacheLevel.DEVICE) -> "SlicedFeatureSet":
        lv = CacheLevel.normalize(level)
        if lv == CacheLevel.DEVICE:
            raise ValueError(
                "SlicedFeatureSet streams slices because the dataset "
                "outgrows resident memory; a DEVICE (HBM) cache cannot "
                "hold it — use CacheLevel.STREAM to rotate budget-sized "
                "shards through HBM, or FeatureSet.from_ndarrays for "
                "data that fits the device budget")
        fs = SlicedFeatureSet.__new__(SlicedFeatureSet)
        fs.__dict__.update(self.__dict__)
        fs.cache_level = lv
        return fs

    def read_rows(self, start: int, stop: int) -> List[np.ndarray]:
        """Materialize global rows [start, stop) across slice files
        (mmap-backed reads, copied out) — the shard loader for the
        STREAM tier.  Bounded by the requested span, not the slice
        layout."""
        if not (0 <= start <= stop <= len(self)):
            raise ValueError(f"row span [{start}, {stop}) out of range "
                             f"for {len(self)} rows")
        width = len(self.slice_paths[0])
        parts: List[List[np.ndarray]] = [[] for _ in range(width)]
        offset = 0
        for si, rows in enumerate(self._slice_rows):
            lo, hi = max(start - offset, 0), min(stop - offset, rows)
            if lo < hi:
                for j, p in enumerate(self.slice_paths[si]):
                    a = np.load(p, mmap_mode="r")
                    parts[j].append(np.asarray(a[lo:hi]))
            offset += rows
            if offset >= stop:
                break
        return [np.concatenate(ps) if len(ps) > 1 else ps[0]
                for ps in parts]

    def __len__(self) -> int:
        return int(sum(self._slice_rows))

    def batches(self, batch_size: int, shuffle: bool = False,
                drop_remainder: bool = False, pad_to: int = 1,
                shuffle_buffer: Optional[int] = None
                ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Stream batches slice-by-slice.  Rows left over when a slice
        doesn't divide the batch are CARRIED into the next slice (total
        loss per epoch is < one batch, same as the base class), so small
        slices still contribute every row.  ``shuffle_buffer`` is
        accepted but moot here: the resident slice IS the shuffle window
        by construction."""
        bs = int(math.ceil(batch_size / pad_to)) * pad_to
        order = (self._rng.permutation(len(self.slice_paths)) if shuffle
                 else np.arange(len(self.slice_paths)))
        carry: Optional[List[np.ndarray]] = None

        def emit(batch):
            for fn in self.transforms:
                batch = fn(*batch)
                if not isinstance(batch, tuple):
                    batch = (batch,)
            return batch

        for si in order:
            arrays = [np.load(p) for p in self.slice_paths[si]]  # DRAM now
            if carry is not None:
                arrays = [np.concatenate([c, a])
                          for c, a in zip(carry, arrays)]
                carry = None
            n = len(arrays[0])
            rows = self._rng.permutation(n) if shuffle else np.arange(n)
            for s in range(n // bs):
                idx = rows[s * bs:(s + 1) * bs]
                yield emit(tuple(a[idx] for a in arrays))
            rem = rows[(n // bs) * bs:]
            if len(rem):
                carry = [a[rem] for a in arrays]
            del arrays          # release the slice before loading the next
        if carry is not None and not drop_remainder:
            yield emit(tuple(carry))
