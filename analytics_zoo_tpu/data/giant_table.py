"""Deterministic lazily-generated giant embedding tables.

The DLRM-scale bench leg (bench.py) and the sharded-table geometry
tests need 10⁸-row tables that can NEVER be materialized on the host —
a 10⁸×64 f32 table is ~25 GiB.  ``SyntheticGiantTable`` is the
table-shaped sibling of ``SlicedFeatureSet``: its size accounting
(``.nbytes``, ``len``) comes from header math alone, and actual values
exist only for the row range somebody asks for, computed on demand as
a pure function of ``(seed, row_id)`` — so every consumer (each model
shard of ``parallel.table_sharding.init_table_sharded``, a parity
check, a re-run on another host) sees the identical table without any
of them holding more than its own slice.

The generator is a vectorized splitmix64-style integer hash: uniform,
stateless, and cheap enough to fill a multi-GiB shard at memory
bandwidth — no RNG object, no sequential dependency between rows.
"""

from __future__ import annotations

import numpy as np

# splitmix64 constants (Steele et al.); the standard finalizer mixes
# each 64-bit counter value into an independent uniform word
_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + _GAMMA) * np.uint64(1)
    x ^= x >> np.uint64(30)
    x *= _MIX1
    x ^= x >> np.uint64(27)
    x *= _MIX2
    x ^= x >> np.uint64(31)
    return x


class SyntheticGiantTable:
    """A virtual ``(rows, dim)`` float table defined by ``(seed, row)``.

    ``rows(lo, hi)`` materializes just that row range (the contract
    ``init_table_sharded`` uses to fill each device's shard), ``row(i)``
    one row; values are uniform in ``[-scale, scale)`` and identical
    for the same ``(seed, row, column)`` regardless of which range they
    were generated through.
    """

    def __init__(self, rows: int, dim: int, seed: int = 0,
                 dtype=np.float32, scale: float = 0.05):
        if rows <= 0 or dim <= 0:
            raise ValueError(f"need positive rows/dim, got {rows}x{dim}")
        self.row_count = int(rows)
        self.dim = int(dim)
        self.seed = int(seed)
        self.dtype = np.dtype(dtype)
        self.scale = float(scale)

    # -- header-only accounting (the SlicedFeatureSet discipline) ------
    def __len__(self) -> int:
        return self.row_count

    @property
    def shape(self):
        return (self.row_count, self.dim)

    @property
    def nbytes(self) -> int:
        """Total virtual bytes — pure arithmetic, nothing generated."""
        return self.row_count * self.dim * self.dtype.itemsize

    # -- on-demand materialization -------------------------------------
    # cells per generation chunk: bounds the uint64/f64 intermediates to
    # ~100 MB however large the requested slice is (a 10⁸-row shard fill
    # must not transiently triple its own footprint)
    _CHUNK_CELLS = 4 << 20

    def rows(self, lo: int, hi: int) -> np.ndarray:
        """Rows ``[lo, hi)`` as a ``(hi-lo, dim)`` array."""
        if not 0 <= lo <= hi <= self.row_count:
            raise IndexError(
                f"row range [{lo}, {hi}) outside table of "
                f"{self.row_count} rows")
        n = hi - lo
        out = np.empty((n * self.dim,), self.dtype)
        # one 64-bit counter per cell: row * dim + col, offset by the
        # seed far enough that different seeds never share counters
        base = np.uint64(self.seed) * np.uint64(0x51ED2701)
        start, stop = lo * self.dim, hi * self.dim
        for c0 in range(start, stop, self._CHUNK_CELLS):
            c1 = min(c0 + self._CHUNK_CELLS, stop)
            idx = np.arange(c0, c1, dtype=np.uint64) + base
            with np.errstate(over="ignore"):  # uint64 wrap is the point
                bits = _splitmix64(idx)
            # top 24 bits -> uniform [0, 1) at f32 resolution, centered
            unit = (bits >> np.uint64(40)).astype(np.float64) / \
                float(1 << 24)
            out[c0 - start:c1 - start] = \
                ((unit * 2.0 - 1.0) * self.scale).astype(self.dtype)
        return out.reshape(n, self.dim)

    def row(self, i: int) -> np.ndarray:
        return self.rows(i, i + 1)[0]
