"""Dataset readers (reference dataset utilities:
models/image/objectdetection/dataset/{Coco,PascalVoc,Imdb}.scala,
examples' MovieLens / news20 loaders).

All readers parse LOCAL files (zero-egress environments); each has a
``generate_*`` companion producing a faithfully shaped synthetic stand-in
so examples/benchmarks run without the real download.
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["read_movielens_1m", "generate_movielens_like",
           "movielens_featureset",
           "read_pascal_voc", "read_coco", "read_text_folder",
           "generate_text_classification"]


# ---------------------------------------------------------------------------
# MovieLens (reference examples/recommendation — ml-1m ratings.dat)
# ---------------------------------------------------------------------------

def read_movielens_1m(path: str) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """Parse ml-1m ``ratings.dat`` (``user::item::rating::ts``) ->
    (user_ids, item_ids, ratings), 1-based ids."""
    f = os.path.join(path, "ratings.dat") if os.path.isdir(path) else path
    users, items, ratings = [], [], []
    with open(f) as fh:
        for line in fh:
            parts = line.strip().split("::")
            if len(parts) < 3:
                continue
            users.append(int(parts[0]))
            items.append(int(parts[1]))
            ratings.append(float(parts[2]))
    return (np.asarray(users, np.int64), np.asarray(items, np.int64),
            np.asarray(ratings, np.float32))


def generate_movielens_like(n_users: int = 6040, n_items: int = 3706,
                            ratings_per_user: int = 20, latent: int = 8,
                            seed: int = 0):
    """MovieLens-1M-shaped synthetic ratings with a low-rank preference
    structure (learnable; see bench.py's convergence evidence)."""
    rs = np.random.RandomState(seed)
    zu = rs.randn(n_users + 1, latent)
    zi = rs.randn(n_items + 1, latent)
    users, items, ratings = [], [], []
    for u in range(1, n_users + 1):
        picked = rs.randint(1, n_items + 1, ratings_per_user)
        score = (zu[u] * zi[picked]).sum(axis=1)
        r = np.clip(np.round(3 + score), 1, 5)
        users.extend([u] * ratings_per_user)
        items.extend(picked.tolist())
        ratings.extend(r.tolist())
    return (np.asarray(users, np.int64), np.asarray(items, np.int64),
            np.asarray(ratings, np.float32))


def movielens_featureset(path: Optional[str] = None,
                         cache_level: Optional[str] = None,
                         memory_type: str = "DRAM", **generate_kw):
    """Ratings as an Estimator-ready ``FeatureSet``:
    arrays ``(user[:, None], item[:, None], rating)`` — the NeuralCF
    explicit-feedback input layout.  Reads ml-1m from ``path`` when
    given, else generates the synthetic stand-in
    (``generate_movielens_like(**generate_kw)``).

    ``cache_level="DEVICE"`` pins the HBM-resident tier: the Estimator
    materializes the set into device memory once and shuffles/gathers
    minibatches inside the compiled step (see data/README.md)."""
    from analytics_zoo_tpu.data.featureset import FeatureSet

    users, items, ratings = (read_movielens_1m(path) if path
                             else generate_movielens_like(**generate_kw))
    return FeatureSet.from_ndarrays(
        [users[:, None].astype(np.int32), items[:, None].astype(np.int32)],
        ratings, memory_type=memory_type, cache_level=cache_level)


# ---------------------------------------------------------------------------
# Pascal VOC (reference PascalVoc.scala — XML annotation per image)
# ---------------------------------------------------------------------------

VOC_CLASSES = ("aeroplane", "bicycle", "bird", "boat", "bottle", "bus",
               "car", "cat", "chair", "cow", "diningtable", "dog", "horse",
               "motorbike", "person", "pottedplant", "sheep", "sofa",
               "train", "tvmonitor")


def read_pascal_voc(annotations_dir: str,
                    class_names: Sequence[str] = VOC_CLASSES,
                    keep_difficult: bool = False) -> List[Dict]:
    """Parse VOC XML annotations -> list of records
    {file, width, height, bboxes (N,4 pixels x1y1x2y2), labels (N,
    1-based), difficult (N,)}."""
    cls_idx = {c: i + 1 for i, c in enumerate(class_names)}
    out = []
    for fn in sorted(os.listdir(annotations_dir)):
        if not fn.endswith(".xml"):
            continue
        root = ET.parse(os.path.join(annotations_dir, fn)).getroot()
        size = root.find("size")
        boxes, labels, difficult = [], [], []
        for obj in root.findall("object"):
            name = obj.findtext("name")
            if name not in cls_idx:
                continue
            diff = int(obj.findtext("difficult") or 0)
            if diff and not keep_difficult:
                continue
            bb = obj.find("bndbox")
            boxes.append([float(bb.findtext("xmin")),
                          float(bb.findtext("ymin")),
                          float(bb.findtext("xmax")),
                          float(bb.findtext("ymax"))])
            labels.append(cls_idx[name])
            difficult.append(diff)
        out.append({
            "file": root.findtext("filename") or fn.replace(".xml", ".jpg"),
            "width": int(size.findtext("width")) if size is not None else 0,
            "height": int(size.findtext("height")) if size is not None
            else 0,
            "bboxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "labels": np.asarray(labels, np.int64),
            "difficult": np.asarray(difficult, np.int64),
        })
    return out


# ---------------------------------------------------------------------------
# COCO (reference Coco.scala — instances json)
# ---------------------------------------------------------------------------

def read_coco(annotation_file: str) -> List[Dict]:
    """Parse a COCO instances JSON -> per-image records
    {file, width, height, bboxes (N,4 pixels x1y1x2y2), labels (N,)}."""
    with open(annotation_file) as f:
        blob = json.load(f)
    images = {im["id"]: im for im in blob.get("images", [])}
    recs = {im_id: {"file": im.get("file_name", ""),
                    "width": im.get("width", 0),
                    "height": im.get("height", 0),
                    "bboxes": [], "labels": []}
            for im_id, im in images.items()}
    for ann in blob.get("annotations", []):
        rec = recs.get(ann["image_id"])
        if rec is None:
            continue
        x, y, w, h = ann["bbox"]                   # coco xywh
        rec["bboxes"].append([x, y, x + w, y + h])
        rec["labels"].append(ann["category_id"])
    out = []
    for rec in recs.values():
        rec["bboxes"] = np.asarray(rec["bboxes"], np.float32).reshape(-1, 4)
        rec["labels"] = np.asarray(rec["labels"], np.int64)
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# text classification corpora (reference news20/IMDB folder layout:
# one subdirectory per class, one document per file)
# ---------------------------------------------------------------------------

def read_text_folder(path: str, encoding: str = "utf-8"
                     ) -> Tuple[List[str], np.ndarray, Dict[str, int]]:
    """Folder-per-class corpus -> (texts, labels (0-based), class_map)."""
    classes = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
    class_map = {c: i for i, c in enumerate(classes)}
    texts, labels = [], []
    for c in classes:
        cdir = os.path.join(path, c)
        for fn in sorted(os.listdir(cdir)):
            fp = os.path.join(cdir, fn)
            if not os.path.isfile(fp):
                continue
            with open(fp, encoding=encoding, errors="replace") as f:
                texts.append(f.read())
            labels.append(class_map[c])
    return texts, np.asarray(labels, np.int64), class_map


def generate_text_classification(n_classes: int = 4, per_class: int = 50,
                                 seed: int = 0
                                 ) -> Tuple[List[str], np.ndarray]:
    """Synthetic folder-corpus stand-in: each class has a distinctive
    keyword vocabulary, so classifiers can actually learn."""
    rs = np.random.RandomState(seed)
    common = ["the", "a", "of", "and", "to", "in", "it", "is"]
    themes = [[f"w{c}_{k}" for k in range(12)] for c in range(n_classes)]
    texts, labels = [], []
    for c in range(n_classes):
        for _ in range(per_class):
            n = rs.randint(12, 30)
            words = [
                themes[c][rs.randint(len(themes[c]))]
                if rs.rand() < 0.55 else common[rs.randint(len(common))]
                for _ in range(n)]
            texts.append(" ".join(words))
            labels.append(c)
    return texts, np.asarray(labels, np.int64)
