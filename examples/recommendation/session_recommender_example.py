"""Session-based recommendation — the session-recommender flow
(reference models/recommendation/SessionRecommender.scala + the
recommendation notebook apps: GRU over the in-session click sequence
[+ purchase-history MLP] -> next-item softmax,
``recommend_for_session``).

The synthetic sessions follow Markov-chain item dynamics (each item has
a preferred successor), so next-item accuracy measures real sequence
learning; history mode appends a user's past purchases through the
two-tower variant.

TPU-first notes: the GRU lowers to a `lax.scan` whose per-step matmuls
batch onto the MXU; the whole session tower + history tower + softmax
head is one fused program.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models import SessionRecommender
from analytics_zoo_tpu.train.optimizers import Adam


def markov_sessions(n, n_items, length, seed=0):
    """Item i prefers successor (i*7+3) % n_items with prob 0.8."""
    rs = np.random.RandomState(seed)
    nxt = (np.arange(n_items + 1) * 7 + 3) % n_items + 1
    sessions = np.zeros((n, length), np.int32)
    targets = np.zeros(n, np.int32)
    for s in range(n):
        cur = rs.randint(1, n_items + 1)
        for t in range(length):
            sessions[s, t] = cur
            cur = nxt[cur] if rs.rand() < 0.8 \
                else rs.randint(1, n_items + 1)
        targets[s] = cur
    return sessions, targets


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--items", type=int, default=200)
    ap.add_argument("--sessions", type=int, default=6000)
    ap.add_argument("--session-length", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--history", action="store_true",
                    help="two-tower variant with purchase history")
    args = ap.parse_args()

    init_zoo_context()
    x, y = markov_sessions(args.sessions, args.items, args.session_length)
    rec = SessionRecommender(item_count=args.items, item_embed=32,
                             rnn_hidden_layers=(40, 20),
                             session_length=args.session_length,
                             include_history=args.history,
                             history_length=4)
    rec.compile(optimizer=Adam(lr=3e-3),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy", "top5_accuracy"])
    split = int(0.9 * len(y))
    inputs = [x]
    if args.history:
        rs = np.random.RandomState(1)
        hist = rs.randint(1, args.items + 1,
                          (len(y), 4)).astype(np.int32)
        inputs = [x, hist]
    rec.fit([a[:split] for a in inputs], y[:split], batch_size=128,
            nb_epoch=args.epochs)
    ev = rec.evaluate([a[split:] for a in inputs], y[split:],
                      batch_size=256)
    print("next-item validation:",
          {k: round(float(v), 4) for k, v in ev.items()})
    recs = rec.recommend_for_session(x[split:split + 3])
    for sess, row in zip(x[split:split + 3], recs):
        print(f"  session {sess[-3:]}... -> top-3 {row[:3]}")
    # markov top-transition is learnable far above the 1/items floor
    assert ev["top5_accuracy"] > 0.3   # defaults reach ~0.83; floor is 0.025


if __name__ == "__main__":
    main()
