"""NeuralCF on MovieLens(-shaped) data with negative sampling
(reference examples/recommendation/NeuralCFexample.scala:44-120).

    python ncf_example.py                       # synthetic ml-1m shape
    python ncf_example.py --data ml-1m/         # real ratings.dat
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.datasets import (generate_movielens_like,
                                             read_movielens_1m)
from analytics_zoo_tpu.models import NeuralCF
from analytics_zoo_tpu.models.recommendation import negative_sample


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="ml-1m dir or ratings.dat (default: synthetic)")
    ap.add_argument("--batch-size", type=int, default=2048)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--users", type=int, default=2000)
    ap.add_argument("--items", type=int, default=1500)
    args = ap.parse_args()

    init_zoo_context(steps_per_execution=8)
    if args.data:
        users, items, ratings = read_movielens_1m(args.data)
        n_users, n_items = int(users.max()), int(items.max())
    else:
        users, items, ratings = generate_movielens_like(
            n_users=args.users, n_items=args.items)
        n_users, n_items = args.users, args.items

    # implicit feedback: 4 sampled negatives per positive
    tr_u, tr_i, tr_y = negative_sample(users, items, n_items,
                                       neg_per_pos=4)
    ncf = NeuralCF(user_count=n_users, item_count=n_items, class_num=2,
                   user_embed=20, item_embed=20,
                   hidden_layers=(40, 20, 10), mf_embed=20)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    ncf.fit([tr_u[:, None].astype(np.int32),
             tr_i[:, None].astype(np.int32)],
            tr_y.astype(np.int32), batch_size=args.batch_size,
            nb_epoch=args.epochs)
    res = ncf.evaluate([tr_u[:, None].astype(np.int32),
                        tr_i[:, None].astype(np.int32)],
                       tr_y.astype(np.int32), batch_size=args.batch_size)
    print("train-set eval:", res)

    recs = ncf.recommend_for_user(1, np.arange(1, n_items + 1),
                                  max_items=5)
    print("top-5 recommendations for user 1:", recs)


if __name__ == "__main__":
    main()
