"""Wide & Deep recommendation (reference WideAndDeepExample.scala):
wide cross features + deep embeddings + continuous columns."""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models import WideAndDeep


def synthetic_census(n=2048, seed=0):
    rs = np.random.RandomState(seed)
    wide_base = rs.randint(0, 100, (n, 2))       # e.g. occupation, edu
    wide_cross = rs.randint(0, 1000, (n, 1))     # crossed buckets
    # wide ids index ONE shared linear table: offset each column by the
    # cumulative dims before it (100, 100, 1000)
    wide = np.concatenate(
        [wide_base[:, :1], wide_base[:, 1:] + 100, wide_cross + 200],
        axis=1)
    indicator = np.zeros((n, 10), np.float32)    # multi-hot width 10
    indicator[np.arange(n), rs.randint(0, 10, n)] = 1.0
    embed = rs.randint(0, 100, (n, 2))
    continuous = rs.randn(n, 3).astype(np.float32)
    logits = (wide_base[:, 0] % 3) + continuous[:, 0] * 2
    label = (logits > 1).astype(np.int32)
    return [wide.astype(np.int32), indicator, embed.astype(np.int32),
            continuous], label


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--model-type", default="wide_n_deep",
                    choices=["wide", "deep", "wide_n_deep"])
    args = ap.parse_args()

    init_zoo_context()
    xs, y = synthetic_census()
    wnd = WideAndDeep(class_num=2, model_type=args.model_type,
                      wide_base_dims=(100, 100), wide_cross_dims=(1000,),
                      indicator_dims=(10,), embed_in_dims=(100, 100),
                      embed_out_dims=(8, 8), continuous_cols=3)
    wnd.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    wnd.fit(xs, y, batch_size=128, nb_epoch=args.epochs)
    print("eval:", wnd.evaluate(xs, y, batch_size=256))


if __name__ == "__main__":
    main()
