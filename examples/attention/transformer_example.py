"""Transformer sentiment classification — the attention example
(reference pyzoo/zoo/examples/attention/transformer.py: TransformerLayer
over IMDB token ids -> first output -> GlobalAveragePooling1D ->
Dropout -> Dense(2 softmax)).

The reference downloads IMDB through keras; this environment has no
egress, so an IMDB-shaped synthetic corpus (class-conditional token
distributions over a 20k vocabulary) stands in by default — pass
``--data`` with a folder-per-class corpus to run on real text.

TPU-first notes: the whole classifier (embedding + attention stack +
pool + head) is ONE jitted SPMD program; `--stacked` stores the blocks
as a single scanned pytree (faster compiles, and the layout the
pipeline-parallel regime shards).
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.datasets import (generate_text_classification,
                                             read_text_folder)
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers import (Dense, Dropout,
                                         GlobalAveragePooling1D,
                                         TransformerLayer)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="folder-per-class corpus (default: synthetic)")
    ap.add_argument("--max-features", type=int, default=20000)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--blocks", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--stacked", action="store_true",
                    help="scan-stacked blocks (pp-shardable layout)")
    args = ap.parse_args()

    init_zoo_context()
    if args.data:
        texts, labels, _ = read_text_folder(args.data)
    else:
        texts, labels = generate_text_classification(n_classes=2,
                                                     per_class=120)
    ts = (TextSet.from_texts(texts, labels)
          .tokenize().normalize()
          .word2idx(max_words_num=args.max_features)
          .shape_sequence(args.max_len))
    x, y = ts.to_arrays()
    y = y.astype(np.int32)
    # the generator emits texts grouped by class — shuffle before the
    # split or the validation slice is single-class
    perm = np.random.RandomState(0).permutation(len(y))
    x, y = x[perm], y[perm]

    tokens = Input(shape=(args.max_len,), dtype="int32")
    seq = TransformerLayer(vocab=args.max_features, seq_len=args.max_len,
                           n_block=args.blocks, nhead=args.heads,
                           hidden_size=args.hidden, causal=False,
                           stacked=args.stacked)(tokens)
    pooled = GlobalAveragePooling1D()(seq)
    pooled = Dropout(0.2)(pooled)
    out = Dense(2, activation="softmax")(pooled)
    model = Model(tokens, out)
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    split = int(0.9 * len(y))
    model.fit(x[:split], y[:split], batch_size=args.batch_size,
              nb_epoch=args.epochs,
              validation_data=(x[split:], y[split:]))
    print("eval:", model.evaluate(x[split:], y[split:],
                                  batch_size=args.batch_size))


if __name__ == "__main__":
    main()
