"""Streaming object detection: an SSD detector behind the Cluster
Serving worker — images flow through a queue, detections flow back
(reference zoo/.../examples/streaming/objectdetection/
StreamingObjectDetection.scala: a Spark streaming query feeding
InferenceModel; here the stream is the serving queue and the "query"
is the worker loop on one chip).

One process (memory queue):
    python streaming_od_example.py

Cross-process (file queue; start the worker first):
    python streaming_od_example.py --queue-dir /tmp/odq --role worker
    python streaming_od_example.py --queue-dir /tmp/odq --role client
"""

import argparse
import json
import time

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.deploy.inference import InferenceModel
from analytics_zoo_tpu.deploy.serving import (ClusterServing, FileQueue,
                                              InputQueue, MemoryQueue,
                                              OutputQueue, ServingConfig)
from analytics_zoo_tpu.models.objectdetection import ObjectDetector

SMALL_CONFIG = {
    "image_size": 64,
    "feature_sizes": (8, 4, 2, 1, 1, 1),
    "min_sizes": (6, 13, 26, 38, 51, 58),
    "max_sizes": (13, 26, 38, 51, 58, 70),
    "aspect_ratios": ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,)),
}


def synthetic_frames(n=16, size=64, seed=0):
    rs = np.random.RandomState(seed)
    imgs = rs.rand(n, size, size, 3).astype(np.float32) * 0.2
    for i in range(n):
        w, h = rs.randint(16, 40, 2)
        x, y = rs.randint(0, size - w), rs.randint(0, size - h)
        imgs[i, y:y + h, x:x + w] = 1.0
    return imgs


def trained_detector(epochs=3, width_mult=1.0):
    rs = np.random.RandomState(0)
    imgs = synthetic_frames(32)
    boxes = np.zeros((32, 1, 4), np.float32)
    labels = np.ones((32, 1), np.int64)
    for i in range(32):
        ys, xs = np.where(imgs[i, :, :, 0] > 0.9)
        if len(xs):
            boxes[i, 0] = (xs.min() / 64, ys.min() / 64,
                           (xs.max() + 1) / 64, (ys.max() + 1) / 64)
    det = ObjectDetector(class_num=2, config=SMALL_CONFIG,
                         width_mult=width_mult)
    det.compile(optimizer="adam", loss=det.loss())
    det.fit_detection(imgs, boxes, labels, batch_size=8, nb_epoch=epochs,
                      verbose=False)
    return det


def detection_forward(det):
    """Serving forward: padded image batch → JSON-safe detections
    (boxes/scores/labels per frame) via the detector's NMS path."""
    def forward(xs):
        out = []
        for b, s, l in det.detect(np.asarray(xs[0]), score_threshold=0.2):
            out.append({"boxes": np.asarray(b).tolist(),
                        "scores": np.asarray(s).tolist(),
                        "labels": np.asarray(l).tolist()})
        return np.asarray([json.dumps(o) for o in out], dtype=object)
    return forward


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", choices=["both", "worker", "client"],
                    default="both")
    ap.add_argument("--queue-dir", default=None,
                    help="FileQueue dir for cross-process streaming")
    ap.add_argument("--frames", type=int, default=8)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--width-mult", type=float, default=1.0,
                    help="SSD trunk width (0.125 for quick CPU smoke)")
    args = ap.parse_args()

    init_zoo_context()
    queue = (FileQueue(args.queue_dir) if args.queue_dir
             else MemoryQueue())

    worker = None
    if args.role in ("both", "worker"):
        det = trained_detector(args.epochs, args.width_mult)
        infer = InferenceModel(detection_forward(det),
                               batch_buckets=(1, 4, 8))
        worker = ClusterServing(infer, queue,
                                ServingConfig(batch_size=8,
                                              poll_timeout_s=0.05))
        worker.start()
        print("worker: detector online, polling the stream")
        if args.role == "worker":
            try:
                while True:
                    time.sleep(1)
            except KeyboardInterrupt:
                worker.stop()
                return

    inq, outq = InputQueue(queue), OutputQueue(queue)
    frames = synthetic_frames(args.frames, seed=7)
    t0 = time.time()
    for i, frame in enumerate(frames):
        inq.enqueue_image(f"frame{i:04d}", image=frame)
    for i in range(args.frames):
        det_json = outq.query(f"frame{i:04d}", timeout=30.0)
        dets = json.loads(det_json) if isinstance(det_json, str) else det_json
        print(f"frame{i:04d}: {len(dets['scores'])} detections "
              f"{['%.2f' % s for s in dets['scores'][:3]]}")
    dt = time.time() - t0
    print(f"streamed {args.frames} frames in {dt:.2f}s "
          f"({args.frames / dt:.1f} fps end-to-end)")
    if worker is not None:
        worker.stop()


if __name__ == "__main__":
    main()
