"""SSD object detection: train on synthetic boxes, run detection
(reference examples/objectdetection/Predict.scala + fine-tune flow).
Use --voc-annotations to read a real Pascal VOC annotation dir."""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.datasets import read_pascal_voc
from analytics_zoo_tpu.models.objectdetection import ObjectDetector

SMALL_CONFIG = {
    "image_size": 64,
    "feature_sizes": (8, 4, 2, 1, 1, 1),
    "min_sizes": (6, 13, 26, 38, 51, 58),
    "max_sizes": (13, 26, 38, 51, 58, 70),
    "aspect_ratios": ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,)),
}


def synthetic_detection_data(n=32, size=64, seed=0):
    """Bright rectangles on noise; boxes normalized x1y1x2y2."""
    rs = np.random.RandomState(seed)
    imgs = rs.rand(n, size, size, 3).astype(np.float32) * 0.2
    boxes = np.zeros((n, 1, 4), np.float32)
    labels = np.ones((n, 1), np.int64)
    for i in range(n):
        w, h = rs.randint(16, 40, 2)
        x, y = rs.randint(0, size - w), rs.randint(0, size - h)
        imgs[i, y:y + h, x:x + w] = 1.0
        boxes[i, 0] = (x / size, y / size, (x + w) / size, (y + h) / size)
    return imgs, boxes, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--voc-annotations", default=None,
                    help="Pascal VOC Annotations/ dir (stats only)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--n", type=int, default=32)
    args = ap.parse_args()

    init_zoo_context()
    if args.voc_annotations:
        recs = read_pascal_voc(args.voc_annotations)
        print(f"VOC: {len(recs)} annotated images, "
              f"{sum(len(r['labels']) for r in recs)} boxes")

    imgs, boxes, labels = synthetic_detection_data(args.n)
    det = ObjectDetector(class_num=2, config=SMALL_CONFIG)
    det.compile(optimizer="adam", loss=det.loss())
    det.fit_detection(imgs, boxes, labels, batch_size=8,
                      nb_epoch=args.epochs, verbose=False)
    results = det.detect(imgs[:4], score_threshold=0.2)
    for i, (b, s, l) in enumerate(results):
        if len(s) == 0:
            print(f"image {i}: no detections above threshold")
            continue
        print(f"image {i}: {len(s)} detections, "
              f"best score {float(s.max()):.3f}")


if __name__ == "__main__":
    main()
