"""Model-inference tour (reference apps/model-inference-examples/): ONE
serving surface — ``InferenceModel`` — fronting every model source the
framework ingests: a natively-trained net, an ONNX file, the int8
quantized variant, a torch module, and the uint8 wire format with
on-device preprocessing.  Each backend serves the same request batch;
the script reports per-backend agreement and latency.
"""

import argparse
import tempfile

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.deploy import InferenceModel, imagenet_preprocess
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers.core import Dense


def train_native(rs, d_in=12, classes=3):
    x = rs.randn(2048, d_in).astype(np.float32)
    w = rs.randn(d_in, classes)
    y = np.argmax(x @ w, axis=1).astype(np.int32)
    net = Sequential()
    net.add(Dense(32, activation="relu", input_shape=(d_in,)))
    net.add(Dense(classes, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    net.fit(x, y, batch_size=128, epochs=12, verbose=False)
    return net, x[:64], y[:64]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    init_zoo_context()
    rs = np.random.RandomState(0)
    net, x, y = train_native(rs)
    params = net.estimator.params
    state = net.estimator.state

    backends = {}

    # 1) native weights, float32
    backends["native_f32"] = InferenceModel.from_keras_net(
        net, params, state, batch_buckets=(args.batch,))

    # 2) weight-only int8 (MXU int8 path)
    backends["native_int8"] = InferenceModel.from_keras_net(
        net, params, state, int8=True, batch_buckets=(args.batch,))

    # 3) ONNX round trip: export via the TF bridge is heavyweight for a
    #    demo; serve an arbitrary jax function instead (from_function is
    #    the escape hatch the reference covered with OpenVINO configs)
    def fn(a):
        out, _ = net.call(params, state, a, training=False)
        return out
    backends["function"] = InferenceModel.from_function(
        fn, batch_buckets=(args.batch,))

    # 4) torch module through the in-process torch path
    try:
        import torch

        tnet = torch.nn.Sequential(
            torch.nn.Linear(12, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 3), torch.nn.Softmax(dim=-1))
        backends["torch"] = InferenceModel.load_torch(tnet)
    except ImportError:
        pass

    # 5) uint8 wire + on-device normalize (serving transfer format)
    backends["uint8_wire"] = InferenceModel.from_keras_net(
        net, params, state,
        preprocess=imagenet_preprocess(scale=1.0, offset=0.0),
        batch_buckets=(args.batch,))

    import time

    ref = np.asarray(backends["native_f32"].predict(x[:args.batch]))
    acc = float((np.argmax(ref, -1) == y[:args.batch]).mean())
    print(f"native accuracy on probe batch: {acc:.2f}")
    for name, m in backends.items():
        probe = (np.clip(x[:args.batch], 0, 255).astype(np.uint8)
                 if name == "uint8_wire" else x[:args.batch])
        t0 = time.perf_counter()
        out = np.asarray(m.predict(probe))
        ms = (time.perf_counter() - t0) * 1e3
        if name in ("native_f32", "native_int8", "function"):
            agree = float((np.argmax(out, -1) == np.argmax(ref, -1)).mean())
            print(f"{name:12s} {ms:7.1f} ms  top-1 agreement {agree:.2f}")
        else:
            print(f"{name:12s} {ms:7.1f} ms  output {out.shape}")
    print(f"served {len(backends)} backends through one InferenceModel "
          "surface")


if __name__ == "__main__":
    main()
