"""Serving with InferenceModel: native load, dynamic batching, int8
(reference inference examples + vnni int8 examples)."""

import argparse
import tempfile

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.deploy.inference import DynamicBatcher, InferenceModel
from analytics_zoo_tpu.models import NeuralCF


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--int8", action="store_true")
    args = ap.parse_args()

    init_zoo_context()
    ncf = NeuralCF(user_count=100, item_count=80, class_num=5)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    u = np.random.randint(1, 100, (256, 1)).astype(np.int32)
    it = np.random.randint(1, 80, (256, 1)).astype(np.int32)
    y = np.random.randint(0, 5, 256).astype(np.int32)
    ncf.fit([u, it], y, batch_size=64, nb_epoch=1, verbose=False)
    path = tempfile.mkdtemp() + "/model.zoo"
    ncf.save_model(path)

    m = InferenceModel.load(path, int8=args.int8)
    preds = m.predict([u[:10], it[:10]])
    print(f"int8={args.int8} predictions:", np.argmax(preds, -1))

    batcher = DynamicBatcher(m, max_batch=64, max_latency_ms=5)
    outs = [batcher.predict([u[i:i + 1], it[i:i + 1]]) for i in range(8)]
    batcher.close()
    print("dynamic-batched single-row requests:",
          [int(np.argmax(o)) for o in outs])


if __name__ == "__main__":
    main()
