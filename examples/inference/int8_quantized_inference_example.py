"""INT8 quantized inference — the vnni/openvino example
(reference pyzoo/zoo/examples/vnni/openvino + apps model-inference:
load a model, calibrate to int8, compare latency and outputs; the
reference's DNNL/VNNI int8 claimed ~2x over f32, wp-bigdl.md:192).

Here quantization is native: per-channel symmetric int8 weights live in
HBM and the dequant fuses into the consuming matmul on the MXU's int8
path (`ops.quantization` / `quantize_pytree`).  The script quantizes a
trained classifier, reports agreement + weight-bytes saved, and on TPU
the int8 matmul path measures ~2.3x f32 (bench.py `matmul_4096`).
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.deploy import InferenceModel
from analytics_zoo_tpu.models.text import TextClassifier
from analytics_zoo_tpu.data.datasets import generate_text_classification
from analytics_zoo_tpu.data.text import TextSet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--requests", type=int, default=64)
    args = ap.parse_args()

    import jax

    init_zoo_context()
    texts, labels = generate_text_classification(n_classes=3, per_class=80)
    ts = (TextSet.from_texts(texts, labels).tokenize().normalize()
          .word2idx(max_words_num=4000).shape_sequence(32))
    x, y = ts.to_arrays()
    clf = TextClassifier(class_num=3, token_length=32,
                         sequence_length=32, encoder="cnn",
                         encoder_output_dim=64, max_words_num=4000)
    clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(x, y.astype(np.int32), batch_size=64, nb_epoch=args.epochs)

    params = jax.device_get(clf.estimator.params)
    state = jax.device_get(clf.estimator.state)
    m_f32 = InferenceModel.from_keras_net(clf.model, params, state,
                                          batch_buckets=(64,))
    m_int8 = InferenceModel.from_keras_net(clf.model, params, state,
                                           int8=True, batch_buckets=(64,))

    q = x[: args.requests]
    p32 = np.asarray(m_f32.predict([q]))
    p8 = np.asarray(m_int8.predict([q]))
    agree = float((p32.argmax(-1) == p8.argmax(-1)).mean())
    drift = float(np.abs(p32 - p8).max())
    f32_bytes = sum(np.asarray(v).nbytes
                    for p in params.values() for v in p.values())
    from analytics_zoo_tpu.deploy.inference import quantize_pytree
    qt = quantize_pytree(params)
    q_bytes = sum(np.asarray(leaf).nbytes
                  for leaf in jax.tree_util.tree_leaves(qt))
    print(f"top-1 agreement int8 vs f32: {agree:.4f} "
          f"(max prob drift {drift:.4f})")
    print(f"weight bytes: f32 {f32_bytes:,} -> int8 {q_bytes:,} "
          f"({f32_bytes / q_bytes:.2f}x smaller)")
    print("on-TPU int8 matmul path: ~2.3x f32 (bench.py matmul_4096)")
    assert agree > 0.95


if __name__ == "__main__":
    main()
