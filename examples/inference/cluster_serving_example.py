"""Cluster serving: a worker loop + client queues
(reference serving/ClusterServing.scala + pyzoo/zoo/serving/client.py —
Redis-stream serving with backpressure; here the queue backend is
pluggable: memory / file / redis).

Run the whole flow in one process:
    python cluster_serving_example.py

Or split worker and client across processes with a shared file queue:
    python cluster_serving_example.py --queue-dir /tmp/zooq --role worker
    python cluster_serving_example.py --queue-dir /tmp/zooq --role client
"""

import argparse
import time

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.deploy.inference import InferenceModel
from analytics_zoo_tpu.deploy.serving import (ClusterServing, FileQueue,
                                              InputQueue, MemoryQueue,
                                              OutputQueue, ServingConfig)
from analytics_zoo_tpu.nn.layers.core import Dense
from analytics_zoo_tpu.nn.topology import Sequential


def build_model():
    net = Sequential()
    net.add(Dense(16, activation="relu", input_shape=(8,)))
    net.add(Dense(3, activation="softmax"))
    net.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rs = np.random.RandomState(0)
    x = rs.randn(256, 8).astype(np.float32)
    y = rs.randint(0, 3, 256).astype(np.int32)
    net.fit(x, y, batch_size=64, nb_epoch=2, verbose=False)
    est = net.estimator
    return InferenceModel.from_keras_net(net, est.params, est.state)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--queue-dir", default=None,
                    help="file-queue dir (enables multi-process mode)")
    ap.add_argument("--role", default="both",
                    choices=["both", "worker", "client"])
    ap.add_argument("--requests", type=int, default=16)
    args = ap.parse_args()

    init_zoo_context()
    queue = (FileQueue(args.queue_dir) if args.queue_dir
             else MemoryQueue())

    serving = None
    if args.role in ("both", "worker"):
        serving = ClusterServing(build_model(), queue,
                                 ServingConfig(batch_size=8)).start()
        print("serving worker started")

    if args.role in ("both", "client"):
        inp, outp = InputQueue(queue), OutputQueue(queue)
        rs = np.random.RandomState(1)
        for i in range(args.requests):
            inp.enqueue(uri=f"req{i}",
                        x=rs.randn(8).astype(np.float32))
        results = {}
        deadline = time.time() + 30
        while len(results) < args.requests and time.time() < deadline:
            results.update(outp.dequeue(timeout=5.0))
        print(f"received {len(results)}/{args.requests} predictions")
        if "req0" in results:
            print("req0 class scores:",
                  np.round(np.asarray(results["req0"]), 3))
        elif not results:
            raise SystemExit("no predictions arrived — is a worker "
                             "running on this queue?")

    if args.role == "worker":
        print("worker running; ctrl-c to stop")
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    if serving is not None:
        # per-stage latency attribution from the pipeline telemetry
        # (docs/SERVING.md): queue_wait / decode / batch_wait / device /
        # respond / e2e, p50 and p99 each
        h = serving.health()
        for stage, s in sorted(h["stages"].items()):
            print(f"  {stage:<12} p50 {s['p50_ms']:7.2f}ms   "
                  f"p99 {s['p99_ms']:7.2f}ms   (n={s['count']})")
        serving.stop()


if __name__ == "__main__":
    main()
