"""Long-context attention with sequence parallelism (ring attention).

The reference's TransformerLayer/BERT materialize the full O(L²)
attention matrix on one host, bounding sequence length by single-node
memory (SURVEY.md §5.7).  Here the sequence axis is sharded over the
mesh: each device holds L/n of Q/K/V, K/V shards rotate around the ring
via ICI neighbour exchanges, and no device ever materializes more than
an (L/n x L/n) tile — context length scales linearly with devices.

    python ring_attention_example.py                # L=4096 over 8 CPU devs
    python ring_attention_example.py --length 8192
    python ring_attention_example.py --real         # real multi-chip slice
"""

import argparse
import os


def _ensure_devices(n: int) -> None:
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--length", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--real", action="store_true")
    args = ap.parse_args()
    if not args.real:
        _ensure_devices(args.devices)

    import jax
    if not args.real:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from analytics_zoo_tpu.ops.attention import reference_attention
    from analytics_zoo_tpu.parallel import ring_self_attention

    n = args.devices
    if len(jax.devices()) < n:
        raise SystemExit(f"need {n} devices, have {len(jax.devices())}")
    L = args.length - args.length % n        # shard evenly
    rs = np.random.RandomState(0)
    shape = (1, args.heads, L, args.dim)
    q = jnp.asarray(rs.randn(*shape).astype(np.float32))
    k = jnp.asarray(rs.randn(*shape).astype(np.float32))
    v = jnp.asarray(rs.randn(*shape).astype(np.float32))

    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("sp",))
    out = ring_self_attention(q, k, v, mesh, "sp", causal=True)
    print(f"ring attention: L={L} over {n} devices "
          f"(per-device sequence {L // n}), out {out.shape}")

    # cross-check against full attention (only feasible at modest L)
    if L <= 4096:
        ref = reference_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(out - ref)))
        print(f"max |ring - full| = {err:.2e}")
        assert err < 2e-4
    # gradients flow through the ring (ppermute has a transpose rule)
    g = jax.grad(lambda qq: jnp.sum(
        ring_self_attention(qq, k, v, mesh, "sp", causal=True) ** 2))(q)
    print(f"grad through ring ok: |dq| = {float(jnp.abs(g).mean()):.4f}")
    print("done: long-context attention sharded over the sequence axis")


if __name__ == "__main__":
    main()
