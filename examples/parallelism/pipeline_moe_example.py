"""Pipeline + expert parallelism on a virtual device mesh.

Both regimes are TPU-native capabilities beyond the reference (SURVEY.md
§2.4 lists PP and EP as explicit gaps in Analytics Zoo).  Run anywhere:

    python pipeline_moe_example.py                 # 8 virtual CPU devices
    python pipeline_moe_example.py --devices 4
    python pipeline_moe_example.py --real          # real multi-chip slice

With ``--real`` no virtual topology is forced and the same code shards
over ICI.
"""

import argparse
import os


def _ensure_devices(n: int) -> None:
    """Fake an n-device CPU topology before the jax *backend* initialises
    (same trick as tests/conftest.py).  Site hooks may have imported the
    jax module already — that is fine, the flags are read lazily at first
    backend use."""
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--real", action="store_true",
                    help="use the real device topology (no CPU fakes)")
    args = ap.parse_args()
    if not args.real:
        _ensure_devices(args.devices)

    import jax
    if not args.real:
        # some PJRT plugins re-force their platform via jax config; the
        # env var alone is not enough to pin CPU
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from analytics_zoo_tpu import init_zoo_context
    from analytics_zoo_tpu.core.context import get_zoo_context
    from analytics_zoo_tpu.nn.layers import SparseMoE
    from analytics_zoo_tpu.parallel import (ExpertParallel, PipelineParallel,
                                            stack_stage_params)

    if len(jax.devices()) < args.devices:
        raise SystemExit(f"need {args.devices} devices, have "
                         f"{len(jax.devices())}; run with JAX_PLATFORMS=cpu")

    # ---- pipeline parallelism: an MLP stack, one stage per device ------
    S, D, B = args.devices, 64, 16 * args.devices
    rs = np.random.RandomState(0)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    stages = [{"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.2),
               "b": jnp.zeros((D,), jnp.float32)} for _ in range(S)]
    stacked = stack_stage_params(stages)
    mesh = Mesh(np.asarray(jax.devices()[:S]).reshape(S), ("pipe",))
    pp = PipelineParallel(mesh, n_microbatches=args.microbatches)
    stacked = pp.shard_params(stacked)      # each stage lives on its device
    x = jnp.asarray(rs.randn(B, D).astype(np.float32))
    y = jnp.asarray(rs.randn(B, D).astype(np.float32))

    @jax.jit
    def pp_step(sp):
        loss, g = jax.value_and_grad(
            lambda sp: jnp.mean((pp.apply(stage_fn, sp, x) - y) ** 2))(sp)
        return jax.tree_util.tree_map(lambda p, gg: p - 0.05 * gg, sp, g), loss

    for i in range(args.steps):
        stacked, loss = pp_step(stacked)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"[pipeline {S} stages] step {i:3d} loss {float(loss):.5f}")

    # ---- expert parallelism: sparse MoE sharded over an expert axis ----
    init_zoo_context(mesh_shape=(args.devices // 2, 2),
                     axis_names=("data", "expert"))
    ctx = get_zoo_context()
    moe = SparseMoE(n_experts=4, hidden_dim=128, top_k=2,
                    capacity_factor=2.0, expert_axis="expert")
    params, state = moe.init(jax.random.PRNGKey(0), (B, D))
    params = jax.device_put(
        params, ExpertParallel(axis="expert").param_shardings(ctx.mesh,
                                                              params))

    @jax.jit
    def ep_step(p):
        def loss_fn(p):
            out, ns = moe.call(p, state, x)
            return jnp.mean((out - y) ** 2) + 0.01 * ns["aux_loss"]
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda pp_, gg: pp_ - 0.05 * gg,
                                      p, g), loss

    for i in range(args.steps):
        params, loss = ep_step(params)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"[moe 4 experts over 'expert' axis] step {i:3d} "
                  f"loss {float(loss):.5f}")
    print("done: pipeline + expert parallel both trained")


if __name__ == "__main__":
    main()
