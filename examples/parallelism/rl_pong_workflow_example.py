"""RL policy-gradient workflow — the Ray examples' capability, the TPU
way (reference pyzoo/zoo/examples/ray/rl_pong/rl_pong.py: parallel env
rollouts on Ray actors feeding a policy-gradient learner;
ray/parameter_server: workers pushing grads to a PS).

Design note (the designed-out story for the Ray family): the reference
scaled "arbitrary Python next to training" by shipping python closures
to Ray actors over the cluster.  On TPU the same capability — many
concurrent environment instances generating experience for one learner
— maps to ``jax.vmap`` over environment STATE (thousands of envs in one
program, no actors, no object store) and ``lax.scan`` over time.  The
parameter-server pattern collapses into data-parallel ``psum`` inside
the jitted update, which is exactly what ``init_zoo_context``'s mesh +
the estimator's SPMD step do for supervised training.

The env is a pong-like interception game: a ball falls with random
horizontal drift; the paddle moves left/stay/right; reward +1 on catch,
-1 on miss.  REINFORCE with a learned baseline trains the policy to
near-perfect interception in a few hundred updates — every rollout
step of every env runs on the accelerator.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu import init_zoo_context


def init_env(key, height=16, width=12):
    kx, kb, kv = jax.random.split(key, 3)
    return {
        "ball_x": jax.random.uniform(kx, (), minval=0.0, maxval=width - 1),
        "ball_y": jnp.zeros(()),
        "vel_x": jax.random.uniform(kv, (), minval=-1.0, maxval=1.0),
        "paddle": jax.random.uniform(kb, (), minval=0.0,
                                     maxval=width - 1),
    }


def obs(env, height=16, width=12):
    return jnp.stack([env["ball_x"] / width, env["ball_y"] / height,
                      env["vel_x"], env["paddle"] / width])


def step_env(env, action, height=16, width=12):
    """action in {0: left, 1: stay, 2: right}; returns (env, reward, done).

    The terminal reward fires exactly ONCE — on the step the ball
    CROSSES the bottom row — so the return is invariant to ``--horizon``
    (longer horizons just step a finished, frozen episode)."""
    paddle = jnp.clip(env["paddle"] + (action - 1.0), 0.0, width - 1)
    ball_x = jnp.clip(env["ball_x"] + env["vel_x"], 0.0, width - 1)
    ball_y = env["ball_y"] + 1.0
    arrived = (ball_y >= height - 1) & (env["ball_y"] < height - 1)
    done = ball_y >= height - 1
    caught = jnp.abs(ball_x - paddle) <= 1.5
    reward = jnp.where(arrived, jnp.where(caught, 1.0, -1.0), 0.0)
    return {"ball_x": ball_x, "ball_y": jnp.minimum(ball_y, height - 1.0),
            "vel_x": env["vel_x"], "paddle": paddle}, reward, done


def policy_net(params, o):
    h = jnp.tanh(o @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"], (h @ params["wv"]
                                             + params["bv"])[0]


def init_params(key, hidden=32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {"w1": jax.random.normal(k1, (4, hidden)) * 0.5,
            "b1": jnp.zeros(hidden),
            "w2": jax.random.normal(k2, (hidden, 3)) * 0.1,
            "b2": jnp.zeros(3),
            "wv": jax.random.normal(k3, (hidden, 1)) * 0.1,
            "bv": jnp.zeros(1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--envs", type=int, default=512,
                    help="concurrent environments (the Ray actor count)")
    ap.add_argument("--updates", type=int, default=150)
    ap.add_argument("--horizon", type=int, default=15)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    init_zoo_context()
    if args.horizon < 15:
        print(f"note: --horizon {args.horizon} < 15 (the drop height): "
              "episodes never terminate, every return is 0")
    tx = optax.adam(args.lr)
    params = init_params(jax.random.PRNGKey(0))
    opt_state = tx.init(params)

    def rollout_loss(params, key):
        """One full-episode rollout for EVERY env, fully on device."""
        keys = jax.random.split(key, args.envs)
        envs = jax.vmap(init_env)(keys)

        def t_step(carry, key_t):
            envs, logp_sum, value0 = carry
            o = jax.vmap(obs)(envs)
            logits, _ = jax.vmap(policy_net, in_axes=(None, 0))(params, o)
            a = jax.random.categorical(key_t, logits, axis=-1)
            lp = jax.nn.log_softmax(logits)[jnp.arange(args.envs), a]
            envs, reward, _ = jax.vmap(step_env)(envs, a.astype(jnp.float32))
            return (envs, logp_sum + lp, value0), reward

        o0 = jax.vmap(obs)(envs)
        _, v0 = jax.vmap(policy_net, in_axes=(None, 0))(params, o0)
        (envs, logp, _), rewards = jax.lax.scan(
            t_step, (envs, jnp.zeros(args.envs), v0),
            jax.random.split(key, args.horizon))
        ret = rewards.sum(0)                      # terminal +-1
        adv = ret - v0                            # learned baseline
        pg = -(jax.lax.stop_gradient(adv) * logp).mean()
        vloss = jnp.mean((ret - v0) ** 2)
        return pg + 0.5 * vloss, ret.mean()

    @jax.jit
    def update(params, opt_state, key):
        (loss, mean_ret), grads = jax.value_and_grad(
            rollout_loss, has_aux=True)(params, key)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, mean_ret

    key = jax.random.PRNGKey(1)
    t0, history = time.perf_counter(), []
    for u in range(args.updates):
        key, sub = jax.random.split(key)
        params, opt_state, mean_ret = update(params, opt_state, sub)
        if (u + 1) % 25 == 0:
            r = float(mean_ret)
            history.append(r)
            print(f"update {u + 1}: mean return {r:+.3f} "
                  f"({args.envs} envs x {args.horizon} steps/update)")
    dt = time.perf_counter() - t0
    steps = args.envs * args.horizon * args.updates
    print(f"{steps} env-steps in {dt:.1f}s = {steps / dt:,.0f} steps/s "
          "(every env step on the accelerator — no actors, no object store)")
    assert history[-1] > history[0] - 0.05, "policy failed to improve"
    print("final mean return:", round(history[-1], 3),
          "(random play is ~-0.5; perfect interception is +1.0)")


if __name__ == "__main__":
    main()
