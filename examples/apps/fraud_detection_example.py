"""Credit-card fraud detection (reference apps/fraud-detection/
fraud-detection.ipynb): heavily imbalanced tabular data -> standardize ->
stratified re-sampling of the majority class -> MLP classifier through the
NNFrames DataFrame API -> AUC / precision / recall on a held-out split.

The reference drove this through Spark ML DLClassifier + StratifiedSampler;
here the same flow runs on a pandas DataFrame through NNClassifier (no
cluster needed — the training step itself is the SPMD program).
"""

import argparse

import numpy as np
import pandas as pd

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers.core import Dense, Dropout
from analytics_zoo_tpu.nnframes import NNClassifier


def synthetic_creditcard(n=20000, d=29, fraud_rate=0.02, seed=0):
    """creditcard.csv-shaped data: PCA-ish features where fraud lives in a
    shifted low-dimensional cone + a skewed Amount column."""
    rs = np.random.RandomState(seed)
    n_fraud = max(8, int(n * fraud_rate))
    x_norm = rs.randn(n - n_fraud, d)
    shift = rs.randn(d) * 2.0
    x_fraud = 0.6 * rs.randn(n_fraud, d) + shift
    x = np.concatenate([x_norm, x_fraud]).astype(np.float32)
    amount = np.abs(rs.lognormal(3.0, 1.0, n)).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_fraud), np.ones(n_fraud)])
    df = pd.DataFrame(x, columns=[f"V{i + 1}" for i in range(d)])
    df["Amount"] = amount
    df["Class"] = y.astype(np.int32)
    return df.sample(frac=1.0, random_state=seed).reset_index(drop=True)


def stratified_resample(df, label_col="Class", majority_keep=0.1, seed=1):
    """Down-sample the majority class (the reference's StratifiedSampler
    role): fraud stays, 'normal' is thinned to rebalance the loss."""
    pos = df[df[label_col] == 1]
    neg = df[df[label_col] == 0].sample(frac=majority_keep,
                                        random_state=seed)
    return pd.concat([pos, neg]).sample(frac=1.0, random_state=seed)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=20000)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    init_zoo_context()
    df = synthetic_creditcard(args.n)
    feature_cols = [c for c in df.columns if c != "Class"]

    # standardize on TRAIN stats only, like the notebook's StandardScaler
    split = int(len(df) * 0.8)
    train_df, test_df = df.iloc[:split].copy(), df.iloc[split:].copy()
    mu, sd = train_df[feature_cols].mean(), train_df[feature_cols].std()
    train_df[feature_cols] = (train_df[feature_cols] - mu) / sd
    test_df[feature_cols] = (test_df[feature_cols] - mu) / sd
    train_df = stratified_resample(train_df)

    # VectorAssembler role: one features column of dense vectors
    for frame in (train_df, test_df):
        frame["features"] = list(
            frame[feature_cols].to_numpy(dtype=np.float32))

    model = Sequential([
        Dense(32, activation="relu", input_shape=(len(feature_cols),)),
        Dropout(0.3),
        Dense(16, activation="relu"),
        Dense(2, activation="softmax")])
    clf = (NNClassifier(model)
           .setFeaturesCol("features")
           .setLabelCol("Class")
           .setBatchSize(args.batch_size)
           .setMaxEpoch(args.epochs))
    fitted = clf.fit(train_df)

    pred = fitted.transform(test_df)
    y = test_df["Class"].to_numpy()
    p = pred["prediction"].to_numpy()
    scores = np.stack(pred["rawPrediction"].to_numpy())[:, 1]

    tp = int(((p == 1) & (y == 1)).sum())
    fp = int(((p == 1) & (y == 0)).sum())
    fn = int(((p == 0) & (y == 1)).sum())
    precision = tp / max(1, tp + fp)
    recall = tp / max(1, tp + fn)
    # AUC by rank statistic (no sklearn dependency)
    order = np.argsort(scores)
    ranks = np.empty(len(scores)); ranks[order] = np.arange(len(scores))
    n_pos, n_neg = int((y == 1).sum()), int((y == 0).sum())
    auc = ((ranks[y == 1].sum() - n_pos * (n_pos - 1) / 2)
           / max(1, n_pos * n_neg))
    print(f"test fraud cases: {n_pos}/{len(y)}")
    print(f"fraud precision {precision:.3f} recall {recall:.3f} "
          f"AUC {auc:.3f}")


if __name__ == "__main__":
    main()
