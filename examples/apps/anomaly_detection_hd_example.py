"""High-dimensional anomaly detection with an autoencoder (reference
apps/anomaly-detection-hd/anomaly-detection-hd.ipynb): ionosphere-shaped
tabular data -> min-max scale -> Dense autoencoder trained on
reconstruction -> flag the rows with the largest reconstruction error.

The reference trained a 2-layer autoencoder (compress rate 0.8, sigmoid
output, binary_crossentropy) for 2500 epochs; the flow here is identical
but sized for a CI smoke run.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers.core import Dense


def synthetic_ionosphere(n=351, d=34, outlier_rate=0.1, seed=0):
    """ionosphere.arff-shaped data: inliers on a smooth low-dim manifold,
    outliers scattered off it (labels only used for evaluation)."""
    rs = np.random.RandomState(seed)
    n_out = int(n * outlier_rate)
    basis = rs.randn(4, d)
    z = rs.randn(n - n_out, 4)
    inliers = np.tanh(z @ basis) + 0.05 * rs.randn(n - n_out, d)
    outliers = rs.uniform(-2, 2, (n_out, d))
    x = np.concatenate([inliers, outliers]).astype(np.float32)
    y = np.concatenate([np.zeros(n - n_out), np.ones(n_out)])
    perm = rs.permutation(n)
    return x[perm], y[perm].astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=351)
    ap.add_argument("--epochs", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=100)
    ap.add_argument("--compress-rate", type=float, default=0.8)
    args = ap.parse_args()

    init_zoo_context()
    x, labels = synthetic_ionosphere(args.n)
    # min-max scale to [0,1] (the notebook's MinMaxScaler + sigmoid output)
    lo, hi = x.min(axis=0), x.max(axis=0)
    x = (x - lo) / np.maximum(hi - lo, 1e-9)
    d = x.shape[1]

    inp = Input(shape=(d,))
    encoded = Dense(int(args.compress_rate * d), activation="relu")(inp)
    decoded = Dense(d, activation="sigmoid")(encoded)
    autoencoder = Model(inp, decoded)
    autoencoder.compile(optimizer="adam", loss="binary_crossentropy")
    autoencoder.fit(x, x, batch_size=args.batch_size, epochs=args.epochs,
                    verbose=False)

    recon = autoencoder.predict(x, batch_size=args.batch_size)
    err = np.mean((recon - x) ** 2, axis=1)
    k = int(labels.sum())                      # flag as many as true outliers
    flagged = np.argsort(-err)[:k]
    hits = int(labels[flagged].sum())
    print(f"outliers: {k}; flagged-by-error hits: {hits} "
          f"(precision@k {hits / max(1, k):.2f})")


if __name__ == "__main__":
    main()
