"""Object-detection notebook app — end-to-end detect-and-visualize flow
(reference apps/object-detection: load a pretrained SSD, read images,
predict, draw boxes with Visualizer, save annotated frames).

The reference downloads a pretrained SSD-MobileNet from the zoo; with no
egress this app trains a small SSD on synthetic box scenes first (or
loads ``--model`` saved by a previous run), then runs the identical
detect -> draw -> save flow on held-out images.

TPU-first notes: detection post-processing (decode + per-class NMS) is
jitted and vmapped over the batch on device; only final kept boxes come
back to host for drawing.
"""

import argparse
import os

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.objectdetection import (ObjectDetector,
                                                      save_detection_images)

SMALL_CONFIG = {
    "image_size": 64,
    "feature_sizes": (8, 4, 2, 1, 1, 1),
    "min_sizes": (6, 13, 26, 38, 51, 58),
    "max_sizes": (13, 26, 38, 51, 58, 70),
    "aspect_ratios": ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,)),
}
CLASS_NAMES = ["background", "block"]


def synthetic_scenes(n=48, size=64, seed=0):
    rs = np.random.RandomState(seed)
    imgs = rs.rand(n, size, size, 3).astype(np.float32) * 0.2
    boxes = np.zeros((n, 1, 4), np.float32)
    labels = np.ones((n, 1), np.int64)
    for i in range(n):
        w, h = rs.randint(16, 40, 2)
        x, y = rs.randint(0, size - w), rs.randint(0, size - h)
        imgs[i, y:y + h, x:x + w] = rs.rand(3) * 0.6 + 0.4
        boxes[i, 0] = (x / size, y / size, (x + w) / size, (y + h) / size)
    return imgs, boxes, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--output", default="/tmp/object_detection_app")
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--n-train", type=int, default=40)
    ap.add_argument("--n-predict", type=int, default=8)
    args = ap.parse_args()

    init_zoo_context()
    det = ObjectDetector(class_num=2, config=SMALL_CONFIG)
    det.model.compile(optimizer="adam", loss=det.loss())

    imgs, boxes, labels = synthetic_scenes(args.n_train + args.n_predict)
    tr = slice(0, args.n_train)
    det.fit_detection(imgs[tr], boxes[tr], labels[tr],
                      batch_size=8, nb_epoch=args.epochs, verbose=False)

    test = imgs[args.n_train:]
    detections = det.detect(test, score_threshold=0.25)
    paths = save_detection_images(args.output, test, detections,
                                  class_names=CLASS_NAMES)
    found = sum(len(d[0]) for d in detections)
    print(f"detected {found} boxes across {len(test)} images")
    print(f"annotated frames written to {os.path.abspath(args.output)}:")
    for p in paths[:3]:
        print(" ", p)
    # quality readout: mean IoU of the top detection vs ground truth
    from analytics_zoo_tpu.models.objectdetection import iou_matrix
    gts = boxes[args.n_train:]
    ious = []
    for (b, s, l), gt in zip(detections, gts):
        if len(b):
            ious.append(float(np.max(iou_matrix(b[:1], gt))))
    if ious:
        print("mean top-1 IoU:", round(float(np.mean(ious)), 3))


if __name__ == "__main__":
    main()
