"""3D image augmentation app — the volumetric preprocessing tour
(reference apps/image-augmentation-3d notebook: load a 3D scan, apply
crop / random crop / rotation / affine / warp transforms and inspect
the results).

The reference notebook reads a sample medical volume; this app builds a
synthetic volume with recognisable structure (an off-centre bright
ellipsoid) so every transform's effect is verifiable numerically: the
printed centroid/mass stats move exactly as the geometry says they
should.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.image3d import (AffineTransform3D, Crop3D,
                                            RandomCrop3D, Rotate3D, Warp3D)


def synthetic_volume(d=32, h=32, w=32, seed=0):
    """Noise floor + a bright ellipsoid centred at (d/3, h/3, w/2)."""
    rs = np.random.RandomState(seed)
    vol = rs.rand(d, h, w).astype(np.float32) * 0.1
    zz, yy, xx = np.mgrid[0:d, 0:h, 0:w].astype(np.float32)
    c = ((zz - d / 3) / (d / 6)) ** 2 + ((yy - h / 3) / (h / 5)) ** 2 \
        + ((xx - w / 2) / (w / 4)) ** 2
    vol[c < 1.0] = 1.0
    return vol


def centroid(vol):
    idx = np.mgrid[0:vol.shape[0], 0:vol.shape[1], 0:vol.shape[2]]
    mass = vol.sum()
    return tuple(round(float((vol * g).sum() / mass), 2) for g in idx)


class _Feat:
    def __init__(self, image):
        self.image = image


def apply(op, vol, seed=0):
    feat = _Feat(vol.copy())
    return op.apply(feat, np.random.RandomState(seed)).image


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=32)
    args = ap.parse_args()

    init_zoo_context()
    s = args.size
    vol = synthetic_volume(s, s, s)
    print(f"input volume {vol.shape}: mass={vol.sum():.0f} "
          f"centroid={centroid(vol)}")

    crop = apply(Crop3D(start=(0, 0, s // 4),
                        patch_size=(s // 2, s // 2, s // 2)), vol)
    print(f"Crop3D -> {crop.shape} centroid={centroid(crop)}")

    rnd = apply(RandomCrop3D(patch_size=(s // 2, s // 2, s // 2)), vol,
                seed=3)
    print(f"RandomCrop3D -> {rnd.shape} centroid={centroid(rnd)}")

    rot = apply(Rotate3D(yaw=np.pi / 2), vol)
    print(f"Rotate3D(yaw=90deg) -> {rot.shape} centroid={centroid(rot)}")

    # anisotropic scale about the volume centre
    mat = np.diag([1.0, 0.8, 1.25]).astype(np.float32)
    aff = apply(AffineTransform3D(mat), vol)
    print(f"AffineTransform3D(scale) -> {aff.shape} "
          f"centroid={centroid(aff)}")

    # smooth sinusoidal displacement field
    zz, yy, xx = np.mgrid[0:s, 0:s, 0:s].astype(np.float32)
    field = np.stack([2 * np.sin(2 * np.pi * yy / s),
                      np.zeros_like(yy), np.zeros_like(yy)], axis=-1)
    warp = apply(Warp3D(field), vol)
    print(f"Warp3D(sinusoidal) -> {warp.shape} centroid={centroid(warp)}")

    # chained pipeline, the notebook's closing example
    chained = apply(Rotate3D(roll=np.pi / 6),
                    apply(Crop3D(start=(2, 2, 2),
                                 patch_size=(s - 4, s - 4, s - 4)), vol))
    print(f"chained crop->rotate -> {chained.shape} "
          f"centroid={centroid(chained)}")


if __name__ == "__main__":
    main()
