"""Image similarity search (reference apps/image-similarity/
image-similarity.ipynb): a scene classifier provides SEMANTIC scores
and its penultimate layer provides VISUAL embeddings; a query image is
matched against a gallery by class probability + embedding cosine
distance, returning the top-k most similar listings.

The reference fine-tuned googlenet_places365 through NNFrames; with zero
egress the backbone here is trained in-process on generated scene
images — the search flow (classify -> embed via ``new_graph`` -> rank)
is the notebook's.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers.convolutional import Convolution2D
from analytics_zoo_tpu.nn.layers.core import Activation, Dense
from analytics_zoo_tpu.nn.layers.normalization import BatchNormalization
from analytics_zoo_tpu.nn.layers.pooling import (GlobalAveragePooling2D,
                                                 MaxPooling2D)
from analytics_zoo_tpu.nn.net import GraphNet

SIZE = 32
SCENES = ("bathroom", "bedroom", "house", "kitchen")


def paint_scene(cls: int, rs) -> np.ndarray:
    """Each scene class gets a palette + texture signature."""
    base = [(200, 210, 215), (90, 60, 120), (60, 140, 60), (40, 90, 180)]
    img = np.ones((SIZE, SIZE, 3), np.float32) * base[cls]
    img += rs.randn(SIZE, SIZE, 3) * 18
    if cls % 2 == 0:    # horizontal banding on even classes
        img[::4] *= 0.6
    else:               # vertical banding on odd
        img[:, ::4] *= 0.6
    return np.clip(img, 0, 255).astype(np.float32) / 255.0


def scene_model() -> Model:
    inp = Input(shape=(SIZE, SIZE, 3), name="image")
    x = Convolution2D(16, 3, 3, border_mode="same", bias=False,
                      name="c1")(inp)
    x = BatchNormalization(name="b1")(x)
    x = Activation("relu")(x)
    x = MaxPooling2D((2, 2))(x)
    x = Convolution2D(32, 3, 3, border_mode="same", bias=False,
                      name="c2")(x)
    x = BatchNormalization(name="b2")(x)
    x = Activation("relu")(x)
    x = GlobalAveragePooling2D(name="embedding")(x)
    x = Dense(len(SCENES), activation="softmax", name="scores")(x)
    return Model(inp, x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gallery", type=int, default=400)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--top-k", type=int, default=5)
    args = ap.parse_args()

    init_zoo_context()
    rs = np.random.RandomState(0)
    labels = rs.randint(0, len(SCENES), args.gallery)
    gallery = np.stack([paint_scene(c, rs) for c in labels])

    model = scene_model()
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(gallery, labels.astype(np.int32), batch_size=64,
              epochs=args.epochs, verbose=False)
    acc = model.evaluate(gallery, labels.astype(np.int32),
                         batch_size=64)["accuracy"]

    # semantic head + visual embedding from the SAME trained graph
    embed_net = GraphNet(model).new_graph("embedding")
    embeds = np.asarray(embed_net.predict(gallery, batch_size=64))
    embeds /= np.linalg.norm(embeds, axis=1, keepdims=True) + 1e-9

    query_cls = 1                                     # a bedroom query
    query = paint_scene(query_cls, rs)[None]
    q_scores = np.asarray(model.predict(query, batch_size=1))[0]
    q_emb = np.asarray(embed_net.predict(query, batch_size=1))[0]
    q_emb /= np.linalg.norm(q_emb) + 1e-9

    # rank: semantic class match probability x visual cosine similarity
    sim = embeds @ q_emb
    sem = np.asarray(model.predict(gallery, batch_size=64))[:, query_cls]
    top = np.argsort(-(sim * sem))[:args.top_k]
    purity = float((labels[top] == query_cls).mean())
    print(f"classifier accuracy {acc:.3f}; query class "
          f"P={q_scores[query_cls]:.2f}")
    print(f"top-{args.top_k} similar images class purity: {purity:.2f}")


if __name__ == "__main__":
    main()
