"""Dogs-vs-cats transfer learning (reference apps/dogs-vs-cats/
transfer-learning.ipynb): read labelled image files -> preprocess ->
load a pretrained backbone -> chop the classifier off (``new_graph``) ->
freeze the backbone -> train a fresh 2-class head -> validate.

The notebook loaded bigdl_inception-v1_imagenet and trained through a
Spark ML Pipeline; here the backbone is "pretrained" in-process on a
4-class proxy task (no egress for real ImageNet weights), then the
identical chop/freeze/fine-tune flow runs through NNClassifier over the
NNImageReader DataFrame.
"""

import argparse
import os
import tempfile

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.nn import Input, Model
from analytics_zoo_tpu.nn.layers.convolutional import Convolution2D
from analytics_zoo_tpu.nn.layers.core import Dense, Flatten
from analytics_zoo_tpu.nn.layers.normalization import BatchNormalization
from analytics_zoo_tpu.nn.layers.core import Activation
from analytics_zoo_tpu.nn.layers.pooling import (GlobalAveragePooling2D,
                                                 MaxPooling2D)
from analytics_zoo_tpu.nn.net import GraphNet
from analytics_zoo_tpu.nnframes import NNClassifier, NNImageReader

SIZE = 32


def _paint(kind: str, rs) -> np.ndarray:
    """Tiny synthetic 'pet photos': warm-toned circles (cats) vs
    cool-toned bars (dogs) on noisy backgrounds — color + shape cues a
    small conv net can separate."""
    import cv2

    img = (rs.rand(SIZE, SIZE, 3) * 60).astype(np.uint8)
    cx, cy = rs.randint(8, SIZE - 8, 2)
    if kind == "cat":   # warm: strong R, weak B
        color = (int(rs.randint(0, 80)), int(rs.randint(60, 140)),
                 int(rs.randint(170, 255)))          # BGR
        cv2.circle(img, (cx, cy), int(rs.randint(5, 9)), color, -1)
    else:               # cool: strong B, weak R
        color = (int(rs.randint(170, 255)), int(rs.randint(60, 140)),
                 int(rs.randint(0, 80)))
        x2, y2 = min(SIZE - 1, cx + 14), min(SIZE - 1, cy + 5)
        cv2.rectangle(img, (cx, cy), (x2, y2), color, -1)
    return img


def write_dataset(root: str, n_per_class: int, seed=0):
    import cv2

    rs = np.random.RandomState(seed)
    for kind in ("cat", "dog"):
        for i in range(n_per_class):
            cv2.imwrite(os.path.join(root, f"{kind}.{i}.jpg"),
                        _paint(kind, rs))


def backbone() -> Model:
    inp = Input(shape=(SIZE, SIZE, 3), name="image")
    x = Convolution2D(16, 3, 3, border_mode="same", bias=False,
                      name="feat1_conv")(inp)
    x = BatchNormalization(name="feat1_bn")(x)
    x = Activation("relu")(x)
    x = MaxPooling2D((2, 2))(x)
    x = Convolution2D(32, 3, 3, border_mode="same", bias=False,
                      name="feat2_conv")(x)
    x = BatchNormalization(name="feat2_bn")(x)
    x = Activation("relu")(x)
    x = GlobalAveragePooling2D(name="pool5")(x)
    x = Dense(4, activation="softmax", name="imagenet_head")(x)
    return Model(inp, x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-per-class", type=int, default=120)
    ap.add_argument("--pretrain-epochs", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    init_zoo_context()
    rs = np.random.RandomState(1)

    # -- stand-in for the downloaded pretrained model: a quick proxy task
    pre = backbone()
    pre.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    px = rs.rand(256, SIZE, SIZE, 3).astype(np.float32)
    py = rs.randint(0, 4, 256).astype(np.int32)
    pre.fit(px, py, batch_size=64, epochs=args.pretrain_epochs,
            verbose=False)

    # -- the app flow: files -> DataFrame -> chop head -> freeze -> tune
    with tempfile.TemporaryDirectory() as root:
        write_dataset(root, args.n_per_class)
        df = NNImageReader.read_images(os.path.join(root, "*.jpg"))
        df["label"] = [1.0 if "cat" in os.path.basename(p) else 0.0
                       for p in df["origin"]]
        df["features"] = [
            (img.astype(np.float32) / 255.0) for img in df["data"]]
        df = df.sample(frac=1.0, random_state=2).reset_index(drop=True)
        split = int(len(df) * 0.85)
        train_df, val_df = df.iloc[:split], df.iloc[split:]

        net = GraphNet(pre).new_graph("pool5")       # drop the 4-way head
        net.freeze(["feat1_conv", "feat1_bn"])       # keep early features
        head = Dense(2, activation="softmax", name="catdog_head")
        full = Model(net.model.inputs,
                     head(net.model.outputs[0]))
        full._frozen = net.model._frozen             # frozen set carries over
        # seed the composed model with the PRETRAINED backbone weights
        import jax

        full.set_initial_weights(jax.device_get(pre.estimator.params),
                                 jax.device_get(pre.estimator.state))
        pretrained_w = np.asarray(
            pre.estimator.params["feat1_conv"]["kernel"])

        clf = (NNClassifier(full)
               .setFeaturesCol("features")
               .setLabelCol("label")
               .setBatchSize(args.batch_size)
               .setMaxEpoch(args.epochs))
        fitted = clf.fit(train_df)
        # the frozen pretrained backbone really survived fine-tuning
        kept = np.allclose(
            np.asarray(fitted.estimator.params["feat1_conv"]["kernel"]),
            pretrained_w)
        pred = fitted.transform(val_df)
        acc = float((pred["prediction"].to_numpy()
                     == val_df["label"].to_numpy()).mean())
        print(f"frozen pretrained backbone intact: {kept}")
        print(f"transfer-learning val accuracy: {acc:.3f} "
              f"({len(val_df)} images)")


if __name__ == "__main__":
    main()
