"""IMDB-style sentiment analysis (reference apps/sentiment-analysis/
sentiment-analysis.ipynb): raw review texts -> TextSet pipeline
(tokenize -> normalize -> word2idx -> shape to fixed length) -> embedding
+ conv/LSTM classifier -> accuracy on a held-out split.

The notebook downloaded imdb.npz and built GloVe-initialised models
(build_model('cnn'|'lstm'|'gru')); with zero egress this generates
IMDB-shaped reviews from sentiment-bearing vocabularies, runs the SAME
text pipeline, and trains the same model family end to end.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.nn import Sequential
from analytics_zoo_tpu.nn.layers.core import Dense, Dropout
from analytics_zoo_tpu.nn.layers.convolutional import Convolution1D
from analytics_zoo_tpu.nn.layers.embedding import Embedding
from analytics_zoo_tpu.nn.layers.pooling import GlobalMaxPooling1D
from analytics_zoo_tpu.nn.layers.recurrent import LSTM

POS = ("great wonderful brilliant moving superb delightful perfect "
       "masterpiece charming gripping").split()
NEG = ("awful terrible boring dull predictable tedious mess lifeless "
       "clumsy forgettable").split()
FILLER = ("the movie film plot acting director scene story script camera "
          "it was and with really very just quite of a an").split()


def synthetic_imdb(n=2000, max_len=60, seed=0):
    rs = np.random.RandomState(seed)
    texts, labels = [], []
    for _ in range(n):
        y = int(rs.randint(2))
        vocab = POS if y else NEG
        words = []
        for _ in range(int(rs.randint(20, max_len))):
            words.append(vocab[rs.randint(len(vocab))]
                         if rs.rand() < 0.25
                         else FILLER[rs.randint(len(FILLER))])
        texts.append(" ".join(words))
        labels.append(y)
    return texts, np.asarray(labels, np.int32)


def build_model(kind: str, vocab_size: int, seq_len: int) -> Sequential:
    m = Sequential()
    m.add(Embedding(vocab_size, 32, input_shape=(seq_len,)))
    if kind == "cnn":
        m.add(Convolution1D(32, 5, activation="relu"))
        m.add(GlobalMaxPooling1D())
    elif kind == "lstm":
        m.add(LSTM(32))
    else:
        raise ValueError(f"unknown model kind {kind}")
    m.add(Dropout(0.2))
    m.add(Dense(2, activation="softmax"))
    return m


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=("cnn", "lstm"), default="cnn")
    ap.add_argument("--n", type=int, default=2000)
    ap.add_argument("--seq-len", type=int, default=60)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    init_zoo_context()
    texts, labels = synthetic_imdb(args.n, args.seq_len)
    ts = (TextSet.from_texts(texts, labels)
          .tokenize().normalize()
          .word2idx(max_words_num=5000)
          .shape_sequence(args.seq_len))
    x, y = ts.to_arrays()
    vocab_size = len(ts.word_index) + 2       # + pad/unk ids

    split = int(len(x) * 0.8)
    model = build_model(args.model, vocab_size, args.seq_len)
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x[:split], y[:split], batch_size=args.batch_size,
              epochs=args.epochs, verbose=False)
    res = model.evaluate(x[split:], y[split:], batch_size=args.batch_size)
    print(f"{args.model} sentiment accuracy: {res['accuracy']:.3f}")


if __name__ == "__main__":
    main()
