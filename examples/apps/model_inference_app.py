"""Model-inference services app — the multi-model serving tour
(reference apps/model-inference-examples: recommendation-inference and
text-classification-inference services built on InferenceModel, each
loading a trained artifact and answering requests).

Two services run in one process here:
1. recommendation: an NCF trained on MovieLens-shaped interactions, then
   served through ``InferenceModel`` answering top-k item requests.
2. text classification: a TextClassifier + the TextSet vocabulary, then
   served for raw-string requests (tokenize -> idx -> predict in the
   service).

TPU-first notes: both services share the chip; each model compiles one
bucketed predict program, and requests batch through it (the flink/java
services in the reference did the same through the JVM InferenceModel).
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.datasets import generate_text_classification
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.deploy import InferenceModel
from analytics_zoo_tpu.models import NeuralCF
from analytics_zoo_tpu.models.text import TextClassifier


def build_recommendation_service(n_users=200, n_items=120, epochs=3):
    rs = np.random.RandomState(0)
    zu, zi = rs.randn(n_users + 1, 6), rs.randn(n_items + 1, 6)
    u = rs.randint(1, n_users + 1, 4000).astype(np.int32)
    i = rs.randint(1, n_items + 1, 4000).astype(np.int32)
    y = ((zu[u] * zi[i]).sum(-1) > 0).astype(np.int32)
    ncf = NeuralCF(user_count=n_users, item_count=n_items, class_num=2,
                   user_embed=8, item_embed=8, hidden_layers=(16, 8),
                   mf_embed=8)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    ncf.fit([u[:, None], i[:, None]], y, batch_size=256, nb_epoch=epochs)
    import jax

    model = InferenceModel.from_keras_net(
        ncf.model, jax.device_get(ncf.estimator.params),
        jax.device_get(ncf.estimator.state), batch_buckets=(32, 256))

    def recommend(user_id: int, k: int = 5):
        items = np.arange(1, n_items + 1, dtype=np.int32)
        users = np.full_like(items, user_id)
        scores = np.asarray(model.predict(
            [users[:, None], items[:, None]]))[:, 1]
        top = np.argsort(-scores)[:k]
        return [(int(items[j]), round(float(scores[j]), 3)) for j in top]

    return recommend


def build_text_service(epochs=4, seq_len=32):
    texts, labels = generate_text_classification(n_classes=3, per_class=80)
    ts = (TextSet.from_texts(texts, labels).tokenize().normalize()
          .word2idx(max_words_num=4000).shape_sequence(seq_len))
    x, y = ts.to_arrays()
    clf = TextClassifier(class_num=3, token_length=16,
                         sequence_length=seq_len, encoder="cnn",
                         encoder_output_dim=32, max_words_num=4000)
    clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(x, y.astype(np.int32), batch_size=64, nb_epoch=epochs)
    import jax

    model = InferenceModel.from_keras_net(
        clf.model, jax.device_get(clf.estimator.params),
        jax.device_get(clf.estimator.state), batch_buckets=(8, 64))
    word_index = ts.word_index

    def classify(raw_texts):
        feats = (TextSet.from_texts(list(raw_texts)).tokenize().normalize()
                 .word2idx(existing_map=word_index)
                 .shape_sequence(seq_len))
        xs, _ = feats.to_arrays()
        probs = np.asarray(model.predict([xs]))
        return probs.argmax(-1).tolist(), probs.max(-1).round(3).tolist()

    return classify, texts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    init_zoo_context()
    print("== recommendation-inference service ==")
    recommend = build_recommendation_service(epochs=args.epochs)
    for user in (7, 42, 99):
        print(f"  top-5 for user {user}: {recommend(user)}")

    print("== text-classification-inference service ==")
    classify, corpus = build_text_service(epochs=args.epochs + 1)
    sample = corpus[:4]
    classes, confidence = classify(sample)
    for t, c, p in zip(sample, classes, confidence):
        print(f"  [{c} @{p}] {t[:48]}...")


if __name__ == "__main__":
    main()
