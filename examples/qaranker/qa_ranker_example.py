"""QA ranking with KNRM — the qaranker example
(reference pyzoo/zoo/examples/qaranker/qa_ranker.py: question/answer
corpora -> tokenize/word2idx/shape -> relation pairs -> KNRM trained
with pairwise rank hinge -> NDCG/MAP validation).

The reference reads the WikiQA corpus from disk; by default this script
generates a WikiQA-shaped corpus (questions with one relevant and
several irrelevant answers sharing topical vocabulary) since the
container has no egress.  Pass ``--data`` with question_corpus.csv /
answer_corpus.csv / relation_train.csv / relation_valid.csv to run the
reference's exact flow on real files.

TPU-first notes: pairwise training feeds (positive, negative) rows
interleaved so ``rank_hinge`` couples them inside one jitted program;
ranking-time scoring batches every (q, a) candidate pair in one
device dispatch.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.models.text import KNRM, Ranker


def synth_wikiqa(n_questions=60, answers_per_q=5, vocab=800, seed=0):
    """WikiQA-shaped relations: each question has 1 relevant answer that
    shares its topic tokens and ``answers_per_q - 1`` distractors."""
    rs = np.random.RandomState(seed)
    q_texts, a_texts, relations = [], [], []
    for q in range(n_questions):
        topic = rs.randint(0, vocab // 10)
        q_words = [f"t{topic}w{rs.randint(8)}" for _ in range(6)]
        q_texts.append(" ".join(["what", "is"] + q_words))
        for a in range(answers_per_q):
            aid = q * answers_per_q + a
            if a == 0:                      # relevant: shares topic words
                words = [f"t{topic}w{rs.randint(8)}" for _ in range(12)]
            else:
                other = rs.randint(0, vocab // 10)
                words = [f"t{other}w{rs.randint(8)}" for _ in range(12)]
            a_texts.append(" ".join(words))
            relations.append((q, aid, 1 if a == 0 else 0))
    return q_texts, a_texts, relations


def to_pairs(relations, qx, ax, rs):
    """Interleave (positive, negative) rows per question — the pairwise
    layout rank_hinge consumes (reference TextSet.from_relation_pairs)."""
    by_q = {}
    for q, a, l in relations:
        by_q.setdefault(q, ([], []))[0 if l else 1].append(a)
    q1, a1, q2, a2 = [], [], [], []
    for q, (pos, neg) in by_q.items():
        if not neg:         # all candidates relevant: nothing to rank
            continue
        for p in pos:
            n = neg[rs.randint(len(neg))]
            q1.append(qx[q]); a1.append(ax[p])
            q2.append(qx[q]); a2.append(ax[n])
    if not q1:
        raise ValueError("no (relevant, irrelevant) pairs in relations — "
                         "pairwise ranking needs at least one negative "
                         "per question")
    qs = np.stack([v for pair in zip(q1, q2) for v in pair])
    ans = np.stack([v for pair in zip(a1, a2) for v in pair])
    y = np.tile([1.0, 0.0], len(q1)).astype(np.float32)
    return qs, ans, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="WikiQA-format dir")
    ap.add_argument("--question-length", type=int, default=10)
    ap.add_argument("--answer-length", type=int, default=40)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    init_zoo_context()
    rs = np.random.RandomState(1)
    if args.data:
        import pandas as pd
        qdf = pd.read_csv(f"{args.data}/question_corpus.csv")
        adf = pd.read_csv(f"{args.data}/answer_corpus.csv")
        rel = pd.read_csv(f"{args.data}/relation_train.csv")
        q_texts, a_texts = list(qdf["text"]), list(adf["text"])
        relations = list(zip(rel["id1"], rel["id2"], rel["label"]))
    else:
        q_texts, a_texts, relations = synth_wikiqa()

    q_set = (TextSet.from_texts(q_texts).tokenize().normalize()
             .word2idx(min_freq=1).shape_sequence(args.question_length))
    a_set = (TextSet.from_texts(a_texts).tokenize().normalize()
             .word2idx(min_freq=1, existing_map=q_set.word_index)
             .shape_sequence(args.answer_length))
    qx, _ = q_set.to_arrays()
    ax, _ = a_set.to_arrays()
    vocab = max(len(q_set.word_index), len(a_set.word_index)) + 2

    knrm = KNRM(text1_length=args.question_length,
                text2_length=args.answer_length,
                max_words_num=vocab, embed_size=32,
                target_mode="ranking")
    knrm.compile(optimizer="adam", loss="rank_hinge")
    tq, ta, ty = to_pairs(relations, qx, ax, rs)
    knrm.fit([tq, ta], ty, batch_size=args.batch_size,
             nb_epoch=args.epochs)

    # rank every candidate list and score with the reference's metrics
    qids = np.asarray([r[0] for r in relations])
    labels = np.asarray([r[2] for r in relations], np.float32)
    all_q = np.stack([qx[r[0]] for r in relations])
    all_a = np.stack([ax[r[1]] for r in relations])
    scores = np.asarray(knrm.predict([all_q, all_a],
                                     batch_size=256)).reshape(-1)
    print("ndcg@3:", round(Ranker.evaluate_ndcg(qids, labels, scores, 3), 4))
    print("ndcg@5:", round(Ranker.evaluate_ndcg(qids, labels, scores, 5), 4))
    print("map:", round(Ranker.evaluate_map(qids, labels, scores), 4))


if __name__ == "__main__":
    main()
