"""Custom loss via the autograd DSL — the autograd example
(reference pyzoo/zoo/examples/autograd/customloss.py: define
mean_absolute_error from autograd primitives, compile a Dense model
with it, recover y = 2x1 + 2x2 + 0.4).

TPU-first note: a custom loss here is ANY jax-traceable callable
``loss(y_true, y_pred) -> scalar`` — it compiles into the same fused
SPMD train step as the built-ins (the reference lowered the autograd
graph to BigDL ops; XLA does that job now).  The autograd module's
primitives (`autograd.abs/mean/square/...`) compose for parity with
reference loss definitions.
"""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.nn.layers.core import Dense
from analytics_zoo_tpu.nn.topology import Sequential
from analytics_zoo_tpu.train.optimizers import SGD


def mean_absolute_error(y_true, y_pred):
    """The reference example's loss, written over jax arrays."""
    import jax.numpy as jnp

    return jnp.mean(jnp.abs(y_true - y_pred))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--epochs", type=int, default=60)
    args = ap.parse_args()

    init_zoo_context()
    rs = np.random.RandomState(0)
    x = rs.uniform(0, 1, (args.n, 2)).astype(np.float32)
    y = ((2 * x).sum(1) + 0.4).reshape(args.n, 1).astype(np.float32)

    model = Sequential()
    model.add(Dense(1, input_shape=(2,)))
    model.compile(optimizer=SGD(lr=1e-1), loss=mean_absolute_error)
    model.fit(x, y, batch_size=32, nb_epoch=args.epochs, verbose=False)

    import jax

    params = jax.device_get(model.estimator.params)
    (w, b) = next((p["kernel"], p["bias"]) for p in params.values()
                  if "kernel" in p)
    print("learned weights:", np.asarray(w).ravel().round(3),
          "bias:", np.asarray(b).round(3), "(target: [2, 2], 0.4)")
    pred = np.asarray(model.predict(x[:4], batch_size=4)).ravel()
    print("pred vs true:", list(zip(pred.round(3), y[:4].ravel())))
    assert np.abs(np.asarray(w).ravel() - 2.0).max() < 0.3
    print("custom-loss regression recovered the generator")


if __name__ == "__main__":
    main()
