"""LSTM anomaly detection over a univariate series
(reference examples/anomalydetection/AnomalyDetection.scala + the
NYC-taxi notebook flow: unroll -> train forecaster -> flag the largest
forecast errors as anomalies)."""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.models.anomalydetection import (AnomalyDetector,
                                                       unroll)


def synthetic_series(n=2000, seed=0):
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    base = np.sin(2 * np.pi * t / 48) + 0.05 * rs.randn(n)
    spikes = rs.choice(n, 8, replace=False)
    base[spikes] += rs.choice([-3.0, 3.0], 8)     # injected anomalies
    return base.astype(np.float32)[:, None], spikes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--unroll", type=int, default=24)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--n", type=int, default=2000)
    args = ap.parse_args()

    init_zoo_context()
    series, injected = synthetic_series(args.n)
    x, y = unroll(series, args.unroll)
    split = int(len(x) * 0.8)

    det = AnomalyDetector(feature_shape=(args.unroll, 1))
    det.compile(optimizer="adam", loss="mse")
    det.fit(x[:split], y[:split], batch_size=args.batch_size,
            nb_epoch=args.epochs)

    pred = det.predict(x, batch_size=args.batch_size).reshape(-1)
    anomalies = det.detect_anomalies(y, pred, anomaly_size=10)
    print(f"flagged {int(np.sum(anomalies))} anomalies "
          f"({len(injected)} injected)")


if __name__ == "__main__":
    main()
