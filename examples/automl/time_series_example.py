"""AutoML time-series forecasting (reference automl notebook flow:
TimeSequencePredictor.fit -> pipeline.predict/evaluate/save)."""

import argparse
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.automl import (SmokeRecipe, TimeSequencePredictor,
                                      load_ts_pipeline)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=300)
    args = ap.parse_args()

    init_zoo_context()
    dt = pd.date_range("2019-01-01", periods=args.n, freq="h")
    value = (np.sin(2 * np.pi * np.arange(args.n) / 24) + 2).astype(
        np.float32)
    df = pd.DataFrame({"datetime": dt, "value": value})
    train, test = df.iloc[:int(args.n * 0.8)], df.iloc[int(args.n * 0.8):]

    class Recipe(SmokeRecipe):
        def search_space(self, feats):
            s = super().search_space(feats)
            s.update(past_seq_len=12, epochs=8)
            return s

    tsp = TimeSequencePredictor(future_seq_len=1)
    pipeline = tsp.fit(train, metric="mse", recipe=Recipe())
    print("test rmse:", pipeline.evaluate(test, metric="rmse"))
    pred = pipeline.predict(test)
    print(pred.tail(3))

    d = tempfile.mkdtemp()
    pipeline.save(d)
    print("reloaded rmse:", load_ts_pipeline(d).evaluate(test, "rmse"))


if __name__ == "__main__":
    main()
