"""Text classification through the TextSet pipeline
(reference examples/textclassification/TextClassification.scala:
tokenize -> word2idx -> shape -> CNN classifier)."""

import argparse

import numpy as np

from analytics_zoo_tpu import init_zoo_context
from analytics_zoo_tpu.data.datasets import (generate_text_classification,
                                             read_text_folder)
from analytics_zoo_tpu.data.text import TextSet
from analytics_zoo_tpu.models.text import TextClassifier


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="folder-per-class corpus (default: synthetic)")
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--encoder", default="cnn",
                    choices=["cnn", "lstm", "gru"])
    args = ap.parse_args()

    init_zoo_context()
    if args.data:
        texts, labels, class_map = read_text_folder(args.data)
        n_classes = len(class_map)
    else:
        texts, labels = generate_text_classification(args.classes)
        n_classes = args.classes

    ts = (TextSet.from_texts(texts, labels)
          .tokenize().normalize().word2idx(max_words_num=5000)
          .shape_sequence(args.seq_len))
    x, y = ts.to_arrays()

    clf = TextClassifier(class_num=n_classes, token_length=32,
                         sequence_length=args.seq_len,
                         encoder=args.encoder, encoder_output_dim=64,
                         max_words_num=5000)
    clf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    clf.fit(x, y.astype(np.int32), batch_size=32, nb_epoch=args.epochs)
    print("eval:", clf.evaluate(x, y.astype(np.int32), batch_size=32))


if __name__ == "__main__":
    main()
